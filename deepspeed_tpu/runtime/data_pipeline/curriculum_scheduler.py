"""Curriculum-learning difficulty scheduler.

Capability parity with reference
``deepspeed/runtime/data_pipeline/curriculum_scheduler.py:11``
(``CurriculumScheduler``): maps global step → difficulty (typically sequence
length) under ``fixed_linear`` / ``fixed_root`` / ``fixed_discrete`` /
``custom`` schedules.  Pure Python host-side logic — difficulty feeds the
engine's per-step seqlen slicing, which stays jit-friendly because each
distinct seqlen is its own compiled program (XLA caches per shape; the
schedule quantises via ``difficulty_step`` exactly so the number of distinct
shapes stays small, same motivation as the reference's Tensor-Core-alignment
note).
"""

import math

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"

MIN_DIFFICULTY = "min_difficulty"
MAX_DIFFICULTY = "max_difficulty"
SCHEDULE_TYPE = "schedule_type"
SCHEDULE_CONFIG = "schedule_config"
TOTAL_STEP = "total_curriculum_step"
DIFFICULTY_STEP = "difficulty_step"
ROOT_DEGREE = "root_degree"
DIFFICULTY = "difficulty"
MAX_STEP = "max_step"


class CurriculumScheduler:

    def __init__(self, config):
        self.state = {}
        for key in (MIN_DIFFICULTY, MAX_DIFFICULTY, SCHEDULE_TYPE):
            assert key in config, \
                f"Curriculum learning requires the config '{key}'"
        self.state[MIN_DIFFICULTY] = config[MIN_DIFFICULTY]
        self.state[MAX_DIFFICULTY] = config[MAX_DIFFICULTY]
        self.state["current_difficulty"] = config[MIN_DIFFICULTY]
        self.state[SCHEDULE_TYPE] = config[SCHEDULE_TYPE]
        self.first_step = True
        self.custom_get_difficulty = None

        stype = config[SCHEDULE_TYPE]
        sconf = config.get(SCHEDULE_CONFIG, {})
        if stype == FIXED_DISCRETE:
            assert DIFFICULTY in sconf and MAX_STEP in sconf, \
                f"fixed_discrete requires '{DIFFICULTY}' and '{MAX_STEP}'"
            assert len(sconf[MAX_STEP]) > 0
            assert len(sconf[DIFFICULTY]) > 0
            assert len(sconf[DIFFICULTY]) == len(sconf[MAX_STEP]) + 1
        elif stype == FIXED_ROOT:
            assert TOTAL_STEP in sconf and DIFFICULTY_STEP in sconf \
                and ROOT_DEGREE in sconf, \
                f"fixed_root requires '{TOTAL_STEP}', '{DIFFICULTY_STEP}', '{ROOT_DEGREE}'"
        elif stype == FIXED_LINEAR:
            assert TOTAL_STEP in sconf and DIFFICULTY_STEP in sconf, \
                f"fixed_linear requires '{TOTAL_STEP}', '{DIFFICULTY_STEP}'"
        elif stype == CUSTOM:
            pass
        else:
            raise RuntimeError(f"unsupported schedule type {stype}")
        self.state[SCHEDULE_CONFIG] = sconf

    def get_current_difficulty(self):
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty):
        self.state["current_difficulty"] = difficulty

    def set_custom_get_difficulty(self, schedule_function):
        self.custom_get_difficulty = schedule_function

    def get_state(self):
        return self.state

    def set_state(self, state):
        self.state = state

    def _fixed_discrete(self, global_steps):
        sconf = self.state[SCHEDULE_CONFIG]
        for i, max_step in enumerate(sconf[MAX_STEP]):
            if global_steps <= max_step:
                return sconf[DIFFICULTY][i]
        return sconf[DIFFICULTY][-1]

    def _fixed_root(self, global_steps, root_degree=None):
        sconf = self.state[SCHEDULE_CONFIG]
        if root_degree is None:
            root_degree = sconf[ROOT_DEGREE]
        next_difficulty = (min(1.0, global_steps / sconf[TOTAL_STEP])
                           ** (1.0 / root_degree))
        next_difficulty = int(next_difficulty *
                              (self.state[MAX_DIFFICULTY] -
                               self.state[MIN_DIFFICULTY]) +
                              self.state[MIN_DIFFICULTY])
        # quantise so the set of distinct difficulties (= compiled shapes on
        # TPU) stays small
        next_difficulty -= next_difficulty % sconf[DIFFICULTY_STEP]
        return min(next_difficulty, self.state[MAX_DIFFICULTY])

    def get_difficulty(self, global_steps):
        stype = self.state[SCHEDULE_TYPE]
        if stype == FIXED_DISCRETE:
            return self._fixed_discrete(global_steps)
        if stype == FIXED_ROOT:
            return self._fixed_root(global_steps)
        if stype == FIXED_LINEAR:
            return self._fixed_root(global_steps, root_degree=1)
        if stype == CUSTOM:
            assert self.custom_get_difficulty is not None, \
                "custom schedule requires set_custom_get_difficulty()"
            return self.custom_get_difficulty(global_steps)
        raise RuntimeError(f"unsupported schedule type {stype}")

    def update_difficulty(self, global_steps):
        if self.state["current_difficulty"] < self.state[MAX_DIFFICULTY]:
            self.state["current_difficulty"] = self.get_difficulty(global_steps)
        return self.state["current_difficulty"]
