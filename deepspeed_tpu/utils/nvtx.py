"""Profiler range annotation — reference ``deepspeed/utils/nvtx.py``
(``instrument_w_nvtx`` wrapping hot functions in NVTX ranges).

TPU analog: ``jax.profiler.TraceAnnotation`` ranges show up in the XLA/xprof
trace exactly where NVTX ranges show up in nsys."""

import functools

import jax


def instrument_w_nvtx(func):
    """Decorator: record ``func``'s span in profiler traces."""

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(func.__qualname__):
            return func(*args, **kwargs)

    return wrapped


def range_push(name):
    """Imperative range open (reference ``accelerator.range_push``)."""
    ann = jax.profiler.TraceAnnotation(name)
    ann.__enter__()
    _stack.append(ann)


def range_pop():
    if _stack:
        _stack.pop().__exit__(None, None, None)


_stack = []
