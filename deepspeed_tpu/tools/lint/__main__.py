"""CLI: ``python -m deepspeed_tpu.tools.lint [paths] [options]``."""

import argparse
import json
import os
import sys

from deepspeed_tpu.tools.lint.core import RULES, run_lint


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="tpu-lint",
        description="Framework-aware static analysis for host-transfer, "
                    "donation, and recompilation hazards.")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (default: the "
                             "installed deepspeed_tpu package)")
    parser.add_argument("--rules", help="comma-separated rule ids to run "
                                        "(default: all)")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as JSON")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--jaxpr", action="store_true",
                        help="run the jaxpr-level entry-point harness "
                             "(traces the registered hot paths on CPU; "
                             "no host callbacks, donations alias)")
    parser.add_argument("--contracts", action="store_true",
                        help="regenerate every program contract and diff "
                             "against PROGRAMS.lock (exit 1 on a break)")
    parser.add_argument("--concurrency", action="store_true",
                        help="run the concurrency-contract gate: the "
                             "TL008/TL009 lock-discipline sweep over the "
                             "given paths (default: the installed "
                             "package), then — when the sweep is clean — "
                             "the interleaving stress harness under "
                             "DSTPU_CONCURRENCY_CHECKS=1")
    parser.add_argument("--comm", action="store_true",
                        help="run the comm-contract gate: the TL010/TL011 "
                             "sharding-lint sweep over the given paths "
                             "(default: the installed package), then — "
                             "when the sweep is clean — the mesh-scaling "
                             "prover (compile every sharding plan at mesh "
                             "sizes 1/2/4/8, diff bytes-per-chip against "
                             "PROGRAMS.lock, fail on undeclared per-chip "
                             "growth)")
    parser.add_argument("--mem", action="store_true",
                        help="run the memory-contract gate: recompile "
                             "the hot-path programs (positional args "
                             "limit to those program names) and the "
                             "sharding plans in the forced tier-1 env, "
                             "extract compiled.memory_analysis() + "
                             "cost_analysis() budgets, and diff them "
                             "against PROGRAMS.lock format 3 — exit 1 "
                             "on any beyond-tolerance byte drift or "
                             "undeclared memory growth")
    parser.add_argument("--update", action="store_true",
                        help="with --contracts: rewrite PROGRAMS.lock "
                             "from the freshly extracted contracts")
    parser.add_argument("--stats-docs", action="store_true",
                        help="assert every serving stats key and "
                             "/metrics series is documented in "
                             "docs/observability.md (exit 1 on drift)")
    args = parser.parse_args(argv)

    if args.stats_docs:
        from deepspeed_tpu.tools.lint import stats_docs
        return stats_docs.main()

    if args.update and not args.contracts:
        print("tpu-lint: error: --update only applies to --contracts",
              file=sys.stderr)
        return 2
    if args.contracts:
        from deepspeed_tpu.tools.lint import contract
        contract.ensure_harness_env()
        return contract.main(update=args.update)
    if args.jaxpr:
        from deepspeed_tpu.tools.lint import contract, jaxpr_check
        contract.ensure_harness_env()
        return jaxpr_check.main()
    if args.comm:
        # tier-1 env forced like --contracts so the CLI and CI agree on
        # the mesh the plans compile against
        from deepspeed_tpu.tools.lint import comm_contract, contract
        contract.ensure_harness_env()
        return comm_contract.main(args.paths or None)
    if args.mem:
        # tier-1 env forced: memory budgets are locked under the same
        # backend the CLI must re-extract them on
        from deepspeed_tpu.tools.lint import contract, mem_contract
        contract.ensure_harness_env()
        return mem_contract.main(args.paths or None)
    if args.concurrency:
        # the tier-1 env is forced like --contracts/--jaxpr so the CLI
        # and the CI gate agree on what they check
        from deepspeed_tpu.tools.lint import contract, interleave_check
        contract.ensure_harness_env()
        paths = args.paths
        if not paths:
            import deepspeed_tpu
            paths = [os.path.dirname(
                os.path.abspath(deepspeed_tpu.__file__))]
        findings, stats = run_lint(paths, rules={"TL008", "TL009"})
        for f in findings:
            print(f)
        suppressed = sum(stats["suppressed"].values())
        print(f"tpu-lint[concurrency]: {len(findings)} finding(s), "
              f"{suppressed} suppressed, {stats['files']} file(s) "
              f"checked")
        if findings:
            return 1                 # static break: skip the slow prover
        return interleave_check.main()

    if args.list_rules:
        from deepspeed_tpu.tools.lint import rules as _r  # noqa: F401
        for rid, check in sorted(RULES.items()):
            print(f"{rid}  {check.title}")
        return 0

    paths = args.paths
    if not paths:
        # resolve the default against the installed package, not the cwd —
        # `ds_lint` from anywhere must not silently check zero files
        import deepspeed_tpu
        paths = [os.path.dirname(os.path.abspath(deepspeed_tpu.__file__))]
    rules = None
    if args.rules:
        from deepspeed_tpu.tools.lint import rules as _r  # noqa: F401
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES)
        if unknown:
            print(f"tpu-lint: error: unknown rule id(s) "
                  f"{sorted(unknown)}; known: {sorted(RULES)}",
                  file=sys.stderr)
            return 2
    findings, stats = run_lint(paths, rules=rules)
    if stats["files"] == 0:
        print(f"tpu-lint: error: no Python files found under {paths}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        suppressed = sum(stats["suppressed"].values())
        print(f"tpu-lint: {len(findings)} finding(s), {suppressed} "
              f"suppressed, {stats['files']} file(s) checked")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
