"""Contiguous memory allocator — reference
``runtime/zero/contiguous_memory_allocator.py`` (287 LoC): a fixed flat
buffer carved into tensor assignments, with release + defragmentation, used
by ZeRO-3's partial-parameter machinery to avoid allocator churn.

On TPU, XLA owns device memory inside a program, but the *host-side staging
tier* (offload buffers, swap staging) has exactly the reference's problem:
repeated alloc/free of pinned host arenas fragments and stalls.  This is the
same allocator over one preallocated numpy arena; tensors are views, and
``defragment()`` compacts live assignments to the front (the reference's
tensor-move callback maps to view re-binding)."""

import numpy as np

from deepspeed_tpu.utils.logging import logger


class ContiguousMemoryAllocator:

    def __init__(self, size, dtype=np.float32, device="cpu"):
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        self.buffer = np.zeros(self.size, self.dtype)
        self.device = device
        # offset -> length of free blocks
        self.contiguous_sizes = {0: self.size}
        # tensor_id -> (offset, numel)
        self.tensor_addresses = {}
        self.tensor_map = {}
        self.total_free = self.size
        self.largest_contiguous = self.size
        self.max_allocated = 0
        self.count = 0

    # ---------------------------------------------------------------- #
    def allocate_tensor(self, numel):
        """Reference ``allocate_tensor``: returns a view of ``numel``
        elements, defragmenting first when only fragmented space remains."""
        numel = int(numel)
        assert numel <= self.total_free, \
            f"allocate {numel} > free {self.total_free}"
        if self.largest_contiguous < numel:
            logger.info("ContiguousMemoryAllocator: defragmenting to satisfy "
                        f"a {numel}-element request")
            self.defragment()
        offset = self._find_block(numel)
        assert offset is not None
        self._carve(offset, numel)
        self.count += 1
        tid = self.count
        self.tensor_addresses[tid] = (offset, numel)
        view = self.buffer[offset:offset + numel]
        self.tensor_map[tid] = view
        self.max_allocated = max(self.max_allocated,
                                 self.size - self.total_free)
        return tid, view

    def release_tensor(self, tid):
        offset, numel = self.tensor_addresses.pop(tid)
        self.tensor_map.pop(tid)
        self._free(offset, numel)

    def release_tensor_with_id(self, tid):
        self.release_tensor(tid)

    def get_tensor(self, tid):
        return self.tensor_map[tid]

    # ---------------------------------------------------------------- #
    def defragment(self):
        """Compact live tensors to the front (reference ``defragment`` moves
        tensors and fires an address-update callback; views re-bind here)."""
        new_offset = 0
        for tid in sorted(self.tensor_addresses,
                          key=lambda t: self.tensor_addresses[t][0]):
            offset, numel = self.tensor_addresses[tid]
            if offset != new_offset:
                self.buffer[new_offset:new_offset + numel] = \
                    self.buffer[offset:offset + numel]
                self.tensor_addresses[tid] = (new_offset, numel)
                self.tensor_map[tid] = self.buffer[new_offset:new_offset + numel]
            new_offset += numel
        self.contiguous_sizes = {new_offset: self.size - new_offset} \
            if new_offset < self.size else {}
        self._recompute()

    # ---------------------------------------------------------------- #
    def _find_block(self, numel):
        best = None
        for off, length in sorted(self.contiguous_sizes.items()):
            if length >= numel and (best is None or
                                    length < self.contiguous_sizes[best]):
                best = off
        return best

    def _carve(self, offset, numel):
        length = self.contiguous_sizes.pop(offset)
        if length > numel:
            self.contiguous_sizes[offset + numel] = length - numel
        self._recompute()

    def _free(self, offset, numel):
        self.contiguous_sizes[offset] = numel
        # merge adjacent free blocks
        merged = {}
        for off in sorted(self.contiguous_sizes):
            length = self.contiguous_sizes[off]
            if merged:
                last = max(merged)
                if last + merged[last] == off:
                    merged[last] += length
                    continue
            merged[off] = length
        self.contiguous_sizes = merged
        self._recompute()

    def _recompute(self):
        self.total_free = sum(self.contiguous_sizes.values())
        self.largest_contiguous = max(self.contiguous_sizes.values()) \
            if self.contiguous_sizes else 0

    def print_allocation(self, resolution=200):
        occupied = self.size - self.total_free
        logger.info(
            f"ContiguousMemoryAllocator: {occupied}/{self.size} used, "
            f"{len(self.tensor_addresses)} tensors, largest free block "
            f"{self.largest_contiguous}")
