from deepspeed_tpu.model_implementations.diffusers.vae import DSVAE  # noqa: F401
from deepspeed_tpu.model_implementations.diffusers.unet import DSUNet  # noqa: F401
