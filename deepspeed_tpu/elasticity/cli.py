"""ds_elastic CLI (reference bin/ds_elastic): inspect elastic configs."""

import argparse
import json

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config


def main():
    parser = argparse.ArgumentParser(description="DeepSpeed elasticity config inspector")
    parser.add_argument("-c", "--config", required=True, help="DeepSpeed config json")
    parser.add_argument("-w", "--world-size", type=int, default=0)
    args = parser.parse_args()
    with open(args.config) as f:
        ds_config = json.load(f)
    if args.world_size:
        batch, valid, mb = compute_elastic_config(ds_config, world_size=args.world_size,
                                                  return_microbatch=True)
        print(f"world size: {args.world_size} -> global batch: {batch}, micro batch: {mb}")
    else:
        batch, valid = compute_elastic_config(ds_config)
        print(f"global batch: {batch}")
        print(f"valid world sizes: {valid}")


if __name__ == "__main__":
    main()
