"""Pallas decode attention — the KV-cache generation kernel.

TPU-native equivalent of the reference's ``softmax_context`` inference op
(``csrc/transformer/inference/csrc/pt_binding.cpp:1934-``; the attention
half of its decode pipeline).  Single-token decode: one query row per
(batch, head) attends over the cache.

Kernel layout (v4, bandwidth-first): decode attention moves ~1.6 GB of KV
cache per token-step at OPT-1.3B/bs16 and does almost no math, so everything
is shaped for DMA efficiency, not MXU occupancy:

* The cache is **S-major with flattened heads** — ``[B, S_max, KVH*D]``
  (optionally layer-stacked ``[L, ...]``).  A KV block is then a fully
  contiguous ``[block_k, KVH*D]`` slab whose minor dim (e.g. 2048) is a
  whole number of 128-lane tiles, so the HBM→VMEM DMA streams at full
  width.  The previous head-major ``[B, KVH, S, D]`` layout produced
  D(=64)-lane-minor blocks that pad to 128 lanes in VMEM — half the
  effective bandwidth — and its per-(batch, head) grid added ~0.6 µs of
  overhead per 64 KB sliver.  Bonus: the decode-step cache write is the raw
  projection output (no per-token transpose at all).
* One grid cell covers ALL kv heads of one (batch row, kv block).  Per-head
  score matmuls are fused into ONE MXU matmul via a **block-diagonal Q**:
  rows = query heads, row h*G+g carries q[h,g] in columns h*D:(h+1)*D and
  zeros elsewhere, so ``Q_bd @ K_slab^T`` lands exactly the per-head scores
  [H, block_k] (the MXU multiplies zeros for free — it is idle here anyway).
  ``P @ V_slab`` similarly yields [H, KVH*D] from which each head's D-column
  diagonal block is accumulated.
* Online softmax runs once per cell over the whole [H, block_k] score tile
  in fp32 scratch, so the cache never materializes an S_max-wide
  probability row in fp32 HBM.

The KV length mask (cache tail + causality for a single new token collapse
to ``pos < length``) is applied per block, and blocks entirely past the
live cache region are skipped: their block index is pinned to the last live
block (Mosaic elides the repeated DMA) and their compute is pl.when-gated.
"""

import functools
import os as _os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.flash_attention import (LSE_LANES, NEG_INF,
                                                           _interpret)
from deepspeed_tpu.utils.jax_compat import CompilerParams as _CompilerParams

DEFAULT_BLOCK_K_DECODE = int(_os.environ.get("DSTPU_DECODE_BLOCK_K", "512"))


def _decode_kernel(len_ref, layer_ref, q_ref, k_ref, v_ref, *rest,
                   scale, block_k, nk, kvh, g, d, stacked, quant, window,
                   mxu_int8, fused_write=False):
    if fused_write:
        # in-kernel cache write (see decode_attention new_k/new_v): the
        # new token's raw K/V rows ride extra inputs and the caches come
        # BACK as aliased outputs pinned at each row's write block
        if quant:
            (ks_ref, vs_ref, kn_ref, vn_ref, o_ref, ko_ref, vo_ref,
             kso_ref, vso_ref, m_scr, l_scr, acc_scr, qbd_scr) = rest
            qs_scr = None
        else:
            ks_ref = vs_ref = kso_ref = vso_ref = qs_scr = None
            (kn_ref, vn_ref, o_ref, ko_ref, vo_ref,
             m_scr, l_scr, acc_scr, qbd_scr) = rest
    elif quant and mxu_int8:
        (ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr, qbd_scr,
         qs_scr) = rest
    elif quant:
        (ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr, qbd_scr) = rest
        qs_scr = None
    else:
        ks_ref = vs_ref = qs_scr = None
        (o_ref, m_scr, l_scr, acc_scr, qbd_scr) = rest

    def _new_rows():
        """This step's K/V rows, quantized the same way the cache stores
        them (payload+scale when ``quant``), plus the DEQUANTIZED values
        this step's attention must see — write-then-read parity with the
        unfused path."""
        kn = kn_ref[0].astype(jnp.float32)               # [KVH, D]
        vn = vn_ref[0].astype(jnp.float32)
        if not quant:
            return kn, vn, kn, vn, None, None
        ks_n = jnp.max(jnp.abs(kn), axis=1, keepdims=True) / 127.0
        vs_n = jnp.max(jnp.abs(vn), axis=1, keepdims=True) / 127.0
        ks_safe = jnp.where(ks_n == 0.0, 1.0, ks_n)
        vs_safe = jnp.where(vs_n == 0.0, 1.0, vs_n)
        kq = jnp.clip(jnp.round(kn / ks_safe), -127, 127)
        vq = jnp.clip(jnp.round(vn / vs_safe), -127, 127)
        return kq, vq, kq * ks_safe, vq * vs_safe, ks_safe, vs_safe
    b = pl.program_id(0)
    ik = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)
        # build the block-diagonal Q once per batch row
        qbd_scr[:] = jnp.zeros_like(qbd_scr)
        q = q_ref[0]                                     # [H, D]
        if mxu_int8:
            # quantize q per head so the score matmul runs int8×int8 on
            # the MXU — the [bk, KVH*D] slabs then never get cast
            qf = q.astype(jnp.float32)
            qs = jnp.max(jnp.abs(qf), axis=1, keepdims=True) / 127.0
            qs = jnp.where(qs == 0.0, 1.0, qs)
            qs_scr[:] = jnp.broadcast_to(qs, qs_scr.shape)
            q = jnp.clip(jnp.round(qf / qs), -127, 127)
        for h in range(kvh):
            qbd_scr[h * g:(h + 1) * g, h * d:(h + 1) * d] = \
                q[h * g:(h + 1) * g].astype(qbd_scr.dtype)

    length = len_ref[b]
    run = ik * block_k < length
    if window is not None:
        # blocks entirely below the live window are skipped (their DMA is
        # elided by the matching index-map pin) — decode cost is O(window)
        run = jnp.logical_and(run, (ik + 1) * block_k > length - window)

    def _expand_scales(s_ref):
        # [bk, KVH] per-(position, kv-head) scales → [H, bk]: row r of the
        # block-diagonal Q belongs to kv head r // g, so its score column j
        # dequantizes by scales[j, r // g].  Only this [bk, KVH]-sized tile
        # is ever transposed — the KV slabs stay in their DMA layout.
        st = (s_ref[0, 0] if stacked else s_ref[0]).astype(jnp.float32)
        st = st.T                                        # [KVH, bk]
        if g == 1:
            return st
        return jnp.repeat(st, g, axis=0)                 # [H, bk]

    # skip KV blocks entirely past the live cache region (and, with a
    # window, entirely before it)
    @pl.when(run)
    def _body():
        k = k_ref[0, 0] if stacked else k_ref[0]         # [bk, KVH*D]
        v = v_ref[0, 0] if stacked else v_ref[0]
        if quant and not mxu_int8:
            # int8 payloads: cast for the MXU; the per-entry scale applies
            # to SCORES (k) and to P (v) — never to the big slabs, so no
            # [bk, KVH*D]-sized reshape/relayout happens in-kernel
            k = k.astype(qbd_scr.dtype)
            v = v.astype(qbd_scr.dtype)
        # all heads' scores in ONE matmul (see module docstring)
        if mxu_int8:
            # int8×int8 MXU path: the slabs go to the matmul untouched
            s = jax.lax.dot_general(
                qbd_scr[:], k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32).astype(jnp.float32)
            s = s * (qs_scr[:, 0:1] * scale)
        else:
            s = jax.lax.dot_general(
                qbd_scr[:], k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
        if quant:
            s = s * _expand_scales(ks_ref)
        pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)                  # [1, bk]
        live = pos < length                              # cache tail mask
        if window is not None:
            # sliding-window decode (mistral-style): the single query sits
            # at position length-1, so the live window is
            # [length - window, length)
            live = jnp.logical_and(live, pos >= length - window)
        if fused_write:
            # the cache does NOT yet hold this step's token: its column
            # (global position length-1, which only occurs in this — the
            # last live — block) is recomputed from the fresh row and
            # substituted into the score tile.  Dequantized values keep
            # write-then-read parity with the unfused path.
            _, _, kn_used, vn_used, _, _ = _new_rows()
            kn_rep = kn_used if g == 1 else jnp.repeat(kn_used, g, axis=0)
            q_f32 = q_ref[0].astype(jnp.float32)         # [H, D]
            col = jnp.sum(q_f32 * kn_rep, axis=1,
                          keepdims=True) * scale         # [H, 1]
            sel_col = (pos == length - 1)                # [1, bk]
            s = jnp.where(sel_col, col, s)
        s = jnp.where(live, s, NEG_INF)                  # [H, bk]
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(live, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, 0:1] * corr + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        pv = p * _expand_scales(vs_ref) if quant else p
        if fused_write:
            # the V slab's row at the write column is stale too: zero that
            # probability column for the big PV matmul and add its rank-1
            # contribution from the fresh (dequantized) V row per head.
            # p_col comes from the RAW probabilities — the fresh row's
            # scale is already folded into vn_used, the slab's stale
            # v-scale must not touch it.
            p_col = jnp.sum(jnp.where(sel_col, p, 0.0), axis=1,
                            keepdims=True)               # [H, 1]
            pv = jnp.where(sel_col, 0.0, pv)
        if mxu_int8:
            # fold the v-scale into P, then quantize P per row: the PV
            # matmul also runs int8×int8 with a per-row rescale after
            rmax = jnp.max(pv, axis=1, keepdims=True) / 127.0
            rsafe = jnp.where(rmax == 0.0, 1.0, rmax)
            pv_i8 = jnp.clip(jnp.round(pv / rsafe), -127, 127) \
                .astype(jnp.int8)
            o_flat = jax.lax.dot_general(
                pv_i8, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32).astype(jnp.float32)
            o_flat = o_flat * rmax
        else:
            o_flat = jax.lax.dot_general(pv.astype(v.dtype), v,
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        # accumulate each head's D-column diagonal block of [H, KVH*D]
        for h in range(kvh):
            rows = slice(h * g, (h + 1) * g)
            contrib = o_flat[rows, h * d:(h + 1) * d]
            if fused_write:
                contrib = contrib + p_col[rows] * vn_used[h:h + 1]
            acc_scr[rows] = acc_scr[rows] * corr[rows] + contrib

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        if fused_write:
            # write this step's row into the cache via the ALIASED,
            # 8-ROW-STRIPE outputs: the output blocks cover only the
            # 8-sublane-aligned stripe containing the write row (pinned
            # by index map), so per step the flush is 8 rows — not a
            # whole block (a full-block write-back measured ~1.8x on the
            # whole decode step at bs64).  The stripe's other 7 rows are
            # merged from the raw input slab (loaded for scores anyway);
            # Mosaic accepts the dynamic 8-aligned ref read.
            # Clamp: a zero-length row (invalid input — lengths INCLUDE
            # this step's token, so the minimum is 1) would compute
            # row = (-1) % block_k = block_k-1 and merge the slab's FAR
            # stripe into the pinned rows 0-7 of the output (the output
            # index map clamps to stripe 0), silently corrupting the
            # cache head.  Clamped, length=0 degenerates to the benign
            # length=1 write at row 0.
            row = jnp.maximum(length - 1, 0) % block_k
            base = (row // 8) * 8
            off = row - base
            sel = jax.lax.broadcasted_iota(
                jnp.int32, (8, 1), 0) == off             # [8, 1]
            kq, vq, _, _, ks_n, vs_n = _new_rows()
            if stacked:
                kraw8 = k_ref[0, 0, pl.dslice(base, 8)]  # [8, KVH*D] raw
                vraw8 = v_ref[0, 0, pl.dslice(base, 8)]
            else:
                kraw8 = k_ref[0, pl.dslice(base, 8)]
                vraw8 = v_ref[0, pl.dslice(base, 8)]
            # per-kv-head merges: Mosaic cannot shape-cast a computed
            # [KVH, D] f32 tile to [1, KVH*D], so each head's D-column
            # stripe merges separately
            for hk in range(kvh):
                cols = slice(hk * d, (hk + 1) * d)
                km = jnp.where(sel, kq[hk:hk + 1],
                               kraw8[:, cols].astype(jnp.float32))
                vm = jnp.where(sel, vq[hk:hk + 1],
                               vraw8[:, cols].astype(jnp.float32))
                if stacked:
                    ko_ref[0, 0, :, cols] = km.astype(ko_ref.dtype)
                    vo_ref[0, 0, :, cols] = vm.astype(vo_ref.dtype)
                else:
                    ko_ref[0, :, cols] = km.astype(ko_ref.dtype)
                    vo_ref[0, :, cols] = vm.astype(vo_ref.dtype)
            if quant:
                if stacked:
                    ks_raw8 = ks_ref[0, 0, pl.dslice(base, 8)] \
                        .astype(jnp.float32)             # [8, KVH]
                    vs_raw8 = vs_ref[0, 0, pl.dslice(base, 8)] \
                        .astype(jnp.float32)
                else:
                    ks_raw8 = ks_ref[0, pl.dslice(base, 8)] \
                        .astype(jnp.float32)
                    vs_raw8 = vs_ref[0, pl.dslice(base, 8)] \
                        .astype(jnp.float32)
                lane = jax.lax.broadcasted_iota(jnp.int32, (1, kvh), 1)
                ksm, vsm = ks_raw8, vs_raw8
                for hk in range(kvh):
                    m = jnp.logical_and(sel, lane == hk)  # [8, KVH]
                    ksm = jnp.where(m, ks_n[hk, 0], ksm)
                    vsm = jnp.where(m, vs_n[hk, 0], vsm)
                if stacked:
                    kso_ref[0, 0] = ksm.astype(kso_ref.dtype)
                    vso_ref[0, 0] = vsm.astype(vso_ref.dtype)
                else:
                    kso_ref[0] = ksm.astype(kso_ref.dtype)
                    vso_ref[0] = vsm.astype(vso_ref.dtype)


def _chunk_prefill_kernel(start_ref, layer_ref, q_ref, k_ref, v_ref, *rest,
                          scale, block_k, nk, c, kvh, g, d, stacked, quant):
    """Multi-token (chunk) prefill against the cache: rows ``iq`` of the
    chunk attend causally to cache positions ``<= start_b + iq``.  Same
    slab layout + online softmax as ``_decode_kernel``, but with a [C, bk]
    score tile per head instead of the block-diagonal all-heads trick
    (C×H rows would not fit one matmul)."""
    if quant:
        (ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr) = rest
    else:
        ks_ref = vs_ref = None
        (o_ref, m_scr, l_scr, acc_scr) = rest
    b = pl.program_id(0)
    ik = pl.program_id(1)
    h_total = kvh * g

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    start = start_ref[b]
    limit = start + c                       # rows reach pos <= start+c-1
    run = ik * block_k < limit

    @pl.when(run)
    def _body():
        k = k_ref[0, 0] if stacked else k_ref[0]         # [bk, KVH*D]
        v = v_ref[0, 0] if stacked else v_ref[0]
        if quant:
            k = k.astype(q_ref.dtype)
            v = v.astype(q_ref.dtype)
            kst = (ks_ref[0, 0] if stacked else ks_ref[0]) \
                .astype(jnp.float32).T                   # [KVH, bk]
            vst = (vs_ref[0, 0] if stacked else vs_ref[0]) \
                .astype(jnp.float32).T
        pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)                  # [1, bk]
        qpos = start + jax.lax.broadcasted_iota(
            jnp.int32, (c, 1), 0)                        # [C, 1]
        live = pos <= qpos                               # [C, bk] causal+tail
        q_all = q_ref[0]                                 # [C, H*D]
        for h in range(h_total):
            hk = h // g
            qh = q_all[:, h * d:(h + 1) * d]             # [C, D]
            kh = k[:, hk * d:(hk + 1) * d]               # [bk, D]
            s = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if quant:
                s = s * kst[hk:hk + 1]                   # [1, bk] k-scales
            s = jnp.where(live, s, NEG_INF)
            m_prev = m_scr[:, h:h + 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            p = jnp.where(live, p, 0.0)
            corr = jnp.exp(m_prev - m_new)
            l_scr[:, h:h + 1] = (l_scr[:, h:h + 1] * corr
                                 + jnp.sum(p, axis=1, keepdims=True))
            m_scr[:, h:h + 1] = m_new
            if quant:
                p = p * vst[hk:hk + 1]                   # v-scales on P
            o = jax.lax.dot_general(
                p.astype(v.dtype), v[:, hk * d:(hk + 1) * d],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)      # [C, D]
            acc_scr[:, h * d:(h + 1) * d] = \
                acc_scr[:, h * d:(h + 1) * d] * corr + o

    @pl.when(ik == nk - 1)
    def _finish():
        for h in range(h_total):
            l = l_scr[:, h:h + 1]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, :, h * d:(h + 1) * d] = \
                (acc_scr[:, h * d:(h + 1) * d] / safe_l).astype(o_ref.dtype)


def chunk_prefill_attention(q, k_cache, v_cache, starts, scale=None,
                            block_k=DEFAULT_BLOCK_K_DECODE, layer=None,
                            k_scale=None, v_scale=None):
    """Chunked-prefill attention: a block of C fresh query tokens (already
    written to the cache at positions ``starts[b] .. starts[b]+C-1``)
    attends causally over the cache.  The memory-bounding half of chunked
    prefill (reference analog: the workspace-resident incremental prefill
    of ``inference_context.h`` + ``softmax_context``'s arbitrary-length
    cache path, ``pt_binding.cpp:456``): score/probability tiles are
    [C, block_k] regardless of prompt or cache length, so a 4k-prompt
    prefill no longer materializes multi-GB per-layer transients.

    q: [B, C, H, D]; caches as in :func:`decode_attention` (S-major slabs,
    optionally layer-stacked + quantized).  starts: [B] int32 — each row's
    chunk start position (cache positions beyond ``starts[b]+iq`` are
    masked per query row ``iq``).  Returns [B, C, H, D].
    """
    B, C, H, D = q.shape
    stacked = k_cache.ndim == 4
    if stacked and layer is None:
        raise ValueError("stacked [L, ...] caches require layer=")
    quant = k_scale is not None
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    S_max, KVHD = k_cache.shape[-2], k_cache.shape[-1]
    KVH = KVHD // D
    G = H // KVH
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    block_k = min(block_k, S_max)
    nk = pl.cdiv(S_max, block_k)
    layer_arr = jnp.asarray([layer if layer is not None else 0], jnp.int32)

    def _live_block(ik, starts_arr, b):
        # pin blocks past the chunk's furthest reachable position
        # (starts[b] + C - 1) to the last live block — their DMA is elided
        # and their compute pl.when-gated off, like decode's dead tail
        last = jnp.maximum((starts_arr[b] + C + block_k - 1) // block_k - 1,
                           0)
        return jnp.minimum(ik, last)

    if stacked:
        kv_spec = pl.BlockSpec(
            (1, 1, block_k, KVHD),
            lambda b, ik, st, li: (li[0], b, _live_block(ik, st, b), 0))
        sc_spec = pl.BlockSpec(
            (1, 1, block_k, KVH),
            lambda b, ik, st, li: (li[0], b, _live_block(ik, st, b), 0))
    else:
        kv_spec = pl.BlockSpec(
            (1, block_k, KVHD),
            lambda b, ik, st, li: (b, _live_block(ik, st, b), 0))
        sc_spec = pl.BlockSpec(
            (1, block_k, KVH),
            lambda b, ik, st, li: (b, _live_block(ik, st, b), 0))

    in_specs = [
        # q flattened to [B, C, H*D] — Mosaic blocks want at most two
        # non-unit trailing dims, and the flat layout matches the cache
        # slabs' full-lane-width tiling anyway
        pl.BlockSpec((1, C, H * D), lambda b, ik, st, li: (b, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [q.reshape(B, C, H * D), k_cache, v_cache]
    if quant:
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]

    out = pl.pallas_call(
        functools.partial(_chunk_prefill_kernel, scale=float(scale),
                          block_k=block_k, nk=nk, c=C, kvh=KVH, g=G, d=D,
                          stacked=stacked, quant=quant),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, nk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, C, H * D),
                                   lambda b, ik, st, li: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((C, H), jnp.float32),         # running max
                pltpu.VMEM((C, H), jnp.float32),         # running sum
                pltpu.VMEM((C, H * D), jnp.float32),     # per-head acc
            ]),
        out_shape=jax.ShapeDtypeStruct((B, C, H * D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=max(
                64 * 1024 * 1024,
                4 * block_k * KVHD * q.dtype.itemsize
                + 2 * C * H * D * 4 + 16 * 1024 * 1024)),
        interpret=_interpret(),
    )(jnp.asarray(starts, jnp.int32), layer_arr, *operands)
    return out.reshape(B, C, H, D)


def decode_attention(q, k_cache, v_cache, lengths,
                     scale=None, block_k=DEFAULT_BLOCK_K_DECODE, layer=None,
                     k_scale=None, v_scale=None, window=None,
                     int8_matmuls=False, new_k=None, new_v=None):
    """Single-token decode attention.

    q: [B, H, D] (this step's query); caches: [B, S_max, KVH*D]
    (S-major, heads flattened into lanes — the layout the model stores, so
    the cache write is the raw projection output and the kernel's KV DMAs
    are contiguous full-lane-width slabs), or the FULL layer-stacked
    [L, B, S_max, KVH*D] cache with ``layer`` a (traced) layer index — the
    kernel's index maps then DMA only this layer's blocks, so the caller
    never materializes a per-layer slice of the stacked cache.
    lengths: [B] int32 — number of valid cache entries INCLUDING this
    step's freshly-written position.  Returns [B, H, D].

    ``k_scale``/``v_scale`` ([..., S_max, KVH]) switch the caches to int8
    payloads with per-(position, kv-head) dequant scales: decode is
    HBM-bound on the KV stream, so halving its bytes nearly halves the
    cache-dominated share of the step.  Dequantization never touches the
    [block_k, KVH*D] slabs — the k-scale lands on the score tile and the
    v-scale on the probability tile (both [H, block_k]).

    ``new_k``/``new_v`` ([B, KVH, D], raw projection rows) switch on the
    FUSED CACHE WRITE: the kernel quantizes (when the cache is int8) and
    writes this step's row at each row's position ``lengths[b]-1`` into
    the caches, returned as ALIASED outputs (``input_output_aliases`` —
    the in-place workspace write of the reference's ``inference_context``)
    — and substitutes the fresh row into this step's own attention.  The
    caller must then NOT pre-write the cache, and every ``lengths[b]``
    must be >= 1 (it counts the fresh row); a zero-length row is clamped
    to the length-1 write position in-kernel instead of corrupting cache
    rows 0-7.  Returns
    ``(out, k_cache, v_cache[, k_scale, v_scale])`` instead of ``out``.
    Measured: the out-of-kernel dynamic-update-slice chain interacting
    with the kernel's cache reads makes XLA copy the multi-GB cache
    per step above ~bs12 x 4k (129 ms/step); the fused write runs at
    kernel-only speed (12.7 ms/step at bs16 x 4k x 24 layers).
    ``int8_matmuls`` is unsupported with the fused write.
    """
    B, H, D = q.shape
    stacked = k_cache.ndim == 4
    if stacked and layer is None:
        raise ValueError("stacked [L, ...] caches require layer=")
    quant = k_scale is not None
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if int8_matmuls and not quant:
        raise ValueError("int8_matmuls requires quantized caches "
                         "(k_scale/v_scale)")
    fused_write = new_k is not None
    if (new_k is None) != (new_v is None):
        raise ValueError("new_k and new_v must be given together")
    if fused_write and int8_matmuls:
        raise ValueError("int8_matmuls is unsupported with the fused "
                         "cache write (new_k/new_v)")
    if fused_write and k_cache.shape[-2] % 8 != 0:
        raise ValueError(
            f"fused cache write needs S_max % 8 == 0 (8-sublane-aligned "
            f"write stripes); got {k_cache.shape[-2]} — round the cache "
            f"length up (required_cache_len does)")
    if fused_write and min(block_k, k_cache.shape[-2]) % 8 != 0:
        raise ValueError(
            f"fused cache write needs block_k % 8 == 0 (the in-block "
            f"stripe base assumes 8-aligned blocks); got block_k="
            f"{min(block_k, k_cache.shape[-2])}")
    mxu_int8 = bool(int8_matmuls)
    S_max, KVHD = k_cache.shape[-2], k_cache.shape[-1]
    KVH = KVHD // D
    G = H // KVH                                         # query heads per kv head
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    block_k = min(block_k, S_max)
    nk = pl.cdiv(S_max, block_k)
    layer_arr = jnp.asarray([layer if layer is not None else 0], jnp.int32)

    def _live_block(ik, lens, b):
        # pin indices past the live cache region to the last live block:
        # Mosaic skips the DMA when a block index repeats, so dead-region
        # grid steps fetch nothing (their compute is pl.when-gated off
        # too).  With a sliding window, blocks entirely BELOW the window
        # pin to its first block the same way — decode DMA is O(window)
        last = jnp.maximum((lens[b] + block_k - 1) // block_k - 1, 0)
        idx = ik
        if window is not None:
            first = jnp.maximum((lens[b] - window) // block_k, 0)
            idx = jnp.maximum(idx, first)
        return jnp.minimum(idx, last)

    if stacked:
        kv_spec = pl.BlockSpec(
            (1, 1, block_k, KVHD),
            lambda b, ik, lens, li: (li[0], b, _live_block(ik, lens, b), 0))
        sc_spec = pl.BlockSpec(
            (1, 1, block_k, KVH),
            lambda b, ik, lens, li: (li[0], b, _live_block(ik, lens, b), 0))
    else:
        kv_spec = pl.BlockSpec(
            (1, block_k, KVHD),
            lambda b, ik, lens, li: (b, _live_block(ik, lens, b), 0))
        sc_spec = pl.BlockSpec(
            (1, block_k, KVH),
            lambda b, ik, lens, li: (b, _live_block(ik, lens, b), 0))

    in_specs = [
        pl.BlockSpec((1, H, D), lambda b, ik, lens, li: (b, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [q, k_cache, v_cache]
    if quant:
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]

    out_specs = [pl.BlockSpec((1, H, D), lambda b, ik, lens, li: (b, 0, 0))]
    out_shape = [jax.ShapeDtypeStruct((B, H, D), q.dtype)]
    io_aliases = {}
    if fused_write:
        # pinned write-STRIPE output specs: the output block is only the
        # 8-sublane-aligned stripe containing each row's write position
        # (block index in 8-row units), constant per batch row, so Mosaic
        # flushes 8 rows once after the final (writing) grid step;
        # input_output_aliases makes the returned caches the SAME buffers
        # the caller passed in (no copy, no extra HBM)
        def _write_stripe(lens, b):
            return jnp.maximum(lens[b] - 1, 0) // 8

        if stacked:
            kvo_spec = pl.BlockSpec(
                (1, 1, 8, KVHD),
                lambda b, ik, lens, li: (li[0], b, _write_stripe(lens, b), 0))
            sco_spec = pl.BlockSpec(
                (1, 1, 8, KVH),
                lambda b, ik, lens, li: (li[0], b, _write_stripe(lens, b), 0))
        else:
            kvo_spec = pl.BlockSpec(
                (1, 8, KVHD),
                lambda b, ik, lens, li: (b, _write_stripe(lens, b), 0))
            sco_spec = pl.BlockSpec(
                (1, 8, KVH),
                lambda b, ik, lens, li: (b, _write_stripe(lens, b), 0))
        nspec = pl.BlockSpec((1, KVH, D), lambda b, ik, lens, li: (b, 0, 0))
        in_specs += [nspec, nspec]
        operands += [new_k, new_v]
        out_specs += [kvo_spec, kvo_spec]
        out_shape += [jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                      jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype)]
        # operand indices INCLUDE the two scalar-prefetch args
        io_aliases = {3: 1, 4: 2}
        if quant:
            out_specs += [sco_spec, sco_spec]
            out_shape += [jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
                          jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype)]
            io_aliases = {3: 1, 4: 2, 5: 3, 6: 4}

    res = pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale),
                          block_k=block_k, nk=nk, kvh=KVH, g=G, d=D,
                          stacked=stacked, quant=quant,
                          window=None if window is None else int(window),
                          mxu_int8=mxu_int8, fused_write=fused_write),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, nk),
            in_specs=in_specs,
            out_specs=out_specs if fused_write else out_specs[0],
            scratch_shapes=[
                pltpu.VMEM((H, LSE_LANES), jnp.float32),
                pltpu.VMEM((H, LSE_LANES), jnp.float32),
                pltpu.VMEM((H, D), jnp.float32),
                pltpu.VMEM((H, KVHD),
                           jnp.int8 if mxu_int8 else q.dtype),
            ] + ([pltpu.VMEM((H, LSE_LANES), jnp.float32)]
                 if mxu_int8 else [])),
        out_shape=out_shape if fused_write else out_shape[0],
        input_output_aliases=io_aliases,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            # the [block_k, KVH*D] K/V slabs double-buffer; the default
            # 16 MB scoped-vmem budget is a hair short at the default
            # block_k, and DSTPU_DECODE_BLOCK_K can grow the slabs further —
            # size the budget from the actual blocks (4 slab buffers +
            # write-block outputs + scratch/q/out headroom)
            vmem_limit_bytes=max(
                96 * 1024 * 1024,
                6 * block_k * KVHD * q.dtype.itemsize + 16 * 1024 * 1024)),
        interpret=_interpret(),
    )(jnp.asarray(lengths, jnp.int32), layer_arr, *operands)
    return res
