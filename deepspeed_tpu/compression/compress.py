"""Compression-aware training, TPU-native.

Capability parity with reference ``deepspeed/compression/compress.py`` and
``basic_layer.py``: weight quantization (QAT with a bit-shedding schedule),
activation quantization, sparse/row/head/channel pruning, layer reduction and
knowledge-distillation student init (``init_compression :95``,
``redundancy_clean :123``, ``student_initialization :167``).

Design: the reference swaps ``nn.Linear`` for ``LinearLayer_Compress``
(`basic_layer.py:121`) and mutates weights through buffers and hooks.  Here a
model is a flax param pytree and compression is a *pure function of params*:

    spec   = init_compression(params, ds_config)        # match groups, score masks
    viewed = apply_compression(params, spec, step)      # inside the jitted step
    params = redundancy_clean(params, spec)             # physical dim reduction

``apply_compression`` runs under ``jit`` — masks are constants folded into the
compiled program, fake-quant uses a straight-through estimator, so XLA fuses
the whole view into the forward matmuls (no extra HBM round trips).

Axis convention: flax kernels are ``[in, out]`` (torch is ``[out, in]``), so
the reference's "row pruning" (output features) masks *columns* here.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.quantizer.kernels import fake_quantize
from deepspeed_tpu.utils.logging import logger
from . import constants as C
from .config import get_compression_config, get_layer_reduction_config
from .helper import (flatten_params, get_by_path, match_module_scope,
                     module_paths, module_weight_path, set_by_path)


class CompressionSpec:
    """Per-module technique bindings + precomputed pruning masks."""

    def __init__(self):
        # {mod_path: {technique: params-dict}}
        self.bindings = {}
        # {mod_path: {technique: np.ndarray bool mask over the named axis}}
        self.masks = {}
        # {mod_path: {technique: [related mod paths]}}
        self.related = {}
        self.shared = {}
        self.layer_reduction = {C.LAYER_REDUCTION_ENABLED: False}

    def bind(self, mod, tech, params, related=None):
        self.bindings.setdefault(mod, {})[tech] = params
        if related:
            self.related.setdefault(mod, {})[tech] = related

    def techniques(self, mod):
        return self.bindings.get(mod, {})


def _keep_mask(scores, dense_ratio):
    """Boolean mask keeping the top ``ceil(dense_ratio*n)`` by score
    (reference TopKBinarizer, ``utils.py``)."""
    scores = np.asarray(scores, dtype=np.float64)
    n = scores.size
    k = max(1, int(math.ceil(dense_ratio * n)))
    idx = np.argsort(-scores, kind="stable")[:k]
    mask = np.zeros(n, dtype=bool)
    mask[idx] = True
    return mask


def init_compression(params, ds_config, teacher_params=None, mpu=None):
    """Match ``different_groups`` scopes against the param tree and score
    initial pruning masks (reference ``compress.py:95``).

    Returns a ``CompressionSpec``.  ``params`` may include a top-level
    'params' collection wrapper (flax); paths are matched against the tree
    as given.
    """
    cfg = get_compression_config(ds_config)
    spec = CompressionSpec()
    spec.layer_reduction = get_layer_reduction_config(ds_config)
    mods = module_paths(params)

    for tech, tc in cfg.items():
        shared = tc[C.SHARED_PARAMETERS]
        spec.shared[tech] = shared
        if not shared.get(C.TECHNIQUE_ENABLED):
            continue
        claimed = set()
        for gname, g in tc[C.DIFFERENT_GROUPS].items():
            matched = []
            for pat in g[C.DIFFERENT_GROUPS_MODULE_SCOPE]:
                for m in match_module_scope(pat, mods):
                    if m not in claimed:
                        matched.append(m)
                        claimed.add(m)
            related_pats = g[C.DIFFERENT_GROUPS_RELATED_MODULE_SCOPE]
            for m in matched:
                rel = []
                if related_pats:
                    # reference pairs related_modules positionally per group;
                    # we resolve each pattern relative to the whole tree.
                    for rpat_list in related_pats:
                        if isinstance(rpat_list, str):
                            rpat_list = [rpat_list]
                        for rpat in rpat_list:
                            cands = match_module_scope(rpat, mods)
                            if not cands:
                                continue
                            # pair with the match(es) sharing the deepest
                            # common ancestor — e.g. layer_0/intermediate/dense
                            # pairs with layer_0/output/dense, not layer_1's.
                            # Nested modules must share at least one ancestor:
                            # a zero-overlap candidate set would otherwise
                            # pair m with every match in the model.
                            floor = 1 if "/" in m else 0
                            best = max(_common_depth(m, r) for r in cands)
                            if best >= floor:
                                rel += [r for r in cands
                                        if _common_depth(m, r) == best]
                            else:
                                logger.warning(
                                    f"related_modules pattern {rpat!r} has no "
                                    f"match near {m!r}; skipping")
                gparams = dict(g[C.DIFFERENT_GROUPS_PARAMETERS])
                gparams.setdefault(C.TECHNIQUE_SCHEDULE_OFFSET,
                                   shared.get(C.TECHNIQUE_SCHEDULE_OFFSET, 0))
                spec.bind(m, tech, gparams, rel)
                _score_mask(spec, params, m, tech, gparams)
            if not matched:
                logger.warning(
                    f"compression group {gname}/{tech} matched no modules")
    return spec


def _common_depth(mod, other):
    """Number of leading path segments two module paths share (how close two
    modules sit in the tree — used to pair each pruned module with *its*
    layer's related modules)."""
    a, b = mod.split("/"), other.split("/")
    n = 0
    for x, y in zip(a[:-1], b[:-1]):
        if x != y:
            break
        n += 1
    return n


def _score_mask(spec, params, mod, tech, gparams):
    if tech not in C.PRUNING_TECHNIQUES:
        return
    w = np.asarray(jax.device_get(get_by_path(
        params, module_weight_path(params, mod))), dtype=np.float32)
    if tech == C.SPARSE_PRUNING:
        ratio = gparams.get(C.SPARSE_PRUNING_DENSE_RATIO, 0.5)
        mask = _keep_mask(np.abs(w).ravel(), ratio).reshape(w.shape)
    elif tech == C.ROW_PRUNING:
        # output features = last axis of a flax kernel
        ratio = gparams.get(C.ROW_PRUNING_DENSE_RATIO, 0.5)
        scores = np.abs(w).reshape(-1, w.shape[-1]).sum(axis=0)
        mask = _keep_mask(scores, ratio)
    elif tech == C.HEAD_PRUNING:
        ratio = gparams.get(C.HEAD_PRUNING_DENSE_RATIO, 0.5)
        num_heads = int(gparams[C.HEAD_PRUNING_NUM_HEADS])
        # applied to the attention output projection: input dim = heads*hd
        head_dim = w.shape[0] // num_heads
        scores = np.abs(w).reshape(num_heads, head_dim, -1).sum(axis=(1, 2))
        mask = _keep_mask(scores, ratio)
    elif tech == C.CHANNEL_PRUNING:
        ratio = gparams.get(C.CHANNEL_PRUNING_DENSE_RATIO, 0.5)
        scores = np.abs(w).reshape(-1, w.shape[-1]).sum(axis=0)
        mask = _keep_mask(scores, ratio)
    spec.masks.setdefault(mod, {})[tech] = mask


def _current_bits(shared, gparams, step):
    start = int(gparams.get(C.WEIGHT_QUANTIZE_START_BITS, 16))
    target = int(gparams.get(C.WEIGHT_QUANTIZE_TARGET_BITS, 8))
    period = int(gparams.get(C.WEIGHT_QUANTIZATION_PERIOD, 1))
    offset = int(gparams.get(C.TECHNIQUE_SCHEDULE_OFFSET, 0))
    if step < offset:
        return start
    sheds = (step - offset) // max(1, period)
    return max(target, start - sheds)


def apply_compression(params, spec, step):
    """Return the compressed *view* of params for the forward pass.

    Pure and jit-safe for a static ``step`` (the engine passes the host-side
    global step, so each technique activation recompiles once — the analog of
    the reference flipping ``*_enabled`` flags in the scheduler)."""
    step = int(step)
    out = params
    for mod, techs in spec.bindings.items():
        wpath = module_weight_path(params, mod)
        w = get_by_path(out, wpath)
        node = get_by_path(out, mod)
        b = node.get("bias") if isinstance(node, dict) else None
        new_b = b
        for tech in C.PRUNING_TECHNIQUES:
            if tech not in techs:
                continue
            if step < int(techs[tech].get(C.TECHNIQUE_SCHEDULE_OFFSET, 0)):
                continue
            mask = spec.masks[mod][tech]
            if tech == C.SPARSE_PRUNING:
                w = w * jnp.asarray(mask, dtype=w.dtype)
            elif tech in (C.ROW_PRUNING, C.CHANNEL_PRUNING):
                m = jnp.asarray(mask, dtype=w.dtype)
                w = w * m  # broadcast over last (output) axis
                if new_b is not None:
                    new_b = new_b * m
                for rel in spec.related.get(mod, {}).get(tech, []):
                    out = _mask_input_axis(out, params, rel, mask)
            elif tech == C.HEAD_PRUNING:
                num_heads = int(techs[tech][C.HEAD_PRUNING_NUM_HEADS])
                head_dim = w.shape[0] // num_heads
                m = jnp.repeat(jnp.asarray(mask, dtype=w.dtype), head_dim)
                w = w * m[:, None]
                for rel in spec.related.get(mod, {}).get(tech, []):
                    out = _mask_output_axis(out, params, rel,
                                            np.repeat(mask, head_dim))
        if C.WEIGHT_QUANTIZATION in techs:
            shared = spec.shared[C.WEIGHT_QUANTIZATION]
            gp = techs[C.WEIGHT_QUANTIZATION]
            if shared.get(C.WEIGHT_QUANTIZE_IN_FORWARD_ENABLED, False) or \
                    step >= int(gp.get(C.TECHNIQUE_SCHEDULE_OFFSET, 0)):
                bits = _current_bits(shared, gp, step)
                if bits < 16:
                    groups = int(shared.get(C.WEIGHT_QUANTIZE_GROUPS, 1))
                    w = fake_quantize(w, groups, bits)
        out = set_by_path(out, wpath, w)
        if new_b is not b:
            out = set_by_path(out, mod + "/bias", new_b)
    return out


def _mask_input_axis(out, params, mod, mask):
    """Zero input features of a related (downstream) module."""
    wpath = module_weight_path(params, mod)
    w = get_by_path(out, wpath)
    m = jnp.asarray(mask, dtype=w.dtype)
    shape = [1] * w.ndim
    shape[-2] = w.shape[-2]
    return set_by_path(out, wpath, w * m.reshape(shape))


def _mask_output_axis(out, params, mod, mask):
    """Zero output features of a related (upstream, e.g. QKV) module."""
    wpath = module_weight_path(params, mod)
    w = get_by_path(out, wpath)
    m = jnp.asarray(mask, dtype=w.dtype)
    new_w = w * m
    out = set_by_path(out, wpath, new_w)
    node = get_by_path(out, mod)
    if isinstance(node, dict) and node.get("bias") is not None:
        out = set_by_path(out, mod + "/bias", node["bias"] * m)
    return out


def redundancy_clean(params, spec, ds_config=None):
    """Physically remove pruned dimensions (reference ``compress.py:123``):
    row/head/channel masks become real slices on the module *and* its
    related modules; sparse masks are folded into the weights."""
    out = params
    for mod, techs in spec.bindings.items():
        for tech, gp in techs.items():
            if tech not in C.PRUNING_TECHNIQUES:
                continue
            mask = spec.masks[mod][tech]
            wpath = module_weight_path(params, mod)
            w = np.asarray(jax.device_get(get_by_path(out, wpath)))
            node = get_by_path(out, mod)
            bias = node.get("bias") if isinstance(node, dict) else None
            if tech == C.SPARSE_PRUNING:
                out = set_by_path(out, wpath, jnp.asarray(w * mask))
                continue
            if tech == C.HEAD_PRUNING:
                head_dim = w.shape[0] // mask.size
                in_mask = np.repeat(mask, head_dim)
                out = set_by_path(out, wpath, jnp.asarray(w[in_mask, :]))
                for rel in spec.related.get(mod, {}).get(tech, []):
                    out = _slice_output_axis(out, rel, in_mask)
                continue
            # row / channel pruning: slice output axis, related input axes
            out = set_by_path(out, wpath, jnp.asarray(w[..., mask]))
            if bias is not None:
                out = set_by_path(out, mod + "/bias",
                                  jnp.asarray(np.asarray(bias)[mask]))
            for rel in spec.related.get(mod, {}).get(tech, []):
                rw_path = module_weight_path(params, rel)
                rw = np.asarray(jax.device_get(get_by_path(out, rw_path)))
                out = set_by_path(out, rw_path, jnp.asarray(rw[..., mask, :]))
    return out


def _slice_output_axis(out, mod, mask):
    wpath = module_weight_path(out, mod)
    w = np.asarray(jax.device_get(get_by_path(out, wpath)))
    out = set_by_path(out, wpath, jnp.asarray(w[..., mask]))
    node = get_by_path(out, mod)
    if isinstance(node, dict) and node.get("bias") is not None:
        b = np.asarray(jax.device_get(node["bias"]))
        out = set_by_path(out, mod + "/bias", jnp.asarray(b[mask]))
    return out


def quant_act(x, bits=8, symmetric=True, static_range=None):
    """Activation fake-quant with STE (reference ``basic_layer.py:17
    QuantAct``).  ``static_range=(min,max)`` selects static calibration;
    default is per-tensor dynamic range."""
    if static_range is not None:
        lo, hi = static_range
        x = jnp.clip(x, lo, hi)
    levels = 2 ** bits - 1
    if symmetric:
        scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8) * 2.0 / levels
        q = jnp.round(x / scale) * scale
    else:
        lo = jnp.min(x)
        scale = jnp.maximum(jnp.max(x) - lo, 1e-8) / levels
        q = jnp.round((x - lo) / scale) * scale + lo
    return x + jax.lax.stop_gradient(q - x)


def student_initialization(student_params, teacher_params, ds_config):
    """Layer-reduction KD init (reference ``compress.py:167``): copy the
    configured ``teacher_layer`` blocks of the teacher into the student's
    consecutive layers, plus ``other_module_name`` subtrees verbatim."""
    lr = get_layer_reduction_config(ds_config)
    assert lr.get(C.LAYER_REDUCTION_ENABLED), "layer_reduction not enabled"
    prefix = lr[C.MODULE_NAME_PREFIX].replace(".", "/")
    teacher_layers = lr[C.TEACHER_LAYER]
    other = [m.replace(".", "/") for m in lr.get(C.OTHER_MODULE_NAME, [])]

    flat_t = flatten_params(teacher_params)
    out = student_params
    for s_idx, t_idx in enumerate(teacher_layers):
        s_pre, t_pre = f"{prefix}_{s_idx}", f"{prefix}_{t_idx}"
        alt_s, alt_t = f"{prefix}/{s_idx}", f"{prefix}/{t_idx}"
        for path, leaf in flat_t.items():
            for sp, tp in ((s_pre, t_pre), (alt_s, alt_t)):
                if path.startswith(tp + "/"):
                    out = set_by_path(out, sp + path[len(tp):], leaf)
    for pat in other:
        for path, leaf in flat_t.items():
            if pat in path:
                out = set_by_path(out, path, leaf)
    return out
