"""Activation checkpointing tests — the analog of the reference's
``tests/unit/runtime/activation_checkpointing/test_activation_checkpointing.py``:
checkpointed forward/backward must match the non-checkpointed one exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.activation_checkpointing import checkpointing


@pytest.fixture(autouse=True)
def _reset():
    checkpointing.reset()
    yield
    checkpointing.reset()


def _mlp(params, x):
    h = jnp.tanh(x @ params["w1"])
    return jnp.sum(h @ params["w2"])


def _params():
    rng = np.random.default_rng(0)
    return {
        "w1": jnp.asarray(rng.normal(size=(16, 32)), jnp.float32),
        "w2": jnp.asarray(rng.normal(size=(32, 8)), jnp.float32),
    }


def test_configure_and_is_configured():
    assert not checkpointing.is_configured()
    checkpointing.configure(None, deepspeed_config={
        "activation_checkpointing": {"partition_activations": True,
                                     "cpu_checkpointing": False}})
    assert checkpointing.is_configured()
    assert checkpointing.get_config()["partition_activations"]


def test_checkpoint_matches_plain_grads():
    checkpointing.configure(None)
    params = _params()
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 16)), jnp.float32)

    plain = jax.grad(lambda p: _mlp(p, x))(params)
    ckpt = jax.grad(lambda p: checkpointing.checkpoint(_mlp, p, x))(params)
    for k in plain:
        # remat replays the forward under a different fusion schedule, so
        # grads match to float32 accumulation order, not bitwise
        np.testing.assert_allclose(plain[k], ckpt[k], rtol=1e-4, atol=1e-6)


def test_checkpoint_inside_jit():
    checkpointing.configure(None)
    params = _params()
    x = jnp.ones((2, 16), jnp.float32)

    @jax.jit
    def step(p):
        return jax.grad(lambda q: checkpointing.checkpoint(_mlp, q, x))(p)

    g = step(params)
    assert g["w1"].shape == (16, 32)


def test_cpu_checkpointing_policy_still_correct():
    checkpointing.configure(None, checkpoint_in_cpu=True)
    params = _params()
    x = jnp.ones((2, 16), jnp.float32)
    plain = jax.grad(lambda p: _mlp(p, x))(params)
    ckpt = jax.grad(lambda p: checkpointing.checkpoint(_mlp, p, x))(params)
    np.testing.assert_allclose(plain["w2"], ckpt["w2"], rtol=1e-4, atol=1e-6)


def test_checkpoint_wrapper():
    checkpointing.configure(None)
    fn = checkpointing.checkpoint_wrapper(_mlp)
    params = _params()
    x = jnp.ones((2, 16), jnp.float32)
    assert np.isfinite(float(fn(params, x)))


def test_rng_tracker_fork_deterministic():
    t1 = checkpointing.model_parallel_cuda_manual_seed(1234)
    with t1.fork() as k1:
        a = jax.random.normal(k1, (4,))
    t2 = checkpointing.model_parallel_cuda_manual_seed(1234)
    with t2.fork() as k2:
        b = jax.random.normal(k2, (4,))
    np.testing.assert_array_equal(a, b)
    # a second fork yields a *different* stream
    with t2.fork() as k3:
        c = jax.random.normal(k3, (4,))
    assert not np.allclose(b, c)
