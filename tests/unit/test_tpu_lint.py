"""tpu-lint tests: every rule (positive + negative fixture), suppression
semantics, CLI exit codes, a zero-findings gate over the real package, and
the jaxpr-level entry-point checks — all tier-1 (no slow marker), so lint
regressions fail the tier-1 command with no extra CI infra."""

import os
import pathlib

import pytest

from deepspeed_tpu.tools.lint import run_lint
from deepspeed_tpu.tools.lint.__main__ import main as lint_main

HERE = pathlib.Path(__file__).resolve().parent
FIXTURES = HERE / "tpu_lint_fixtures"
PACKAGE = HERE.parents[1] / "deepspeed_tpu"


def lint_fixture(name, rules=None):
    findings, stats = run_lint([str(FIXTURES / name)], rules=rules)
    return findings, stats


@pytest.mark.parametrize("rule_id,expected_min", [
    ("TL001", 7), ("TL002", 3), ("TL003", 4), ("TL004", 2), ("TL005", 2),
    ("TL006", 9), ("TL007", 4), ("TL008", 6), ("TL009", 5), ("TL010", 7),
    ("TL011", 8)])
def test_rule_positive_fixture(rule_id, expected_min):
    findings, _ = lint_fixture(f"{rule_id.lower()}_positive.py")
    hits = [f for f in findings if f.rule == rule_id]
    assert len(hits) >= expected_min, \
        f"{rule_id}: expected >= {expected_min} findings, got {findings}"


@pytest.mark.parametrize("rule_id",
                         ["TL001", "TL002", "TL003", "TL004", "TL005",
                          "TL006", "TL007", "TL008", "TL009", "TL010",
                          "TL011"])
def test_rule_negative_fixture(rule_id):
    findings, _ = lint_fixture(f"{rule_id.lower()}_negative.py")
    hits = [f for f in findings if f.rule == rule_id]
    assert not hits, f"{rule_id} false positives: {hits}"


def test_tl001_reachability_through_helper():
    """A sync inside a plain helper CALLED from a hot path is flagged."""
    findings, _ = lint_fixture("tl001_positive.py")
    helper_hits = [f for f in findings
                   if f.rule == "TL001" and 18 <= f.line <= 20]
    assert helper_hits, "sync in helper reachable from @hot_path not flagged"


def test_suppression_line_function_and_wrong_rule():
    findings, stats = lint_fixture("suppression.py")
    # line- and function-level TL001 suppressions hold (3 sites suppressed)
    assert stats["suppressed"].get("TL001", 0) == 3
    # the wrong-rule suppression does NOT silence TL001
    leaked = [f for f in findings if f.rule == "TL001"]
    assert len(leaked) == 1 and "step_with_wrong_rule" in \
        pathlib.Path(leaked[0].path).read_text().splitlines()[leaked[0].line - 2]


def test_cli_exit_codes(capsys):
    assert lint_main([str(FIXTURES / "tl001_positive.py")]) == 1
    assert lint_main([str(FIXTURES / "tl001_negative.py")]) == 0
    capsys.readouterr()


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("TL001", "TL002", "TL003", "TL004", "TL005", "TL006",
                "TL007", "TL008", "TL009", "TL010", "TL011"):
        assert rid in out


def test_cli_update_requires_contracts(capsys):
    assert lint_main(["--update"]) == 2
    capsys.readouterr()


def test_cli_concurrency_exits_nonzero_on_unlocked_access(capsys):
    """`ds_lint --concurrency` on a synthetically introduced unlocked
    guarded-field access must exit nonzero from the STATIC sweep (the
    slow interleaving prover is skipped once the sweep is dirty)."""
    assert lint_main(["--concurrency",
                      str(FIXTURES / "tl008_positive.py")]) == 1
    out = capsys.readouterr().out
    assert "TL008" in out and "tpu-lint[concurrency]" in out


def test_cli_stats_docs_gate_green_and_detects_drift(tmp_path, capsys):
    """`ds_lint --stats-docs` (tier-1): every serving stats key and
    /metrics series must appear backticked in docs/observability.md —
    green on the repo as committed, exit 1 when the doc loses a key,
    exit 2 when the collector loses its sources."""
    from deepspeed_tpu.tools.lint import stats_docs
    assert lint_main(["--stats-docs"]) == 0
    out = capsys.readouterr().out
    assert "stats keys" in out and "documented" in out
    # the collectors see the real metric surface
    keys = stats_docs.collect_stats_keys()
    series = stats_docs.collect_metric_series()
    assert {"iterations", "decode_tokens", "completed",
            "lock_wait_scheduler_s"} <= keys
    assert {"dstpu_serving_queue_depth", "dstpu_serving_ttft_seconds",
            "dstpu_serving_lock_wait_seconds"} <= series
    # drift detection: a doc missing everything but one key fails loudly
    thin = tmp_path / "obs.md"
    thin.write_text("| `iterations` | count |\n")
    assert stats_docs.main(doc_path=str(thin)) == 1
    out = capsys.readouterr().out
    assert "decode_tokens" in out and "dstpu_serving_ttft_seconds" in out
    capsys.readouterr()


def test_cli_comm_exits_nonzero_on_sweep_finding(capsys):
    """`ds_lint --comm` on a source tree with an unsuppressed replicated
    spec must exit 1 from the STATIC sweep (the mesh-scaling prover is
    skipped once the sweep is dirty)."""
    assert lint_main(["--comm", str(FIXTURES / "tl010_positive.py")]) == 1
    out = capsys.readouterr().out
    assert "TL010" in out and "tpu-lint[comm]" in out


def test_cli_comm_synthetic_replication_break(capsys, monkeypatch):
    """Acceptance: `ds_lint --comm` exits 1 on a synthetic replication
    break — a fixture plan whose replicated batch weak-scales with the
    mesh compiles at {1,2,4}, its per-chip all-reduce volume grows, and
    the prover fails READABLY (op, transitions, the smell, the fix)."""
    monkeypatch.setenv("DSTPU_COMM_PLANS_MODULE",
                       str(FIXTURES / "comm_fixture_plans.py"))
    assert lint_main(["--comm", str(FIXTURES / "tl010_negative.py")]) == 1
    out = capsys.readouterr().out
    assert "GROWS with mesh size" in out
    assert "fixture.replicated_batch" in out
    assert "replicated-tensor smell" in out
    assert "allowed_growth" in out


def test_tl011_canonical_axes_mirror_topology():
    """TL011's axis literal set is a pure-data mirror of the topology's
    AXIS_ORDER (the linter never imports the code under analysis) — this
    is the registry-matches-engine test keeping the two in lockstep."""
    from deepspeed_tpu.parallel.topology import AXIS_ORDER
    from deepspeed_tpu.tools.lint.rules.tl011_resharding_seams import \
        _CANONICAL_AXES
    assert _CANONICAL_AXES == AXIS_ORDER


def test_cli_concurrency_clean_paths_reach_the_prover(capsys, monkeypatch):
    """With a clean sweep, --concurrency hands off to the interleaving
    harness (stubbed here — the real harness runs as its own tier-1
    test in test_serving_concurrency.py)."""
    from deepspeed_tpu.tools.lint import interleave_check
    monkeypatch.setattr(interleave_check, "main", lambda: 0)
    assert lint_main(["--concurrency",
                      str(FIXTURES / "tl008_negative.py")]) == 0
    capsys.readouterr()


# ------------------------------------------------------------------ #
# Suppression edge cases: decorated functions + multi-rule disables
# ------------------------------------------------------------------ #
def test_suppression_on_decorated_functions():
    """A function-level disable works from the decorator line, from the
    LAST of stacked decorators, and from the def line under a decorator —
    all three cover the whole body."""
    findings, stats = lint_fixture("suppression_edge.py")
    deco = [f for f in findings if f.rule == "TL001" and f.line <= 23]
    assert not deco, f"decorated-function suppression leaked: {deco}"


def test_multi_rule_disable_on_one_line():
    """`disable=TL001,TL005 -- reason` suppresses BOTH rules on the line;
    a single-rule disable on the same pattern still leaks the other."""
    findings, stats = lint_fixture("suppression_edge.py")
    assert stats["suppressed"].get("TL001", 0) == 5
    assert stats["suppressed"].get("TL005", 0) == 1
    leaked = [f for f in findings if f.rule == "TL005"]
    assert len(leaked) == 1, leaked
    src = pathlib.Path(leaked[0].path).read_text().splitlines()
    assert "disable=TL001 --" in src[leaked[0].line - 1]


def test_package_is_lint_clean():
    """The gate: the real package must carry zero unsuppressed findings —
    new hazards either get fixed or get a reasoned disable comment."""
    findings, stats = run_lint([str(PACKAGE)])
    assert stats["files"] > 100, "package path wrong?"
    assert not findings, "unsuppressed tpu-lint findings:\n" + \
        "\n".join(str(f) for f in findings)


def test_hot_path_decorator_is_identity():
    from deepspeed_tpu.tools.lint.hotpath import REGISTERED, hot_path

    @hot_path("test.path")
    def fn(x):
        return x + 1

    assert fn(1) == 2
    assert ("test.path", fn.__module__, fn.__qualname__) in REGISTERED


# ------------------------------------------------------------------ #
# jaxpr-level entry-point checks (CPU)
# ------------------------------------------------------------------ #
@pytest.mark.parametrize("builder_name", [
    "runtime_train_step", "runtime_apply_update", "inference_decode",
    "inference_prefill_chunk", "serving_decode_step",
    "serving_admission_prefill", "serving_admit",
    "serving_decode_step_paged", "serving_admission_prefill_paged",
    "serving_admit_paged", "hybrid_rollout"])
def test_jaxpr_entry_point(builder_name):
    from deepspeed_tpu.parallel.topology import reset_topology
    from deepspeed_tpu.tools.lint import entry_points, jaxpr_check
    reset_topology()
    try:
        ep = getattr(entry_points, builder_name)()
        result = jaxpr_check.check_entry_point(ep)
        assert result.ok, f"{ep.name}: {result.problems}"
    finally:
        reset_topology()


def test_jaxpr_check_flags_missing_donation():
    """The harness must actually detect an undonated large-buffer program."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.tools.lint.entry_points import EntryPoint
    from deepspeed_tpu.tools.lint.jaxpr_check import check_entry_point

    fn = jax.jit(lambda params: jax.tree.map(lambda p: p * 2, params))
    ep = EntryPoint("synthetic.undonated", fn,
                    ({"w": jnp.ones((4, 4))},), expect_donation=True)
    result = check_entry_point(ep)
    assert not result.ok and "donation" in result.problems[0]


def test_jaxpr_check_flags_callbacks():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deepspeed_tpu.tools.lint.entry_points import EntryPoint
    from deepspeed_tpu.tools.lint.jaxpr_check import check_entry_point

    def with_callback(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1

    ep = EntryPoint("synthetic.callback", jax.jit(with_callback),
                    (jnp.ones((4,)),), expect_donation=False)
    result = check_entry_point(ep)
    assert not result.ok and "callback" in result.problems[0]


# ------------------------------------------------------------------ #
# Runtime retrace counter (the dynamic half of TL006)
# ------------------------------------------------------------------ #
def test_serving_programs_compile_exactly_once_across_rounds():
    """Acceptance: the serving decode (and admit / admission-prefill)
    programs compile EXACTLY ONCE across >= 3 dispatch rounds with
    drifting host bookkeeping — round-varying request counts, prompt
    lengths/contents, eos ids, client ids, deadlines.  One extra
    signature anywhere here is tomorrow's 30 s mid-serve recompile."""
    from deepspeed_tpu.tools.lint.retrace_check import \
        measure_serving_retraces
    result = measure_serving_retraces(rounds=3)
    assert len(result["per_round"]) == 3
    for r, counts in enumerate(result["per_round"], 1):
        for program, n in counts.items():
            assert n == 1, \
                f"round {r}: serving {program} program compiled {n} " \
                f"signatures (retrace drift): {result}"
