"""Device-sync helpers.

Through the axon tunnel ``jax.block_until_ready`` can return before the
device work is actually done; the reliable fence is a DEPENDENT transfer —
fetching a scalar derived from the output forces completion.  Every timing
path (bench.py, op_bench, flops profiler) must use this one helper.
"""

import jax
import jax.numpy as jnp


def dependent_sync_scalar(x):
    """Block until ``x`` (array or pytree) is computed by fetching one
    scalar derived from it; returns that scalar as a float.

    The derivation happens ON DEVICE (a reduce over a unit slice), so the
    transfer is ~8 bytes regardless of the output size — never a full-leaf
    device-to-host copy inside a timed region."""
    leaf = jax.tree.leaves(x)[0]
    if getattr(leaf, "ndim", 0):
        leaf = jnp.sum(leaf[..., :1])
    return float(jax.device_get(leaf))
