"""Bounded retry with exponential backoff + jitter.

The policy for every transient failure class the fault subsystem absorbs:
checkpoint I/O (network filesystems flake), collective initialization
(peers of a resized slice arrive seconds apart), inference executable
loads (shared compile-cache stores are eventually consistent).  Jitter is
deterministic per (attempt, pid) so retries stay reproducible under test
while still decorrelating a herd of preempted workers in production.
"""

import os
import time

from deepspeed_tpu.utils.logging import logger

# the transient class worth retrying: OS-level I/O (IOError and
# TimeoutError are OSError aliases/subclasses; InjectedFault is an
# IOError).  Deliberately NOT Exception — a ValueError from corrupt
# state must fail fast, not loop.
TRANSIENT_IO_ERRORS = (OSError,)

# OSError subclasses that are PERMANENT (a typo'd path or a permissions
# problem does not heal with backoff) — retry_call re-raises these
# immediately, and the supervisor treats them as bugs, not faults
PERMANENT_OS_ERRORS = (FileNotFoundError, NotADirectoryError,
                       IsADirectoryError, PermissionError,
                       FileExistsError)


def is_transient(exc):
    """True when ``exc`` is in the retryable class: an OSError that is
    not one of the permanent-errno subclasses."""
    return isinstance(exc, TRANSIENT_IO_ERRORS) \
        and not isinstance(exc, PERMANENT_OS_ERRORS)


def backoff_delay(attempt, base=0.5, max_delay=30.0, jitter=0.25):
    """Delay before retry ``attempt`` (1-based): ``base * 2^(attempt-1)``
    capped at ``max_delay``, plus up to ``jitter`` fraction of that,
    derived deterministically from (attempt, pid)."""
    delay = min(float(max_delay), float(base) * (2.0 ** (attempt - 1)))
    if jitter:
        # cheap deterministic hash → [0, 1): reproducible, no RNG state
        seed = (attempt * 2654435761 + os.getpid() * 40503) & 0xFFFFFFFF
        delay += delay * float(jitter) * (seed / 2 ** 32)
    return delay


def retry_call(fn, *args, retries=3, base=0.5, max_delay=30.0, jitter=0.25,
               retry_on=TRANSIENT_IO_ERRORS, on_retry=None, label=None,
               sleep=time.sleep, **kwargs):
    """Call ``fn(*args, **kwargs)``; on a ``retry_on`` exception, back off
    and retry up to ``retries`` times (so at most ``retries + 1`` calls).
    ``on_retry(attempt, exc)`` is invoked before each backoff — the
    supervisor counts retries through it.  The final failure re-raises."""
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if retry_on is TRANSIENT_IO_ERRORS and not is_transient(e):
                raise        # permanent errno class: backoff cannot help
            attempt += 1
            if attempt > retries:
                raise
            delay = backoff_delay(attempt, base, max_delay, jitter)
            logger.warning(f"[fault] {label or getattr(fn, '__name__', fn)}"
                           f": transient failure ({type(e).__name__}: {e});"
                           f" retry {attempt}/{retries} in {delay:.2f}s")
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(delay)


def retry_policy_from_config(fault_config):
    """kwargs for :func:`retry_call` from a :class:`FaultConfig` (or None
    → a single attempt, no retries: seed behavior)."""
    if fault_config is None or not getattr(fault_config, "enabled", False):
        return dict(retries=0, base=0.0, jitter=0.0)
    return dict(retries=int(fault_config.max_retries),
                base=float(fault_config.backoff_base_secs),
                max_delay=float(fault_config.backoff_max_secs),
                jitter=float(fault_config.backoff_jitter))
