"""InferenceEngine — sharded, jitted generation.

TPU-native re-design of reference ``inference/engine.py:89``
(``InferenceEngine``): the reference swaps model layers for fused CUDA
kernels (``_apply_injection_policy :408``), slices weights for TP
(``module_inject/replace_module.py:31``), manages a KV-cache workspace
(``inference_context.h``), and captures CUDA graphs (``:526``).  Here:

* "kernel injection" is compilation: the whole decode step is one jitted XLA
  program (fused by construction), with Pallas flash attention for prefill
  where supported — there is no separate injected-module zoo to maintain;
* TP weight slicing is a sharding plan (AutoTP name rules,
  ``runtime/zero/partition.py``) applied as param ``NamedSharding``s — XLA
  inserts the per-layer collectives the reference codes by hand;
* the KV cache is a donated, statically-shaped [L, B, S_max, KVH*D] buffer
  (S-major, heads flattened — the decode kernel's full-lane-width DMA
  layout) updated in-place via donation (the workspace allocator
  equivalent);
* CUDA-graph capture/replay == jit compile/execute — every step after the
  first runs from the executable cache.

``generate`` implements greedy + temperature/top-k/top-p sampling with a
``lax.scan`` decode loop (one compiled program for the whole generation).
"""

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.parallel import topology as topo_mod
from deepspeed_tpu.runtime import compile_cache as compile_cache_mod
from deepspeed_tpu.runtime.zero.partition import build_sharding_plan
from deepspeed_tpu.runtime.config import ZeroConfig
from deepspeed_tpu.tools.lint.hotpath import hot_path
from deepspeed_tpu.utils.logging import log_dist, logger


class MemoryGuardExceeded(RuntimeError):
    """A generation program's compiled footprint exceeded
    ``memory_guard_fraction`` of device memory under ``strict_memory``.
    With the ``fault`` block's ``bucket_downshift`` on, ``generate``
    catches this and splits the batch instead of failing the request."""


class InferenceEngine:

    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None,
                 params=None):
        self.module = model
        self._config = config or DeepSpeedInferenceConfig()
        tp = self._config.tensor_parallel.tp_size
        self.topology = topo_mod.initialize_topology(tp=tp, ep=self._config.ep_size)
        self.mesh = self.topology.mesh
        from deepspeed_tpu.inference.config import normalize_dtype_str
        self.compute_dtype = {"bfloat16": jnp.bfloat16,
                              "float16": jnp.float16,
                              "float32": jnp.float32}[
                                  normalize_dtype_str(self._config.dtype)]
        self._quantizer = None
        if self._config.quant.enabled:
            from deepspeed_tpu.runtime.weight_quantizer import (
                WeightQuantization)
            self._quantizer = WeightQuantization(
                bits=self._config.quant.bits,
                group_size=self._config.quant.group_size,
                per_channel=self._config.quant.per_channel)
        self._params = None
        self._compiled = {}
        self._workspace = KVCacheWorkspace(model)
        self._aot = {}
        self._tags = {}          # id(jit fn) -> stable program tag
        # ids of jitted fns that must NOT touch the persistent caches —
        # neither the serialized-executable store nor the XLA disk cache.
        # The serving slot programs register here: reloading any of them
        # in a fresh process nondeterministically corrupts the slot
        # workspace or segfaults (see ServingEngine.__init__ /
        # compile_cache.suspended_persistent_cache); they recompile once
        # per process instead
        self._persist_opt_out = set()
        # persistent compile/executable cache (None = disabled: the AOT
        # path below still compiles per process, just without disk reuse)
        self._program_cache = compile_cache_mod.ProgramCache.from_config(
            self._config.compile_cache)
        self._rng = jax.random.key(0)
        # fault/degradation accounting (docs/fault_tolerance.md):
        # transient executable-load retries and strict_memory batch splits
        self.fault_stats = {"exec_load_retries": 0, "bucket_downshifts": 0}
        # signatures the memory guard refused under strict_memory —
        # repeat requests at that bucket skip straight to the downshift
        self._guard_refused = set()
        if params is not None:
            self.set_params(params)
        elif self._config.checkpoint is not None:
            self.load_checkpoint(self._config.checkpoint)

    # ------------------------------------------------------------------ #
    # Weights: the "injection"/TP-slicing step (reference engine.py:408)
    # ------------------------------------------------------------------ #
    def _plan_for(self, abstract):
        # inference: params sharded over tp only (no ZeRO axes), replicated
        # over dp — the AutoTP analog
        return build_sharding_plan(abstract, self.topology, ZeroConfig(stage=0))

    def set_params(self, params):
        if self._quantizer is not None:
            # INT8/INT4-at-rest (reference WeightQuantization at checkpoint
            # load): payload+scales live in HBM; dequant runs inside the
            # jitted programs, fused into each weight's consumer.  Unquantized
            # leaves (biases/norms) still cast to the compute dtype; all
            # leaves are placed replicated (quantized TP is unsupported).
            if self.topology.tp > 1:
                logger.warning("weight quantization with tp>1: quantized "
                               "payloads are replicated, not TP-sharded")
            cast = self.compute_dtype
            rep = NamedSharding(self.mesh, P())

            def quantize_and_cast(t):
                t = self._quantizer.quantize_tree(t)
                from deepspeed_tpu.runtime.weight_quantizer import _is_qw
                return jax.tree.map(
                    lambda p: p if _is_qw(p) else (
                        p.astype(cast)
                        if jnp.issubdtype(p.dtype, jnp.floating) else p),
                    t, is_leaf=_is_qw)
            self._params = jax.jit(quantize_and_cast,
                                   out_shardings=rep)(params)
            n = sum(int(np.prod(l.shape))
                    for l in jax.tree.leaves(self._params))
            log_dist(f"inference params quantized to "
                     f"int{self._quantizer.bits}: {n/1e6:.1f}M values",
                     ranks=[0])
            return
        abstract = jax.eval_shape(lambda t: t, params)
        self._plan = self._plan_for(abstract)
        cast = self.compute_dtype
        put = jax.jit(lambda t: jax.tree.map(
            lambda p: p.astype(cast)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, t),
            out_shardings=self._plan.param_shardings)
        self._params = put(params)
        n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(self._params))
        log_dist(f"inference params placed: {n/1e6:.1f}M, tp={self.topology.tp}, "
                 f"dtype={cast.__name__}", ranks=[0])

    def _deq(self, params):
        """Identity for float params; in-trace dequantization when weight
        quantization is on (called inside every compiled program)."""
        if self._quantizer is None:
            return params
        return self._quantizer.dequantize_tree(params, self.compute_dtype)

    def init_params(self, example_ids=None, seed=0):
        """Random init (testing / benchmarking without a checkpoint)."""
        if example_ids is None:
            example_ids = jnp.zeros((1, 8), jnp.int32)
        params = self.module.init(jax.random.key(seed), {"input_ids": example_ids})
        self.set_params(params)

    def load_checkpoint(self, path, tag=None):
        """Directory → engine (Orbax) checkpoint; single file → a
        ``save_16bit_model`` export (safetensors / torch state dict with
        flax-named keys).  Files needing full (code-executing) unpickling —
        legacy pickled pytrees, torch files with non-allowlisted objects —
        only load with ``DSTPU_ALLOW_PICKLE_CHECKPOINTS=1``.  HF-named
        exports (``hf_policy=...``) go through ``module_inject`` instead."""
        import os, pickle
        if os.path.isfile(path):
            if path.endswith(".safetensors"):
                from safetensors.numpy import load_file
                self.set_params(_unflatten_flax_paths(load_file(path)))
                return
            sd = None
            try:
                # weights_only=True: never execute pickled code from an
                # untrusted checkpoint during format probing
                import torch
                sd = torch.load(path, map_location="cpu", weights_only=True)
            except (pickle.UnpicklingError, RuntimeError, ImportError):
                pass                     # not a weights-only-loadable file
            if sd is not None:
                self.set_params(_unflatten_flax_paths(
                    {k: (v.float().numpy() if hasattr(v, "numpy") else v)
                     for k, v in sd.items()}))
                return
            # full unpickling executes arbitrary code — only for files the
            # operator explicitly vouches for
            if os.environ.get("DSTPU_ALLOW_PICKLE_CHECKPOINTS") != "1":
                raise ValueError(
                    f"{path}: not loadable with weights_only unpickling; "
                    "full pickle execution is disabled for untrusted files. "
                    "Set DSTPU_ALLOW_PICKLE_CHECKPOINTS=1 to load a legacy "
                    "pickled pytree (or a torch file with non-allowlisted "
                    "objects) you trust.")
            try:                         # torch-zip file with custom objects
                import torch
                sd = torch.load(path, map_location="cpu", weights_only=False)
                self.set_params(_unflatten_flax_paths(
                    {k: (v.float().numpy() if hasattr(v, "numpy") else v)
                     for k, v in sd.items()}))
                return
            except (pickle.UnpicklingError, RuntimeError, ImportError,
                    ValueError):
                pass                     # bare pickle stream → legacy path
            with open(path, "rb") as f:
                self.set_params(pickle.load(f))
            return
        from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import \
            OrbaxCheckpointEngine
        eng = OrbaxCheckpointEngine()
        if tag is None and os.path.exists(os.path.join(path, "latest")):
            with open(os.path.join(path, "latest")) as f:
                tag = f.read().strip()
        state_path = os.path.join(path, str(tag), "state") if tag else path
        arrays, _ = eng.load(state_path)
        self.set_params(arrays["module"] if isinstance(arrays, dict)
                        and "module" in arrays else arrays)

    @property
    def params(self):
        return self._params

    # ------------------------------------------------------------------ #
    # Forward / generation
    # ------------------------------------------------------------------ #
    def forward(self, input_ids, attention_mask=None, **kwargs):
        """Full logits (reference engine.forward :586); ``attention_mask``
        masks padded positions."""
        assert self._params is not None, "no parameters: set_params/init_params first"
        if kwargs:
            raise TypeError(f"unsupported forward arguments: {sorted(kwargs)}")
        key = "fwd" if attention_mask is None else "fwd_masked"
        if key not in self._compiled:
            # decoder families expose a ``logits`` method; encoder modules
            # (BERT) return logits from __call__ directly
            has_logits = hasattr(type(self.module), "logits")
            if attention_mask is None:
                fwd = (lambda p, ids: self.module.apply(
                    self._deq(p), ids, method=type(self.module).logits)) \
                    if has_logits \
                    else (lambda p, ids: self.module.apply(
                        self._deq(p), {"input_ids": ids}))
                self._compiled[key] = jax.jit(fwd)
            else:
                fwd = (lambda p, ids, m: self.module.apply(
                    self._deq(p), ids, m, method=type(self.module).logits)) \
                    if has_logits else \
                    (lambda p, ids, m: self.module.apply(
                        self._deq(p), {"input_ids": ids, "attention_mask": m}))
                self._compiled[key] = jax.jit(fwd)
        args = (self._params, jnp.asarray(input_ids))
        if attention_mask is not None:
            args += (jnp.asarray(attention_mask),)
        return self._compiled[key](*args)

    __call__ = forward

    def _get_generate(self, prompt_len, max_new_tokens, do_sample, temperature,
                      top_k, top_p, with_mask=False, prefill_chunk=None,
                      external_prefill=False):
        # the loop form (early-exit while vs scan) rides the key: it is
        # part of the compiled program's identity, and the executable
        # STORE key derives from this tuple — without it a warm cache
        # would silently reload the other form and decode_early_exit
        # would be a no-op exactly on warm starts
        key = ("gen", prompt_len, max_new_tokens, do_sample, temperature,
               top_k, top_p, with_mask, prefill_chunk, external_prefill,
               self._config.decode_early_exit)
        if key in self._compiled:
            return self._compiled[key]
        # carry the quantized tree through the scan only when its dequant
        # materializes full weights (see WeightQuantization
        # .materializing_dequant for the why of both directions)
        self._compiled[key] = make_generate_fn(
            self.module, self.compute_dtype, prompt_len, max_new_tokens,
            do_sample, temperature, top_k, top_p,
            param_transform=self._deq, with_mask=with_mask,
            carry_params=self._quantizer is not None
            and self._quantizer.materializing_dequant,
            prefill_chunk=prefill_chunk, external_prefill=external_prefill,
            early_exit=self._config.decode_early_exit)
        self._tags[id(self._compiled[key])] = key
        return self._compiled[key]

    def _prefill_chunk_for(self, batch_size, prompt_len):
        cfg = self._config.prefill_chunk_size
        if cfg in (None, 0, "none", "off"):
            return None
        if cfg == "auto":
            return default_prefill_chunk(batch_size, prompt_len)
        # user-specified chunk: align like the fused-write checks do —
        # round UP to a multiple of 8 (Mosaic's sublane granularity; the
        # chunk kernel's q block and the cache-pad arithmetic both assume
        # 8-row alignment) with a floor of 8, and cap at 512 (the kernel's
        # VMEM accumulator bound; a larger chunk would silently fall to the
        # dense attend path whose [B,H,S,S_max] fp32 transient this
        # chunking exists to avoid)
        c = min(512, max(8, -(-int(cfg) // 8) * 8))
        if c != int(cfg):
            from deepspeed_tpu.utils.logging import warning_once
            warning_once(f"prefill_chunk_size={cfg} adjusted to {c} "
                         f"(multiple of 8, min 8, max 512)")
        return c if c < prompt_len else None

    def prefill_plan(self, batch_size, prompt_len, paged=False):
        """Which prefill pipeline ``generate(batch, prompt)`` will take,
        as ``(mode, chunk, reason)`` — ``("chunked", C, ...)`` for the
        split per-chunk path, ``("one_pass", None, ...)`` otherwise.

        Observability for long-prompt serving points (the bench records
        it): the ``"auto"`` chunk policy declines chunking both for small
        working sets AND when the Pallas chunk kernel is unavailable —
        the latter silently drops a long prompt onto the one-pass path,
        whose dense-attention fallback materializes ``[B, H, S, S]``
        fp32 scores (~32 GB at bs16 x 4k) and OOMs where the chunked
        pipeline runs fine.  Pin ``prefill_chunk_size`` to an int to
        force the chunked pipeline regardless of the kernel gate (each
        chunk then attends through ``cached_attention``'s paths, with a
        dense per-chunk fallback of only ``[B, H, C, S_max]``).

        Every reason carries a ``[kernels: ...]`` tail naming the
        attention-registry modes the run will actually dispatch through
        (``pallas_chunked_prefill`` / ``pallas_paged_decode`` /
        ``pallas_decode`` / ``reference_fallback`` — see
        ``ops/transformer/registry.py``), so bench records attribute
        which kernel path ran, not just which pipeline was planned.
        ``paged=True`` asks for the paged-serving attribution (block
        tables + page-pool kernels) instead of the monolithic one."""
        from deepspeed_tpu.ops.transformer.registry import kernel_modes
        pe = getattr(getattr(self.module, "config", None),
                     "position_embedding", None)
        modes = kernel_modes(paged=bool(paged), has_bias=(pe == "alibi"))
        tail = (" [kernels: prefill=%s decode=%s]"
                % (modes["prefill_chunk"], modes["decode"]))
        cfg = self._config.prefill_chunk_size
        chunk = self._prefill_chunk_for(int(batch_size), int(prompt_len))
        if chunk is not None and chunk < prompt_len:
            why = "explicit prefill_chunk_size" \
                if cfg not in ("auto",) else "auto policy accepted"
            return "chunked", chunk, why + tail
        if cfg in (None, 0, "none", "off"):
            return "one_pass", None, "chunking disabled by config" + tail
        if cfg == "auto":
            from deepspeed_tpu.ops.transformer.flash_attention import \
                pallas_supported
            if not pallas_supported():
                return ("one_pass", None,
                        "auto policy declined: Pallas chunk kernel "
                        "unavailable on this backend" + tail)
            return ("one_pass", None,
                    "auto policy declined: working set under "
                    "DSTPU_PREFILL_TOKEN_BUDGET" + tail)
        return "one_pass", None, "chunk >= prompt_len" + tail

    @hot_path("inference.generate")
    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=-1, seed=None,
                 attention_mask=None):
        """Autoregressive generation: returns [B, prompt_len+max_new_tokens]
        — prompt followed by new tokens, the HF ``generate`` contract
        (reference ``engine._generate :614``).

        ``attention_mask`` supports RIGHT-padded prompts (1 = real token):
        each row continues from its own prompt length; generated tokens
        occupy the trailing ``max_new_tokens`` columns of the result while
        the prompt columns (including pads) stay in place.
        """
        assert self._params is not None, "no parameters: set_params/init_params first"
        input_ids = jnp.asarray(input_ids)
        if attention_mask is not None:
            require_right_padded(attention_mask)
        if seed is not None:
            self._rng = jax.random.key(seed)
        self._rng, rng = jax.random.split(self._rng)
        try:
            return self._generate_once(
                input_ids, max_new_tokens, do_sample, temperature, top_k,
                top_p, eos_token_id, rng, attention_mask)
        except MemoryGuardExceeded:
            fcfg = getattr(self._config, "fault", None)
            B = input_ids.shape[0]
            if fcfg is None or not (fcfg.enabled and fcfg.bucket_downshift) \
                    or B <= 1:
                raise
            # graceful degradation (fault.bucket_downshift): the request's
            # batch bucket compiles over the memory guard — serve it as two
            # sequential half-batches instead of failing.  Latency roughly
            # doubles for this request; sampling streams differ from the
            # unsplit run (each half draws its own keys).  Recursion
            # bottoms out at batch 1, where the guard verdict is final.
            half = B // 2
            self.fault_stats["bucket_downshifts"] += 1
            logger.warning(  # tpu-lint: disable=TL003 -- generate() is host-side dispatch (the jitted programs live in _get_generate); this handler runs after a compile refusal, never in-trace
                f"strict_memory: generation batch {B} exceeds the memory "
                f"guard — bucket-downshifting to {half}+{B - half} "
                "sequential half-batches (fault.bucket_downshift)")
            kw = dict(max_new_tokens=max_new_tokens, do_sample=do_sample,
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      eos_token_id=eos_token_id)
            mask = attention_mask
            lo = self.generate(input_ids[:half], attention_mask=None
                               if mask is None else mask[:half], **kw)
            hi = self.generate(input_ids[half:], attention_mask=None
                               if mask is None else mask[half:], **kw)
            return jnp.concatenate([lo, hi], axis=0)

    def _generate_once(self, input_ids, max_new_tokens, do_sample,
                       temperature, top_k, top_p, eos_token_id, rng,
                       attention_mask):
        B, P = input_ids.shape
        chunk = self._prefill_chunk_for(B, P)
        n_chunks = -(-P // chunk) if chunk else 1
        if n_chunks > 1:
            # chunked prefill runs as REPEATED CALLS of one per-chunk
            # executable instead of an in-program scan — the scan's
            # while-loop carries a partial extra copy of the cache that
            # XLA will not alias away (measured ~2.8 GB at a 4k cache;
            # the same copy at bs128's 5.1 GB cache OOM'd the 2-chunk
            # in-program form), and per-call the donated cache aliases
            # straight through, so peak memory is max(chunk program,
            # decode program), not their union.  Costs one dispatch per
            # chunk (~0.1 s each on the tunnel).
            return self._generate_split(
                input_ids, int(max_new_tokens), bool(do_sample),
                float(temperature), int(top_k), float(top_p),
                eos_token_id, rng, attention_mask, chunk)
        fn = self._get_generate(P, int(max_new_tokens),
                                bool(do_sample), float(temperature), int(top_k),
                                float(top_p),
                                with_mask=attention_mask is not None,
                                prefill_chunk=chunk)
        cache = self._workspace.take(
            B, required_cache_len(P, int(max_new_tokens), chunk),
            self.compute_dtype)
        try:
            args = (self._params, cache, input_ids, rng,
                    jnp.asarray(eos_token_id))
            if attention_mask is not None:
                args += (jnp.asarray(attention_mask),)
            out, cache = self._run_guarded(fn, args)
        finally:
            # on failure the (possibly donated-and-dead) buffer still goes
            # back; take() checks liveness before reuse
            self._workspace.give_back(cache)
        return out

    def _make_chunk_fn(self):
        """A fresh (unmemoized) per-chunk prefill program instance — the
        serving engine uses its own instance so its persist-opt-out never
        touches the engine-shared one (and a store-reloaded shared
        executable can never serve admission prefill)."""
        module, deq = self.module, self._deq

        @hot_path("inference.prefill_chunk")
        def chunk_step(params, cache, chunk_ids, start, logits_at):
            return module.apply(deq(params), chunk_ids, cache, start,
                                method=type(module).decode,
                                logits_at=logits_at)
        return jax.jit(chunk_step, donate_argnums=(1,))

    def _get_chunk_fn(self, C, B):
        """The per-chunk prefill executable of the split-prefill path (one
        donated-cache program replayed for every chunk)."""
        ck = ("chunkfill", C, B)
        if ck not in self._compiled:
            self._compiled[ck] = self._make_chunk_fn()
            self._tags[id(self._compiled[ck])] = ck
        return self._compiled[ck]

    def _generate_split(self, input_ids, max_new_tokens, do_sample,
                        temperature, top_k, top_p, eos_token_id, rng,
                        attention_mask, chunk):
        """Split-prefill generation: one donated-cache per-chunk prefill
        executable (chunk start and per-row logits positions are traced
        ARGUMENTS, so all chunks replay the same program) followed by the
        decode-only program.  See generate() for when this path wins."""
        B, P = input_ids.shape
        C = int(chunk)
        n = -(-P // C)
        cache = self._workspace.take(
            B, required_cache_len(P, max_new_tokens, C), self.compute_dtype)
        chunk_fn = self._get_chunk_fn(C, B)
        ids_pad = jnp.pad(input_ids, ((0, 0), (0, n * C - P)))
        if attention_mask is not None:
            last = jnp.sum(attention_mask.astype(jnp.int32), axis=1) - 1
        else:
            last = jnp.full((B,), P - 1, jnp.int32)
        try:
            sel = None
            for ci in range(n):
                local = jnp.clip(last - ci * C, 0, C - 1)
                logits, cache = self._run_guarded(
                    chunk_fn,
                    (self._params, cache, ids_pad[:, ci * C:(ci + 1) * C],
                     jnp.asarray(ci * C, jnp.int32), local))
                in_chunk = ((last // C) == ci)[:, None, None]
                sel = logits if sel is None \
                    else jnp.where(in_chunk, logits, sel)
            fn = self._get_generate(P, max_new_tokens, do_sample, temperature,
                                    top_k, top_p,
                                    with_mask=attention_mask is not None,
                                    external_prefill=True)
            args = (self._params, cache, input_ids, rng,
                    jnp.asarray(eos_token_id))
            args += ((jnp.asarray(attention_mask),)
                     if attention_mask is not None else (None,))
            args += (sel,)
            out, cache = self._run_guarded(fn, args)
        finally:
            self._workspace.give_back(cache)
        return out

    def release_workspace(self):
        """Free the persistent KV-cache workspace buffer (reference
        ``release_workspace``, ``inference_context.h``)."""
        self._workspace.release()

    def serve(self, monitor=None, draft_module=None, draft_params=None,
              **overrides):
        """A continuous-batching :class:`~deepspeed_tpu.inference.serving.
        ServingEngine` over this engine (``docs/serving.md``): slot-based
        in-flight batching — ``submit()`` requests, ``drain()`` results;
        new requests join freed KV slots between decode iterations instead
        of waiting for a whole ``generate()`` batch to finish.  Knobs come
        from the ``serving`` config block, overridable per call
        (``engine.serve(num_slots=16)``); ``serving.paged=True`` swaps
        the per-slot monolithic KV lanes for a block-table page pool
        with copy-on-write prefix sharing (``engine.serve(paged=True,
        page_size=64)``); ``serving.speculative=True`` turns on
        draft-assisted speculative decoding — pass the draft model as
        ``engine.serve(speculative=True, draft_module=...,
        draft_params=...)`` or set ``serving.spec_draft_model``
        (``docs/serving.md`` "Speculative decoding")."""
        from deepspeed_tpu.inference.serving.engine import ServingEngine
        return ServingEngine(self, monitor=monitor,
                             draft_module=draft_module,
                             draft_params=draft_params, **overrides)

    def _run_guarded(self, fn, args):
        """Compile-and-check-then-execute: the generation program is
        AOT-compiled ONCE per argument signature (same executable the jit
        path would build — donation included) and its
        ``memory_analysis()`` is checked against ``memory_guard_fraction``
        of device memory before the first execution.  Near the limit XLA
        silently switches to staging buffers and decode collapses ~8x
        (docs/performance.md, "measure the cliff"); the reference's
        workspace allocator bounds-checks the same way
        (``inference_context.h:24-87``).  With the ``compile_cache`` block
        enabled, the executable is reloaded from / persisted to the
        on-disk store (runtime/compile_cache.py), so a warm process skips
        XLA compilation entirely."""
        sig = (id(fn),) + compile_cache_mod.abstract_signature(args)
        if sig in self._guard_refused:
            # this signature's program was already compiled once and
            # refused by the memory guard — refusing from memory spares
            # every subsequent over-budget request the doomed multi-second
            # XLA compile before its bucket downshift
            raise MemoryGuardExceeded(
                f"strict_memory: generation program for this signature was "
                f"previously refused by the memory guard (batch "
                f"{args[2].shape[0] if len(args) > 2 and hasattr(args[2], 'shape') else '?'})")
        compiled = self._aot.get(sig)
        if compiled is None:
            try:
                compiled, _, _ = self._aot_compile_resilient(fn, args)
            except MemoryGuardExceeded:
                self._guard_refused.add(sig)
                raise
            if compiled is None:
                # AOT path is an optimization + guardrail; never let it
                # block generation (fall back to the plain jit call).
                # Opt-out programs must stay cache-detached here too — a
                # fallback jit compile with the XLA disk cache attached
                # could reload exactly the cross-process executable the
                # opt-out exists to avoid
                self._aot[sig] = fn
                if id(fn) in self._persist_opt_out:
                    with compile_cache_mod.suspended_persistent_cache():
                        return fn(*args)
                return fn(*args)
            self._aot[sig] = compiled
        return compiled(*args)

    def _aot_compile_resilient(self, fn, args):
        """``_aot_compile`` under the fault block's bounded
        retry/backoff: a transient I/O failure while loading/persisting
        an executable (shared stores on network filesystems flake)
        retries ``fault.max_retries`` times; exhaustion degrades to the
        plain jit path instead of failing the request.  A
        :class:`MemoryGuardExceeded` refusal is NOT transient and
        propagates immediately."""
        fcfg = getattr(self._config, "fault", None)
        if fcfg is None or not fcfg.enabled or fcfg.max_retries <= 0:
            return self._aot_compile(fn, args)
        from deepspeed_tpu.runtime.fault.retry import (
            retry_call, retry_policy_from_config, TRANSIENT_IO_ERRORS)

        def count(_attempt, _exc):
            self.fault_stats["exec_load_retries"] += 1

        try:
            return retry_call(self._aot_compile, fn, args,
                              label="inference executable load",
                              on_retry=count,
                              **retry_policy_from_config(fcfg))
        except TRANSIENT_IO_ERRORS as e:
            logger.warning(f"executable load still failing after "
                           f"{fcfg.max_retries} retries "
                           f"({type(e).__name__}: {e}) — degrading to the "
                           "plain jit path for this program")
            return None, 0.0, False

    def _cache_context(self):
        """Engine facts that change compiled programs but not arg shapes —
        part of every executable-store key."""
        q = self._config.quant
        return (repr(getattr(self.module, "config",
                             type(self.module).__name__)),
                self.compute_dtype.__name__,
                tuple(sorted(dict(self.mesh.shape).items())),
                (q.enabled, q.bits, q.group_size, q.per_channel))

    def _aot_compile(self, fn, args):
        """Lower+compile ``fn`` for ``args`` (through the executable store
        when enabled), memory-guard the result.  Returns ``(compiled,
        compile_seconds, store_hit)`` — compiled is None on failure.
        ``args`` may be abstract (``ShapeDtypeStruct``) — warmup path."""
        from deepspeed_tpu.runtime.fault import inject as fault_inject
        fault_inject.fire("infer.executable_load")
        tag = self._tags.get(id(fn))
        if id(fn) in self._persist_opt_out:
            # fresh compile with BOTH persistent layers detached (see
            # _persist_opt_out above) — once per process per signature
            with compile_cache_mod.suspended_persistent_cache():
                compiled, dt, hit = compile_cache_mod.aot_compile_with_store(
                    None, f"infer:{tag[0] if tag else 'untagged'}",
                    (), fn, args)
        else:
            compiled, dt, hit = compile_cache_mod.aot_compile_with_store(
                self._program_cache if tag is not None else None,
                f"infer:{tag[0] if tag else 'untagged'}",
                (tag, compile_cache_mod.abstract_signature(args),
                 self._cache_context()),
                fn, args)
        if compiled is None:
            return None, 0.0, False
        # guard BEFORE caching: under strict_memory every retry with
        # the same over-budget signature must refuse again, not find
        # a cached executable and run unguarded
        self._guard_memory(compiled)
        return compiled, dt, hit

    def _guard_memory(self, compiled):
        import os
        limit = int(os.environ.get("DSTPU_HBM_BYTES_OVERRIDE", "0"))
        if not limit:
            from deepspeed_tpu.profiling.flops_profiler.profiler import \
                device_hbm_bytes
            limit = device_hbm_bytes()
        if not limit:
            return                        # no budget info (CPU backend)
        try:
            ma = compiled.memory_analysis()
            need = ma.temp_size_in_bytes + ma.argument_size_in_bytes
        except Exception as e:            # introspection is best-effort
            logger.debug(f"memory guardrail skipped: {e}")
            return
        frac = self._config.memory_guard_fraction
        if need <= frac * limit:
            return
        msg = (f"generation program needs {need / 1e9:.1f} GB "
               f"(args {ma.argument_size_in_bytes / 1e9:.1f} + temps "
               f"{ma.temp_size_in_bytes / 1e9:.1f}) — above "
               f"{frac:.0%} of device memory ({limit / 1e9:.1f} GB). "
               f"XLA enters staging mode near this line and decode "
               f"throughput collapses nonlinearly; use a smaller batch or "
               f"shorter max cache (docs/performance.md, 'measure the "
               f"cliff').")
        if self._config.strict_memory:
            raise MemoryGuardExceeded(f"strict_memory: {msg}")
        logger.warning(msg)

    # ------------------------------------------------------------------ #
    # Warmup: pay all compiles up front (and once per machine, with the
    # compile_cache block enabled)
    # ------------------------------------------------------------------ #
    def warmup(self, prompt_len, max_new_tokens, batch_sizes=(1,),
               do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
               with_mask=False, monitor=None):
        """AOT-compile every program a ``generate(prompt_len,
        max_new_tokens)`` call will need, for each batch-size bucket —
        including the split-prefill pair (per-chunk executable + decode-only
        program) when the chunk policy routes that batch there.  Nothing
        executes: arguments are abstract, so no HBM is touched beyond the
        already-placed params.

        Returns ``{program_name: compile_seconds}`` (0.0 = warm already /
        executable-store hit).  ``monitor``: an optional
        ``MonitorMaster``-like object; each program's compile time is
        reported as a ``Compile/<name>_secs`` event."""
        assert self._params is not None, \
            "no parameters: set_params/init_params first"
        report = {}
        for B in batch_sizes:
            report.update(self._warmup_one(
                int(B), int(prompt_len), int(max_new_tokens),
                bool(do_sample), float(temperature), int(top_k),
                float(top_p), bool(with_mask)))
        for name, dt in report.items():
            log_dist(f"warmup[{name}]: "
                     + ("cached" if dt == 0.0 else f"{dt:.1f}s"), ranks=[0])
        if monitor is not None and getattr(monitor, "enabled", True):
            monitor.write_events([(f"Compile/{name}_secs", dt, 0)
                                  for name, dt in report.items()])
        return report

    precompile = warmup

    def _warmup_one(self, B, P, new, do_sample, temperature, top_k, top_p,
                    with_mask):
        chunk = self._prefill_chunk_for(B, P)
        n_chunks = -(-P // chunk) if chunk else 1
        cache = jax.eval_shape(
            lambda: self.module.init_cache(
                B, required_cache_len(P, new, chunk), dtype=self.compute_dtype))
        ids = jax.ShapeDtypeStruct((B, P), jnp.int32)
        rng = jax.eval_shape(lambda: jax.random.key(0))
        # concrete, WEAK-typed int32 — exactly what generate() builds from
        # the default ``eos_token_id=-1`` (a ShapeDtypeStruct would be
        # strong-typed and the warmed executable would refuse the call)
        eos = jnp.asarray(-1)
        mask = jax.ShapeDtypeStruct((B, P), jnp.int32) if with_mask else None

        def warm(fn, args, name):
            sig = (id(fn),) + compile_cache_mod.abstract_signature(args)
            if sig in self._aot:
                return {name: 0.0}
            compiled, dt, hit = self._aot_compile(fn, args)
            if compiled is None:
                logger.warning(f"warmup: {name} failed to AOT-compile — "
                               f"it will compile on first use instead")
                return {}
            self._aot[sig] = compiled
            return {name: 0.0 if hit else dt}

        report = {}
        if n_chunks > 1:
            C = int(chunk)
            chunk_fn = self._get_chunk_fn(C, B)
            cargs = (self._params, cache, jax.ShapeDtypeStruct((B, C), jnp.int32),
                     jax.ShapeDtypeStruct((), jnp.int32),
                     jax.ShapeDtypeStruct((B,), jnp.int32))
            report.update(warm(chunk_fn, cargs, f"prefill_chunk:b{B}c{C}"))
            # the decode-only program consumes the chunk program's
            # last-position logits — eval_shape gives their exact
            # shape/dtype (and the cache's post-donation abstract value)
            logits, cache = jax.eval_shape(chunk_fn, *cargs)
            fn = self._get_generate(P, new, do_sample, temperature, top_k,
                                    top_p, with_mask=with_mask,
                                    external_prefill=True)
            args = (self._params, cache, ids, rng, eos, mask, logits)
            report.update(warm(fn, args, f"decode:b{B}p{P}n{new}"))
        else:
            fn = self._get_generate(P, new, do_sample, temperature, top_k,
                                    top_p, with_mask=with_mask,
                                    prefill_chunk=chunk)
            args = (self._params, cache, ids, rng, eos)
            if with_mask:
                args += (mask,)
            report.update(warm(fn, args, f"generate:b{B}p{P}n{new}"))
        return report


def _unflatten_flax_paths(flat):
    """{'a/b/c': array} → nested variables dict, re-rooted under 'params'
    when the export stripped that collection prefix (save_16bit_model
    does).  HF-named keys (dots, no flax structure) raise with guidance."""
    if any("." in k and "/" not in k for k in flat):
        raise ValueError(
            "this file carries HF-named keys (hf_policy export); load it "
            "through module_inject's policy convert + _materialize instead")
    from deepspeed_tpu.compression.helper import unflatten_params
    return unflatten_params(
        {(k if k.startswith("params/") else f"params/{k}"): v
         for k, v in flat.items()})


def require_right_padded(attention_mask):
    """Validate a generation attention_mask at the API boundary: every row
    must be RIGHT-padded (1s then 0s) and non-empty — HF tokenizers default
    decoder-only generation to LEFT padding, which would silently index
    mid-prompt logits, and an all-pad row would condition on pad logits."""
    m = np.asarray(attention_mask)  # tpu-lint: disable=TL001 -- API-boundary validation of the caller's (host) mask, once per generate
    if not (np.diff(m.astype(np.int8), axis=1) <= 0).all():
        raise ValueError(
            "attention_mask must be RIGHT-padded (1s then 0s per row); "
            "re-tokenize with padding_side='right'")
    if (m.sum(axis=1) == 0).any():
        raise ValueError("attention_mask has an all-padding row (empty "
                         "prompt) — drop it before generate()")


class KVCacheWorkspace:
    """Engine-owned persistent KV-cache buffer — the TPU analog of the
    reference's reusable inference workspace
    (``csrc/transformer/inference/includes/inference_context.h:24-87``:
    allocate once, decode into it in place, reallocate only when the
    requested shape changes).  The buffer is DONATED into each generation
    program and reclaimed from its output, so the decode scan updates the
    cache in place instead of entry-copying + double-buffering a fresh
    zeros cache per call (measured ~2x-the-cache compiled temps before,
    ~1x after — see docs/performance.md).

    Stale contents are harmless by construction: every attention path masks
    KV positions beyond each row's live length, so a reused buffer's old
    tokens are never read.
    """

    def __init__(self, module):
        self._module = module
        self._key = None
        self._cache = None

    def take(self, batch_size, max_len, dtype):
        """Hand out the workspace for a ``(B, max_len)`` generation; the
        caller must ``give_back`` the program's output cache (the donated
        input buffer is dead after the call)."""
        key = (int(batch_size), int(max_len), jnp.dtype(dtype).name)
        cache, self._cache = self._cache, None
        if cache is not None and any(
                getattr(l, "is_deleted", lambda: False)()
                for l in jax.tree.leaves(cache)):
            # a generation program that failed AFTER donation leaves the
            # given-back buffers dead — reallocate instead of handing a
            # deleted array to the next program
            cache = None
        if cache is None or self._key != key:
            cache = None                    # drop the old buffer first
            self._key = key
            cache = self._module.init_cache(batch_size, max_len, dtype=dtype)
        return cache

    def give_back(self, cache):
        self._cache = cache

    def release(self):
        """Free the workspace buffer (reference ``release_workspace``)."""
        self._cache = None
        self._key = None


def auto_prefill_chunk(batch_size, prompt_len, token_budget=None):
    """Pick the chunked-prefill chunk size (or None for one-pass prefill):
    chunking pays when the prefill working set ``B x P`` is large enough
    that per-layer transients crowd the KV cache out of HBM (measured
    cliff: bs128 x 256 / bs16 x 4k OOM one-pass but run chunked).  The
    chunk targets ``B x C <= token_budget`` (env
    ``DSTPU_PREFILL_TOKEN_BUDGET``, default 16384 tokens), floored at 128
    and capped at 512 (the kernel's VMEM accumulator bound)."""
    import os
    budget = int(token_budget
                 or os.environ.get("DSTPU_PREFILL_TOKEN_BUDGET", "16384"))
    if batch_size * prompt_len <= budget:
        return None
    c = 512
    while c > 128 and batch_size * c > budget:
        c //= 2
    return c if c < prompt_len else None


def default_prefill_chunk(batch_size, prompt_len):
    """The shared chunk policy (serving + hybrid rollouts): auto chunk
    sizing gated on kernel availability."""
    from deepspeed_tpu.ops.transformer.flash_attention import pallas_supported
    if not pallas_supported():
        return None                      # chunk attention needs the kernel
    return auto_prefill_chunk(batch_size, prompt_len)


def required_cache_len(prompt_len, max_new_tokens, prefill_chunk):
    """KV-workspace length for a generation: chunked prefill right-pads
    the prompt to a chunk multiple and WRITES those pad positions, so the
    cache must cover them — a shorter cache would let XLA clamp the last
    chunk's dynamic_update_slice start and silently overwrite real prompt
    K/V.  (Pad K/V beyond the live region are never read, and decode
    overwrites position ``prompt_len + t`` before reading it.)"""
    base = prompt_len + max_new_tokens
    if prefill_chunk and prefill_chunk < prompt_len:
        padded = -(-prompt_len // prefill_chunk) * prefill_chunk
        base = max(base, padded)
    # multiple of 8: the fused decode kernel's write-stripe outputs are
    # 8-sublane-aligned blocks (positions beyond prompt+new are never
    # attended — length-masked like any unwritten tail)
    return -(-base // 8) * 8


def build_sample_fn(do_sample, temperature, top_k, top_p):
    """The one sampling rule every decode path shares (whole-batch
    generation, hybrid rollouts, the serving decode step): greedy argmax,
    or temperature / top-k / top-p sampling over fp32 logits.  Shared so
    the serving engine's per-slot decode samples BITWISE like
    ``generate()`` does — the scheduler-correctness contract."""

    def sample_fn(logits, rng):
        logits = logits.astype(jnp.float32)
        if not do_sample:
            return jnp.argmax(logits, axis=-1)
        if temperature != 1.0:
            logits = logits / jnp.maximum(temperature, 1e-6)
        if top_k > 0:
            kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
            logits = jnp.where(logits < kth, -1e30, logits)
        if 0.0 < top_p < 1.0:
            sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
            cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
            logits = jnp.where(logits < cutoff, -1e30, logits)
        return jax.random.categorical(rng, logits, axis=-1)

    return sample_fn


def make_generate_fn(module, compute_dtype, prompt_len, max_new_tokens,
                     do_sample, temperature, top_k, top_p,
                     param_transform=None, with_mask=False,
                     carry_params=None, prefill_chunk=None,
                     external_prefill=False, early_exit=True):
    """Build the jitted generation program: one-pass prefill + lax.scan
    decode loop with greedy / temperature / top-k / top-p sampling.  Shared
    by ``InferenceEngine`` and ``DeepSpeedHybridEngine``.

    ``with_mask=True`` supports RIGHT-padded prompts: ``fn`` takes an
    ``attention_mask`` [B, prompt] and each row continues from its own
    prompt length — generated tokens overwrite the pad slots in the KV
    cache (the live region stays contiguous, which is what the Pallas
    decode kernel's per-row length mask expects), while the returned array
    keeps the HF layout ``[prompt columns..., generated columns...]``.

    The KV cache is an explicit, DONATED argument (allocate it with
    ``module.init_cache``/``KVCacheWorkspace``): the donated buffer aliases
    the output cache, so prefill writes and the decode scan's per-token
    updates all land in one workspace buffer — no entry copy, no
    double-buffered loop carry (the in-place workspace semantics of the
    reference's ``inference_context.h``).

    Returns ``fn(params, cache, input_ids, rng, eos_id[, attention_mask,
    prefill_logits]) -> ([B, prompt+new], cache)``.  The cache must be at
    least ``required_cache_len(prompt_len, max_new_tokens, prefill_chunk)``
    positions long (chunked prefill writes the padded prompt tail).
    ``external_prefill=True`` builds the decode-only program: the caller
    prefilled the cache already (engine split-prefill path) and passes the
    last-position ``prefill_logits`` [B, 1, V].

    ``early_exit=True`` (default) hoists the decode scan into a BOUNDED
    ``lax.while_loop`` that stops once every row is ``done`` — short
    completions no longer pay ``max_new_tokens`` masked decode steps.
    Tokens are bitwise-identical either way (post-done steps emit
    ``eos_id`` in both forms; the output buffer is eos-prefilled), only
    the number of executed decode steps differs.  ``early_exit=False``
    keeps the scan form (``decode_early_exit`` in the inference config)."""

    sample_fn = build_sample_fn(do_sample, temperature, top_k, top_p)

    if carry_params is None:
        carry_params = param_transform is not None

    @hot_path("inference.decode")
    def generate(params, cache, input_ids, rng, eos_id,
                 attention_mask=None, prefill_logits=None):
        deq = param_transform if param_transform is not None else (lambda p: p)
        B = input_ids.shape[0]
        # static guard: an undersized cache would let XLA CLAMP the padded
        # last chunk's write start, silently overwriting real prompt K/V
        min_len = prompt_len + max_new_tokens
        if prefill_chunk and prefill_chunk < prompt_len \
                and not external_prefill:
            min_len = max(min_len,
                          -(-prompt_len // prefill_chunk) * prefill_chunk)
        if cache["k"].shape[-2] < min_len:  # tpu-lint: disable=TL006 -- static under-size guard (raises at build time); each generate program sees one cache shape by construction
            raise ValueError(
                f"KV cache has {cache['k'].shape[-2]} positions but this "
                f"generation needs >= {min_len} (prompt {prompt_len} + new "
                f"{max_new_tokens}, chunked-prefill pad included) — size "
                f"it with required_cache_len()")
        # prefill the prompt in one pass (dequant fused into the prefill),
        # projecting ONLY each row's last real position through the vocab
        # head — full [B, prompt, V] prefill logits are a multi-GB
        # temporary at long prompts/large batches
        if with_mask:
            # right-padded rows: each row's next token comes from its LAST
            # REAL position and decoding continues at per-row offsets
            n = jnp.sum(attention_mask.astype(jnp.int32), axis=1)   # [B]
            last_pos = n - 1
        else:
            n = None
            last_pos = jnp.full((B,), prompt_len - 1, jnp.int32)
        if external_prefill:
            # the caller ran prefill (engine split-prefill path) and hands
            # in the last-position logits; the cache already holds the
            # prompt's K/V
            logits = prefill_logits
        elif prefill_chunk and prefill_chunk < prompt_len:
            # memory-bounded chunked prefill (see Transformer.
            # prefill_chunked): per-layer transients are O(B*chunk), the
            # enabler for big-batch and long-prompt serving points
            logits, cache = module.apply(
                deq(params), input_ids, cache, int(prefill_chunk),
                method=type(module).prefill_chunked, logits_at=last_pos)
        else:
            logits, cache = module.apply(deq(params), input_ids, cache, 0,
                                         method=type(module).decode,
                                         logits_at=last_pos)
        rng, sub = jax.random.split(rng)
        last = logits[:, 0]
        if with_mask:
            pos0 = n
        else:
            # scalar position: keeps the row-uniform cache-write fast path
            pos0 = jnp.asarray(prompt_len, jnp.int32)
        next_tok = sample_fn(last, sub)

        # When the dequant MATERIALIZES full weights (grouped scales,
        # int4, the hybrid rollout view) the quantized tree rides the
        # scan CARRY and is dequantized inside the body: carried values
        # are not loop-invariant to XLA, so the compute-dtype weights
        # stay a per-step temporary instead of a hoisted 2x-size loop
        # constant.  When the dequant FUSES into its consumers
        # (per-channel int8, or no quantization at all), carrying would
        # only copy the full tree into the loop's temp allocation
        # (~1.4 GB at 1.3B int8) on top of the argument buffers — at
        # bs128/seq384 that share of HBM pushed the program into XLA's
        # staging mode and decode collapsed 8x — so those cases close
        # over the argument buffers instead.
        def step(carry, _):
            tok, cache, pos, rng, done, qparams = carry
            p = deq(qparams if carry_params else params)
            logits, cache = module.apply(p, tok[:, None], cache,
                                         pos, method=type(module).decode)
            rng, sub = jax.random.split(rng)
            nxt = sample_fn(logits[:, -1], sub)
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
            return (nxt, cache, pos + 1, rng, done, qparams), nxt

        done0 = (next_tok == eos_id)
        T = max_new_tokens - 1
        if early_exit and T > 0:
            # bounded while_loop in place of the scan: stops the moment
            # every row is done, so a batch of short completions pays only
            # the steps it actually decodes.  Post-done steps emit eos_id
            # (same as the scan form) and the output buffer is prefilled
            # with eos_id, so tokens are bitwise-identical to the scan.
            buf0 = jnp.full((B, T), eos_id).astype(jnp.int32)

            def cond(carry):
                t, _, _, _, _, done, _, _ = carry
                return (t < T) & jnp.logical_not(jnp.all(done))

            def body(carry):
                t, tok, cache, pos, rng, done, qparams, buf = carry
                (tok, cache, pos, rng, done, qparams), nxt = step(
                    (tok, cache, pos, rng, done, qparams), None)
                buf = jax.lax.dynamic_update_slice(
                    buf, nxt.astype(jnp.int32)[:, None], (0, t))
                return (t + 1, tok, cache, pos, rng, done, qparams, buf)

            init = (jnp.asarray(0, jnp.int32), next_tok, cache, pos0, rng,
                    done0, params if carry_params else 0, buf0)
            _, _, cache, _, _, _, _, toks_bt = jax.lax.while_loop(
                cond, body, init)
            out = jnp.concatenate(
                [input_ids, next_tok[:, None], toks_bt], axis=1)
            return out, cache
        (_, cache, _, _, _, _), toks = jax.lax.scan(
            step, (next_tok, cache, pos0, rng, done0,
                   params if carry_params else 0),
            None, length=max_new_tokens - 1)
        # HF contract: prompt + generated tokens
        out = jnp.concatenate([input_ids, next_tok[:, None], toks.T], axis=1)
        return out, cache

    return jax.jit(generate, donate_argnums=(1,))
