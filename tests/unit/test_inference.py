"""Inference engine tests — analog of reference
``tests/unit/inference/test_inference.py``: KV-cached decode must agree with
the full forward pass, generation must run jitted with TP sharding."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig


def tiny_cfg(**over):
    base = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, use_flash_attention=False, dtype="float32")
    base.update(over)
    return TransformerConfig(**base)


@pytest.fixture
def model_and_params():
    model = Transformer(tiny_cfg())
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (2, 12)), jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    return model, params, ids


def test_cached_decode_matches_full_forward(model_and_params):
    """Prefill+decode with KV cache must reproduce teacher-forced logits."""
    model, params, ids = model_and_params
    full_logits = model.apply(params, ids, method=Transformer.logits)

    cache = model.init_cache(2, 12)
    # prefill first 8 tokens, then decode one at a time
    logits_p, cache = model.apply(params, ids[:, :8], cache, 0,
                                  method=Transformer.decode)
    np.testing.assert_allclose(np.asarray(logits_p), np.asarray(full_logits[:, :8]),
                               atol=2e-4, rtol=2e-4)
    pos = 8
    for t in range(8, 12):
        step_logits, cache = model.apply(params, ids[:, t:t + 1], cache, pos,
                                         method=Transformer.decode)
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full_logits[:, t]),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"decode step {t} diverged")
        pos += 1


def test_greedy_generation_deterministic(model_and_params):
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    engine.set_params(params)
    out1 = engine.generate(ids, max_new_tokens=8)
    out2 = engine.generate(ids, max_new_tokens=8)
    assert out1.shape == (2, 20)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_greedy_matches_no_cache_rollout(model_and_params):
    """Greedy generate must equal the naive re-forward argmax rollout."""
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    engine.set_params(params)
    gen = np.asarray(engine.generate(ids, max_new_tokens=6))

    seq = np.asarray(ids)
    for _ in range(6):
        logits = model.apply(params, jnp.asarray(seq), method=Transformer.logits)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(gen, seq)


def test_sampled_generation_runs(model_and_params):
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    engine.set_params(params)
    out = engine.generate(ids, max_new_tokens=5, do_sample=True,
                          temperature=0.8, top_k=10, top_p=0.9, seed=7)
    assert out.shape == (2, 17)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < 97))


def test_eos_early_stop(model_and_params):
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    engine.set_params(params)
    # force eos = whatever greedy emits first → everything after must be eos
    first = int(np.asarray(engine.generate(ids, max_new_tokens=1))[0, -1])
    out = np.asarray(engine.generate(ids, max_new_tokens=6, eos_token_id=first))
    assert np.all(out[0, ids.shape[1]:] == first)


def test_inference_tp_sharding(model_and_params):
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32",
                       "tensor_parallel": {"tp_size": 2}})
    engine.set_params(params)
    assert engine.topology.tp == 2
    leaves = jax.tree.leaves(engine.params)
    assert any("tp" in str(l.sharding.spec) for l in leaves), \
        "no inference param sharded over tp"
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (2, 16)
