"""TL004 positive fixture: unhashable / array-valued static args."""
import jax
import jax.numpy as jnp


def run(shape, x):
    return x.reshape(shape)


run_jit = jax.jit(run, static_argnums=(0,))
out = run_jit([4, 4], jnp.ones(16))                    # TL004: list static


@jax.jit
def _inline(x):
    return x


def scale(factors, x):
    return x


scale_jit = jax.jit(scale, static_argnames=("factors",))
out2 = scale_jit(factors=jnp.array([1.0, 2.0]), x=jnp.ones(2))   # TL004: array static
