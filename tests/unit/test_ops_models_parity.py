"""Tests for DeepSpeedTransformerLayer, TiledLinear, contiguous allocator,
CPU Adagrad, spatial ops, and the diffusers/CLIP wrappers (analogs of
reference tests/unit/ops/{transformer,adagrad,spatial} and
model_implementations coverage)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn


# ------------------------------------------------------------------ #
# DeepSpeedTransformerLayer
# ------------------------------------------------------------------ #
def test_transformer_layer_forward_and_grad():
    from deepspeed_tpu.ops.transformer.transformer import (
        DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
    cfg = DeepSpeedTransformerConfig(hidden_size=64, heads=4,
                                     attn_dropout_ratio=0.0,
                                     hidden_dropout_ratio=0.0)
    layer = DeepSpeedTransformerLayer(cfg)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 16, 64)),
                    jnp.float32)
    params = layer.init(jax.random.key(0), x)
    y = layer.apply(params, x)
    assert y.shape == x.shape
    # attention_mask path
    mask = jnp.ones((2, 16, 16), bool).at[:, :, 8:].set(False)
    ym = layer.apply(params, x, attention_mask=mask)
    assert ym.shape == x.shape
    assert not np.allclose(np.asarray(y), np.asarray(ym))
    # differentiable end-to-end
    g = jax.grad(lambda p: layer.apply(p, x).sum())(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


def test_transformer_layer_pre_vs_post_ln():
    from deepspeed_tpu.ops.transformer.transformer import (
        DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((1, 8, 32)),
                    jnp.float32)
    outs = []
    for pre in (True, False):
        cfg = DeepSpeedTransformerConfig(hidden_size=32, heads=2,
                                         pre_layer_norm=pre,
                                         attn_dropout_ratio=0.0,
                                         hidden_dropout_ratio=0.0)
        layer = DeepSpeedTransformerLayer(cfg)
        p = layer.init(jax.random.key(0), x)
        outs.append(np.asarray(layer.apply(p, x)))
    assert not np.allclose(outs[0], outs[1])


# ------------------------------------------------------------------ #
# TiledLinear
# ------------------------------------------------------------------ #
def test_tiled_linear_matches_dense():
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear
    tl = TiledLinear(in_features=12, out_features=8, in_splits=3, out_splits=2)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 12)),
                    jnp.float32)
    params = tl.init(jax.random.key(0), x)["params"]
    y = tl.apply({"params": params}, x)
    assert y.shape == (4, 8)
    # the tiles compose to one logical [in, out] weight
    W = TiledLinear.full_weight(params, in_splits=3, out_splits=2)
    b = jnp.concatenate([params["bias_0"], params["bias_1"]])
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ W + b), rtol=1e-5)


def test_tiled_linear_return_bias():
    from deepspeed_tpu.runtime.zero.tiling import TiledLinearReturnBias
    tl = TiledLinearReturnBias(in_features=8, out_features=6, in_splits=2,
                               out_splits=3)
    x = jnp.ones((2, 8), jnp.float32)
    params = tl.init(jax.random.key(0), x)
    y, b = tl.apply(params, x)
    assert y.shape == (2, 6) and b.shape == (6,)


# ------------------------------------------------------------------ #
# ContiguousMemoryAllocator
# ------------------------------------------------------------------ #
def test_contiguous_allocator_alloc_release_defrag():
    from deepspeed_tpu.runtime.zero.contiguous_memory_allocator import (
        ContiguousMemoryAllocator)
    a = ContiguousMemoryAllocator(100)
    t1, v1 = a.allocate_tensor(40)
    t2, v2 = a.allocate_tensor(30)
    t3, v3 = a.allocate_tensor(30)
    assert a.total_free == 0
    v2[:] = 7.0
    a.release_tensor(t1)
    a.release_tensor(t3)
    # 70 free but fragmented (40 front + 30 back) → defrag must make room
    assert a.total_free == 70 and a.largest_contiguous == 40
    t4, v4 = a.allocate_tensor(60)
    assert v4.shape == (60,)
    # live tensor data survived the compaction
    np.testing.assert_array_equal(a.get_tensor(t2), np.full(30, 7.0))


def test_contiguous_allocator_over_alloc_raises():
    from deepspeed_tpu.runtime.zero.contiguous_memory_allocator import (
        ContiguousMemoryAllocator)
    a = ContiguousMemoryAllocator(10)
    a.allocate_tensor(8)
    with pytest.raises(AssertionError):
        a.allocate_tensor(4)


# ------------------------------------------------------------------ #
# CPU Adagrad
# ------------------------------------------------------------------ #
def test_cpu_adagrad_matches_numpy():
    from deepspeed_tpu.ops.adagrad import DeepSpeedCPUAdagrad
    rng = np.random.default_rng(0)
    p0 = rng.standard_normal(64).astype(np.float32)
    g = rng.standard_normal(64).astype(np.float32)
    opt = DeepSpeedCPUAdagrad([p0.copy()], lr=0.1, eps=1e-10)
    opt.step([g])
    opt.step([g])
    # reference adagrad recurrence
    acc = np.zeros(64, np.float64)
    p = p0.astype(np.float64).copy()
    for _ in range(2):
        acc += g.astype(np.float64) ** 2
        p -= 0.1 * g / (np.sqrt(acc) + 1e-10)
    np.testing.assert_allclose(opt.params[0], p.astype(np.float32),
                               rtol=1e-4, atol=1e-5)
    sd = opt.state_dict()
    assert sd["step"] == 2


# ------------------------------------------------------------------ #
# spatial ops
# ------------------------------------------------------------------ #
def test_spatial_bias_adds():
    from deepspeed_tpu.ops.spatial import (nhwc_bias_add, nhwc_bias_add_add,
                                           nhwc_bias_add_bias_add)
    x = jnp.ones((2, 4, 4, 8))
    b = jnp.arange(8, dtype=jnp.float32)
    other = jnp.full((2, 4, 4, 8), 2.0)
    np.testing.assert_allclose(np.asarray(nhwc_bias_add(x, b))[0, 0, 0],
                               1.0 + np.arange(8))
    np.testing.assert_allclose(np.asarray(nhwc_bias_add_add(x, b, other))[0, 0, 0],
                               3.0 + np.arange(8))
    np.testing.assert_allclose(
        np.asarray(nhwc_bias_add_bias_add(x, b, other, b))[0, 0, 0],
        3.0 + 2 * np.arange(8))


# ------------------------------------------------------------------ #
# diffusers / CLIP wrappers
# ------------------------------------------------------------------ #
class TinyVAE(nn.Module):
    def setup(self):
        self.enc = nn.Dense(4)
        self.dec = nn.Dense(8)

    def __call__(self, x):
        return self.decode(self.encode(x))

    def encode(self, x):
        return self.enc(x)

    def decode(self, z):
        return self.dec(z)


class TinyUNet(nn.Module):
    @nn.compact
    def __call__(self, sample, t, enc):
        h = nn.Dense(sample.shape[-1])(sample)
        return h + t.reshape(-1, *([1] * (sample.ndim - 1))).astype(h.dtype) \
            + nn.Dense(sample.shape[-1])(enc)


def test_dsvae_wrapper():
    from deepspeed_tpu.model_implementations.diffusers import DSVAE
    m = TinyVAE()
    x = jnp.ones((2, 8))
    params = m.init(jax.random.key(0), x)
    ds = DSVAE(m, params)
    z = ds.encode(x)
    assert z.shape == (2, 4)
    out = ds.decode(z)
    assert out.shape == (2, 8)
    np.testing.assert_allclose(np.asarray(ds(x)), np.asarray(m.apply(params, x)),
                               rtol=1e-6)
    # replay path exercised (shape-keyed executable cache)
    assert ds._forward.iter_count == 1


def test_dsunet_and_clip_wrappers():
    from deepspeed_tpu.model_implementations.diffusers import DSUNet
    from deepspeed_tpu.model_implementations.transformers.clip_encoder import (
        DSClipEncoder, build_causal_attention_mask)
    m = TinyUNet()
    sample = jnp.ones((2, 8))
    t = jnp.asarray([1.0, 2.0])
    enc = jnp.ones((2, 16))
    params = m.init(jax.random.key(0), sample, t, enc)
    ds = DSUNet(m, params)
    out = ds(sample, t, enc)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(m.apply(params, sample, t, enc)),
                               rtol=1e-6)
    mask = build_causal_attention_mask(2, 4)
    assert mask.shape == (2, 1, 4, 4)
    assert float(mask[0, 0, 0, 1]) < -1e30 or float(mask[0, 0, 0, 1]) < 0
    assert float(mask[0, 0, 1, 0]) == 0.0


def test_compiled_graph_module_disable():
    from deepspeed_tpu.model_implementations.features import CompiledGraphModule
    calls = {"n": 0}

    def f(p, x):
        calls["n"] += 1
        return x * p

    g = CompiledGraphModule(f, enable_cuda_graph=False)
    g(2.0, jnp.ones(3))
    g(2.0, jnp.ones(3))
    assert calls["n"] == 2  # eager path when capture disabled
