"""Serving SLO / robustness tests (``inference/serving/``,
``docs/serving.md`` "Robustness & SLOs").

Covers the typed terminal statuses (deadline shedding before admission
and in-slot, client cancellation), bounded-queue backpressure
(reject/block), the dispatch circuit breaker (trip, reject-with-reason,
half-open recovery), the drain() wall-clock timeout diagnostics, and the
graceful-preemption drain → crash-atomic snapshot → bitwise resume path
— including the acceptance proofs: a subprocess driver killed at EVERY
serving fault-injection seam whose merged outputs are bitwise-identical
to an uninterrupted run, and compile-cache counters showing ZERO new
decode executables across an overload + drain + resume cycle."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference.serving.slo import (CircuitOpen, DrainTimeout,
                                                 QueueFull, RequestStatus)
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
from deepspeed_tpu.runtime.fault import inject
from deepspeed_tpu.runtime.fault.manifest import list_tags, verify_manifest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
DRIVER = os.path.join(REPO, "tests", "unit", "serving_driver.py")


@pytest.fixture(autouse=True)
def _disarm_injection():
    inject.reset_injection()
    yield
    inject.reset_injection()


def tiny_cfg(**over):
    base = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, use_flash_attention=False, dtype="float32")
    base.update(over)
    return TransformerConfig(**base)


SERVING = {"enabled": True, "num_slots": 3, "max_cache_len": 64,
           "prefill_chunk": 8, "prefill_token_budget": 16,
           "decode_block": 2}


@pytest.fixture
def served_engine():
    model = Transformer(tiny_cfg())
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 8,
                       "serving": SERVING})
    eng.set_params(params)
    return eng


def _prompts(rng, n, lo=9, hi=21):
    return [rng.integers(1, 97, (int(p),)).astype(np.int32)
            for p in rng.integers(lo, hi, (n,))]


# --------------------------------------------------------------------- #
# Deadlines: shed before admission, retire in-slot
# --------------------------------------------------------------------- #
def test_deadline_shed_before_admission(served_engine):
    """An already-expired deadline sheds the request from the queue with
    terminal status SHED_DEADLINE — it never occupies a slot — while
    deadline-less requests complete bitwise."""
    eng = served_engine
    rng = np.random.default_rng(41)
    p1, p2 = _prompts(rng, 2)
    srv = eng.serve()
    r_ok = srv.submit(p1, max_new_tokens=5, client_id="ok")
    r_shed = srv.submit(p2, max_new_tokens=5, deadline_s=0.0)
    outs = srv.drain()
    assert sorted(outs) == sorted([r_ok, r_shed])
    assert outs[r_shed] is None
    res = srv.result(r_shed)
    assert res.status == RequestStatus.SHED_DEADLINE
    assert "never occupied a slot" in res.detail
    assert srv.stats["admitted"] == 1, "shed request must not admit"
    assert srv.stats["shed"] == 1
    ok = srv.result(r_ok)
    assert ok.status == RequestStatus.COMPLETED
    assert ok.client_id == "ok" and ok.ttft_s is not None
    np.testing.assert_array_equal(
        outs[r_ok], np.asarray(eng.generate(p1[None], max_new_tokens=5))[0])


def test_deadline_retires_in_slot_and_slot_is_reusable(served_engine):
    """An in-slot deadline expiry retires the request at the next
    scheduling point (host-mirror only — no device round trip) and the
    freed lane serves the next request bitwise-correctly."""
    eng = served_engine
    rng = np.random.default_rng(43)
    p1, p2 = _prompts(rng, 2)
    srv = eng.serve(num_slots=1)
    r1 = srv.submit(p1, max_new_tokens=30, deadline_s=60.0)
    r2 = srv.submit(p2, max_new_tokens=4)
    while srv.active_slots == 0:
        srv.step()
    srv._requests[r1].deadline = time.monotonic() - 1.0   # force expiry
    outs = srv.drain()
    assert outs[r1] is None
    assert srv.result(r1).status == RequestStatus.SHED_DEADLINE
    assert "in slot" in srv.result(r1).detail
    np.testing.assert_array_equal(
        outs[r2], np.asarray(eng.generate(p2[None], max_new_tokens=4))[0])


def test_cancel_queued_and_running(served_engine):
    eng = served_engine
    rng = np.random.default_rng(45)
    p1, p2, p3 = _prompts(rng, 3)
    srv = eng.serve(num_slots=1)
    r1 = srv.submit(p1, max_new_tokens=30)
    r2 = srv.submit(p2, max_new_tokens=5)
    # queued cancellation is immediate
    assert srv.cancel(r2) is True
    assert srv.result(r2).status == RequestStatus.CANCELLED
    assert srv.cancel(r2) is False, "terminal requests cannot re-cancel"
    # an id this server never issued is a CLIENT error, not a no-op
    with pytest.raises(KeyError, match="unknown request id"):
        srv.cancel(10**9)
    # in-slot cancellation retires at this scheduling point
    while srv.active_slots == 0:
        srv.step()
    assert srv.cancel(r1) is True
    assert srv.active_slots == 0
    r3 = srv.submit(p3, max_new_tokens=4)
    outs = srv.drain()
    assert outs.get(r1, None) is None and outs.get(r2, "x") in (None, "x")
    np.testing.assert_array_equal(
        outs[r3], np.asarray(eng.generate(p3[None], max_new_tokens=4))[0])
    assert srv.stats["cancelled"] == 2


# --------------------------------------------------------------------- #
# Backpressure: bounded queue, reject / block
# --------------------------------------------------------------------- #
def test_backpressure_reject_and_block(served_engine):
    eng = served_engine
    rng = np.random.default_rng(47)
    prompts = _prompts(rng, 5)
    srv = eng.serve(num_slots=1, max_queue_depth=2, queue_policy="reject")
    srv.submit(prompts[0], max_new_tokens=3)
    srv.submit(prompts[1], max_new_tokens=3)
    with pytest.raises(QueueFull, match="max_queue_depth=2"):
        srv.submit(prompts[2], max_new_tokens=3)
    srv.drain()

    srv2 = eng.serve(num_slots=1, max_queue_depth=2, queue_policy="block")
    rids = [srv2.submit(p, max_new_tokens=3) for p in prompts]
    outs = srv2.drain()
    outs.update({r: srv2.result(r).output for r in rids
                 if r not in outs})          # finished during blocking
    for r, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            outs[r], np.asarray(eng.generate(p[None], max_new_tokens=3))[0])

    with pytest.raises(ValueError, match="queue_policy"):
        eng.serve(queue_policy="drop")


# --------------------------------------------------------------------- #
# Circuit breaker
# --------------------------------------------------------------------- #
def test_circuit_breaker_trips_rejects_and_recovers(served_engine):
    """N consecutive failed dispatches trip the breaker: failures are
    absorbed (requests ABORTED, scheduler stays consistent), submit()
    rejects with the reason, and after the cooldown a half-open probe
    closes it — the queued requests then complete bitwise."""
    eng = served_engine
    rng = np.random.default_rng(49)
    prompts = _prompts(rng, 4)
    srv = eng.serve(num_slots=2, breaker_threshold=2,
                    breaker_cooldown_s=0.05)
    rids = [srv.submit(p, max_new_tokens=4) for p in prompts]

    real_run = eng._run_guarded
    sick = [True]

    def failing_run(fn, args):
        if sick[0]:
            raise RuntimeError("injected sick-device dispatch failure")
        return real_run(fn, args)

    eng._run_guarded = failing_run
    try:
        srv.step()                       # failure 1 — absorbed
        assert not srv._breaker.open
        srv.step()                       # failure 2 — breaker trips
        assert srv._breaker.open
        with pytest.raises(CircuitOpen, match="consecutive dispatch"):
            srv.submit(prompts[0], max_new_tokens=2)
        # open breaker: no dispatches are attempted at all
        calls = srv.stats["prefill_tokens"]
        srv.step()
        assert srv.stats["prefill_tokens"] == calls
    finally:
        eng._run_guarded = real_run
    sick[0] = False
    time.sleep(0.06)                     # past the cooldown -> half-open
    outs = srv.drain()
    assert not srv._breaker.open
    aborted = [r for r in rids
               if srv.result(r).status == RequestStatus.ABORTED]
    done = [r for r in rids
            if srv.result(r).status == RequestStatus.COMPLETED]
    assert len(aborted) == 2 and len(done) == 2, \
        [srv.result(r).status for r in rids]
    for r in done:
        p = prompts[rids.index(r)]
        np.testing.assert_array_equal(
            outs[r], np.asarray(eng.generate(p[None], max_new_tokens=4))[0])
    assert srv._breaker.trips == 1
    # after recovery a fresh submit works again
    r_new = srv.submit(prompts[0], max_new_tokens=3)
    assert srv.drain()[r_new] is not None


def test_circuit_breaker_half_open_admits_submissions(served_engine):
    """A breaker that opened with an EMPTY queue must not lock the
    server out of submit() forever: once the cooldown elapses
    (half-open), submissions are admitted again and the next dispatch is
    the probe."""
    eng = served_engine
    rng = np.random.default_rng(59)
    (p1,) = _prompts(rng, 1)
    srv = eng.serve(num_slots=1, breaker_threshold=2,
                    breaker_cooldown_s=0.05)
    srv._breaker.record_failure(RuntimeError("boom 1"))
    srv._breaker.record_failure(RuntimeError("boom 2"))
    assert srv._breaker.open
    with pytest.raises(CircuitOpen):
        srv.submit(p1, max_new_tokens=3)
    time.sleep(0.06)                      # cooldown elapsed -> half-open
    r = srv.submit(p1, max_new_tokens=3)  # admitted: the probe's work
    out = srv.drain()[r]
    assert not srv._breaker.open          # probe dispatch succeeded
    np.testing.assert_array_equal(
        out, np.asarray(eng.generate(p1[None], max_new_tokens=3))[0])


def test_restore_rejects_requests_that_do_not_fit(served_engine, tmp_path):
    """A snapshot from a larger-lane server restored onto a smaller one:
    requests that cannot fit the new lanes are ABORTED with a clear
    reason (never streamed past the lane's end); fitting ones resume."""
    eng = served_engine
    rng = np.random.default_rng(61)
    big = eng.serve(max_cache_len=128, num_slots=2)
    r_big = big.submit(rng.integers(1, 97, (50,)).astype(np.int32),
                       max_new_tokens=40)
    r_ok = big.submit(rng.integers(1, 97, (10,)).astype(np.int32),
                      max_new_tokens=4)
    big.preempt(str(tmp_path), drain_budget_s=0.0)

    small = eng.serve(max_cache_len=64, num_slots=2)
    restored = small.restore(str(tmp_path))
    assert restored == [r_ok]
    res = small.result(r_big)
    assert res.status == RequestStatus.ABORTED
    assert "cache positions" in res.detail
    outs = small.drain()
    assert outs[r_ok] is not None and r_big in outs


# --------------------------------------------------------------------- #
# drain() timeout diagnostics
# --------------------------------------------------------------------- #
def test_drain_timeout_reports_per_slot_diagnostics(served_engine):
    eng = served_engine
    rng = np.random.default_rng(51)
    (p1,) = _prompts(rng, 1)
    srv = eng.serve(num_slots=2)
    r1 = srv.submit(p1, max_new_tokens=30)
    while srv.active_slots == 0:
        srv.step()
    srv._dispatch_decode = lambda: False          # wedge the scheduler
    with pytest.raises(DrainTimeout) as ei:
        srv.drain(timeout_s=0.2)
    msg = str(ei.value)
    assert "slot" in msg and f"request {r1}" in msg \
        and "last dispatch" in msg, msg


# --------------------------------------------------------------------- #
# Serving fault-injection seams
# --------------------------------------------------------------------- #
def test_serving_seams_registered_and_fire(served_engine):
    for point in ("serving.pre_admit", "serving.pre_decode_dispatch",
                  "serving.mid_drain", "serving.sigterm_at_iter"):
        assert point in inject.injection_points()
    # a raise at the decode seam propagates (breaker off = seed behavior)
    # and the scheduler recovers consistently afterwards
    eng = served_engine
    rng = np.random.default_rng(53)
    p1, p2 = _prompts(rng, 2)
    srv = eng.serve(num_slots=1)
    srv.submit(p1, max_new_tokens=4)
    inject.configure_injection({"point": "serving.pre_decode_dispatch",
                                "action": "raise"})
    with pytest.raises(IOError, match="injected transient fault"):
        srv.drain()
    inject.reset_injection()
    assert srv.active_slots == 0 and not srv._events
    r2 = srv.submit(p2, max_new_tokens=4)
    np.testing.assert_array_equal(
        srv.drain()[r2],
        np.asarray(eng.generate(p2[None], max_new_tokens=4))[0])


# --------------------------------------------------------------------- #
# Graceful preemption: drain -> snapshot -> bitwise resume (in-process)
# --------------------------------------------------------------------- #
def test_preempt_snapshot_resume_bitwise(served_engine, tmp_path):
    """Mid-flight preemption: undrained requests (including ones with
    PARTIAL token progress) snapshot crash-atomically; a fresh server
    restores them — same rids, prefix continuation — and every request's
    stitched output is bitwise its solo generate() run."""
    from deepspeed_tpu.inference.serving.snapshot import read_snapshot_tag
    eng = served_engine
    rng = np.random.default_rng(55)
    prompts = _prompts(rng, 5)
    news = [int(n) for n in rng.integers(6, 13, (5,))]
    srv = eng.serve(num_slots=2)
    rids = [srv.submit(p, max_new_tokens=n, client_id=i)
            for i, (p, n) in enumerate(zip(prompts, news))]
    early = {}
    for _ in range(6):                    # some requests mid-decode
        early.update(srv.step())
    tag, snapped, finished = srv.preempt(str(tmp_path), drain_budget_s=0.0)
    finished = {**early, **finished}
    assert snapped, "expected undrained work at preemption"
    assert verify_manifest(str(tmp_path / tag)) == []
    state = read_snapshot_tag(str(tmp_path), tag)
    assert any(r["tokens"] for r in state["requests"]), \
        "expected a mid-decode request with partial tokens"
    assert {r["rid"] for r in state["requests"]} == set(snapped)
    with pytest.raises(RuntimeError, match="closed"):
        srv.submit(prompts[0], max_new_tokens=2)

    srv2 = eng.serve(num_slots=2)
    restored = srv2.restore(str(tmp_path))
    assert sorted(restored) == sorted(snapped)
    assert srv2.stats["resumed"] == len(restored)
    outs = dict(finished)
    outs.update(srv2.drain())
    for rid, p, n in zip(rids, prompts, news):
        want = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
        np.testing.assert_array_equal(
            outs[rid], want,
            err_msg=f"resumed request {rid} diverges from solo run")
        assert srv2.result(rid).client_id == rids.index(rid) \
            if rid in restored else True
    # a new submission on the resumed server gets a fresh, unused rid
    assert srv2.submit(prompts[0], max_new_tokens=2) not in rids
    srv2.drain()


def test_snapshot_corruption_walks_back(tmp_path):
    from deepspeed_tpu.inference.serving.snapshot import (
        load_newest_snapshot, save_snapshot)
    req = {"rid": 0, "client_id": None, "prompt": [1, 2, 3], "tokens": [],
           "max_new": 4, "eos": -1, "deadline_remaining_s": None,
           "submitted_it": 0}
    save_snapshot(str(tmp_path), "serving_1",
                  {"seq": 1, "next_rid": 1, "rng": [0, 0],
                   "requests": [req]})
    save_snapshot(str(tmp_path), "serving_2",
                  {"seq": 2, "next_rid": 2, "rng": [0, 0],
                   "requests": [dict(req, rid=1)]})
    tag, state = load_newest_snapshot(str(tmp_path))
    assert tag == "serving_2" and state["requests"][0]["rid"] == 1
    # size-preserving corruption: manifest checksums catch it, walk back
    payload = tmp_path / "serving_2" / "serving_state.json"
    raw = bytearray(payload.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    payload.write_bytes(bytes(raw))
    tag, state = load_newest_snapshot(str(tmp_path))
    assert tag == "serving_1" and state["requests"][0]["rid"] == 0
    # stale staging orphans are never candidates
    (tmp_path / "serving_9.tmp").mkdir()
    tag, _ = load_newest_snapshot(str(tmp_path))
    assert tag == "serving_1"


# --------------------------------------------------------------------- #
# The one-decode-executable invariant across overload + drain + resume
# --------------------------------------------------------------------- #
def test_overload_drain_resume_zero_new_decode_executables(tmp_path):
    """Acceptance: an overload burst (submits > slots, a deadline shed,
    a cancellation) + graceful drain + restarted-server resume mints
    ZERO new decode executables — each server compiles exactly ONE
    decode-step signature for its whole lifetime (overload, drain and
    resume all ride traced slot arguments), and the serving programs
    never touch the executable store (reloaded serving executables
    corrupt the slot workspace — ServingEngine.__init__)."""
    from deepspeed_tpu.runtime import compile_cache as cc

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        model = Transformer(tiny_cfg())
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (1, 12)),
                          jnp.int32)
        params = model.init(jax.random.key(0), {"input_ids": ids})
        config = {"dtype": "float32", "prefill_chunk_size": 8,
                  "serving": SERVING,
                  "compile_cache": {"enabled": True,
                                    "cache_dir": str(tmp_path / "cache"),
                                    "min_compile_time_secs": 0.0}}
        snap = str(tmp_path / "snap")
        rng = np.random.default_rng(57)
        prompts = _prompts(rng, 7)
        news = [int(n) for n in rng.integers(4, 9, (7,))]

        def fresh_server():
            eng = deepspeed_tpu.init_inference(model, config=config)
            eng.set_params(params)
            srv = eng.serve()
            return eng, srv, srv.warmup()

        # --- overload burst on a cold server, then graceful drain ---
        eng1, srv1, report1 = fresh_server()
        rids = [srv1.submit(p, max_new_tokens=n, client_id=i)
                for i, (p, n) in enumerate(zip(prompts[:5], news[:5]))]
        r_shed = srv1.submit(prompts[5], max_new_tokens=4, deadline_s=0.0)
        r_cancel = srv1.submit(prompts[6], max_new_tokens=4)
        srv1.cancel(r_cancel)
        early = {}
        for _ in range(4):
            early.update(srv1.step())
        s1 = cc.stats().snapshot()
        tag, snapped, finished = srv1.preempt(snap, drain_budget_s=0.0)
        finished = {**early, **finished}
        assert srv1.result(r_shed).status == RequestStatus.SHED_DEADLINE
        assert srv1.result(r_cancel).status == RequestStatus.CANCELLED

        # --- restarted server: resume and finish ---
        eng2, srv2, report2 = fresh_server()
        s2 = cc.stats().snapshot()
        # the restart compiled its own serving programs — no store
        # traffic in either direction (reloaded serving executables are
        # the corruption hazard the opt-out exists for)
        assert any(k.startswith("serving_decode") for k in report2)
        assert s2["executable_saves"] == s1["executable_saves"]
        assert s2["executable_hits"] == s1["executable_hits"]
        restored = srv2.restore(snap)
        assert sorted(restored) == sorted(snapped)
        outs = dict(finished)
        outs.update(srv2.drain())
        s3 = cc.stats().snapshot()
        assert s3["executable_saves"] == s1["executable_saves"], \
            "the overload+drain+resume cycle persisted a new executable"
        # the cycle minted no decode executables beyond ONE per server:
        # overload, shed, cancel, drain and resume all ride traced slot
        # arguments
        for srv, eng in ((srv1, eng1), (srv2, eng2)):
            n_decode = sum(1 for sig in eng._aot
                           if sig and sig[0] == id(srv._decode_fn))
            assert n_decode == 1, n_decode
        for rid, p, n in zip(rids, prompts[:5], news[:5]):
            want = np.asarray(
                eng2.generate(p[None], max_new_tokens=n))[0]
            np.testing.assert_array_equal(outs[rid], want)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)
        cc._configured_dir = prev_dir


# --------------------------------------------------------------------- #
# The kill-at-seam acceptance proof (subprocess, every serving seam)
# --------------------------------------------------------------------- #
def _run_serving_driver(ckpt_dir, results_path, cache_dir,
                        inject_spec=None, drain_budget=0.0,
                        speculative=False):
    env = dict(os.environ)
    env["DSTPU_REPO_ROOT"] = REPO
    env["DSTPU_DRIVER_CACHE"] = str(cache_dir)
    env.pop("DSTPU_FAULT_INJECT", None)
    env.pop("BENCH_MODEL", None)
    if inject_spec:
        env["DSTPU_FAULT_INJECT"] = inject_spec
    return subprocess.run(
        [sys.executable, DRIVER, "--ckpt-dir", str(ckpt_dir),
         "--results", str(results_path),
         "--drain-budget", str(drain_budget)]
        + (["--spec"] if speculative else []),
        env=env, capture_output=True, text=True, timeout=240)


def _merged_results(path):
    out = {}
    with open(path) as f:
        for line in f:
            idx, status, toks = line.strip().split(",", 2)
            out[int(idx)] = (status, toks)
    return out


@pytest.fixture(scope="module")
def serving_driver_reference(tmp_path_factory):
    """One uninterrupted driver run: the bitwise reference (and the
    shared per-module compile cache every scenario reuses — safe: kills
    land at seams, never mid-cache-write)."""
    base = tmp_path_factory.mktemp("serving_driver")
    cache = base / "cache"
    results = base / "ref_results.txt"
    proc = _run_serving_driver(base / "ckpt", results, cache)
    assert proc.returncode == 0, proc.stderr[-3000:]
    ref = _merged_results(results)
    assert sorted(ref) == [0, 1, 2, 3, 4, 5]
    assert ref[5][0] == "SHED_DEADLINE", ref
    assert all(ref[i][0] == "COMPLETED" for i in range(5)), ref
    return {"cache": cache, "ref": ref, "base": base}


# (scenario, DSTPU_FAULT_INJECT spec, expected first-run rc, drain
#  budget, speculative serving)
SERVING_KILL_SCENARIOS = [
    # graceful: SIGTERM mid-serving -> drain -> snapshot -> exit 3
    ("sigterm_graceful",
     "point=serving.sigterm_at_iter,action=sigterm,at=4", 3, 0.0, False),
    # hard kills (os._exit, no cleanup) at each dispatch seam
    ("exit_pre_admit",
     "point=serving.pre_admit,action=exit,at=2", 17, 0.0, False),
    ("exit_pre_decode_dispatch",
     "point=serving.pre_decode_dispatch,action=exit,at=3", 17, 0.0,
     False),
    # hard kill DURING the graceful drain, before the snapshot publishes
    ("exit_mid_drain",
     "point=serving.sigterm_at_iter,action=sigterm,at=5;"
     "point=serving.mid_drain,action=exit,at=1", 17, 5.0, False),
    # SPECULATIVE serving (self-draft, k=2): SIGTERM mid-speculation —
    # the snapshot must hold committed tokens only (uncommitted draft
    # tokens are discarded), the resumed SPECULATIVE run must merge
    # bitwise with the NON-speculative reference (the bitwise-greedy
    # contract and the kill harness, proven together)
    ("sigterm_graceful_spec",
     "point=serving.sigterm_at_iter,action=sigterm,at=4", 3, 0.0, True),
    # hard kill at the decode seam mid-speculation: in-flight verify
    # windows die unprocessed, nothing uncommitted may leak into results
    ("exit_pre_decode_dispatch_spec",
     "point=serving.pre_decode_dispatch,action=exit,at=3", 17, 0.0,
     True),
]


@pytest.mark.parametrize("name,spec,want_rc,budget,speculative",
                         SERVING_KILL_SCENARIOS,
                         ids=[s[0] for s in SERVING_KILL_SCENARIOS])
def test_serving_kill_at_seam_resumes_bitwise(
        name, spec, want_rc, budget, speculative,
        serving_driver_reference, tmp_path):
    """Acceptance: the serving driver killed at each serving seam —
    gracefully (SIGTERM -> drain -> crash-atomic snapshot) or hard
    (os._exit) — relaunches, resumes/resubmits, and every non-shed
    request completes with greedy outputs BITWISE-identical to the
    uninterrupted reference run; the deadline request reports
    SHED_DEADLINE in every scenario.  The *_spec scenarios run the SAME
    workload under speculative serving (self-draft) and must still
    match the non-speculative reference bitwise — mid-speculation kills
    may never surface uncommitted draft tokens."""
    ref = serving_driver_reference["ref"]
    cache = serving_driver_reference["cache"]
    results = tmp_path / "results.txt"
    proc = _run_serving_driver(tmp_path / "ckpt", results, cache,
                               inject_spec=spec, drain_budget=budget,
                               speculative=speculative)
    assert proc.returncode == want_rc, \
        f"{name}: expected rc={want_rc}, got {proc.returncode}\n" \
        + proc.stderr[-3000:] + proc.stdout[-1000:]
    if want_rc == 3:
        # graceful preemption published a manifest-valid snapshot
        tags = list_tags(str(tmp_path / "ckpt"))
        assert tags, "preemption must leave a snapshot"
        assert verify_manifest(str(tmp_path / "ckpt" / tags[0])) == []
    proc = _run_serving_driver(tmp_path / "ckpt", results, cache,
                               drain_budget=budget,
                               speculative=speculative)
    assert proc.returncode == 0, \
        f"{name}: resume failed\n" + proc.stderr[-3000:]
    got = _merged_results(results)
    assert got == ref, \
        f"{name}: resumed outputs diverge from the uninterrupted run\n" \
        f"want {ref}\ngot  {got}"
