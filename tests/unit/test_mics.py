"""MiCS (hierarchical / partial ZeRO-3) — reference ``runtime/zero/mics.py``
(``MiCS_Init:54``, ``MiCS_Optimizer:350``): ZeRO shards live within replica
groups of ``mics_shard_size`` devices and replicate across groups, so
param gathers ride ICI-local links (two-hop gather, ``mics.py:24-29``).

TPU realization: ``mics_shard_size`` splits the DP world into an ``mdp``
replica-group axis times an ``edp`` shard axis of exactly that size; the
sharding plan restricts ZeRO axes to ``edp`` (``runtime/zero/partition.py``).
"""

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
from deepspeed_tpu.parallel.topology import reset_topology


def tiny_cfg(**over):
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                max_seq_len=16, dtype="float32", use_flash_attention=False,
                remat=False)
    base.update(over)
    return TransformerConfig(**base)


def make_engine(mics=2, stage=3, **cfg_over):
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(tiny_cfg(**cfg_over)),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": stage,
                                      "mics_shard_size": mics}})
    return engine


def batch(seed=0, bs=8, seq=16):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 64, (bs, seq)).astype(np.int32)}


def test_mics_topology_split():
    """mics_shard_size=2 on 8 devices → 4 replica groups (mdp) × 2-wide
    shard groups (edp)."""
    reset_topology()
    engine = make_engine(mics=2)
    assert engine.topology.edp == 2
    assert engine.topology.mdp == 4
    assert engine.topology.mesh.shape["edp"] == 2
    assert engine.topology.mesh.shape["mdp"] == 4


def test_mics_param_shardings_are_group_local():
    """ZeRO-3 + MiCS: params shard over edp ONLY (replicated across the
    mdp replica groups) — the reference's shard-within-group semantics."""
    reset_topology()
    engine = make_engine(mics=2)
    b = batch()
    loss = engine(b)
    engine.backward(loss)
    engine.step()
    specs = [str(l.sharding.spec) for l in jax.tree.leaves(engine.params)]
    assert any("edp" in s for s in specs), "no param sharded over edp"
    assert not any("mdp" in s for s in specs), \
        "MiCS params must be REPLICATED across replica groups (mdp)"
    # optimizer state follows the same group-local rule
    opt_specs = [str(l.sharding.spec)
                 for l in jax.tree.leaves(engine._opt_state)
                 if hasattr(l, "sharding")]
    assert any("edp" in s for s in opt_specs)
    assert not any("mdp" in s for s in opt_specs)


def test_mics_trains():
    reset_topology()
    engine = make_engine(mics=2)
    b = batch(seed=3)
    losses = []
    for _ in range(6):
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"MiCS no learning: {losses}"


def test_mics_equals_flat_zero_loss_trajectory():
    """MiCS is a memory/communication layout, not an algorithm change:
    the training trajectory must match flat ZeRO-3 exactly."""
    def run(mics):
        reset_topology()
        engine = make_engine(mics=mics)
        b = batch(seed=5)
        out = []
        for _ in range(3):
            loss = engine(b)
            engine.backward(loss)
            engine.step()
            out.append(float(jax.device_get(loss)))
        return out

    np.testing.assert_allclose(run(-1), run(2), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_mics_checkpoint_reshards_to_flat_and_back(tmp_path):
    """Save under MiCS (edp=2 × mdp=4), load into a FRESH flat ZeRO-3
    engine (edp=8) and vice versa — values identical, training continues
    (reference MiCS↔ZeRO checkpoint compatibility, ``mics.py:350``)."""
    reset_topology()
    e1 = make_engine(mics=2)
    b = batch(seed=7)
    for _ in range(2):
        loss = e1(b)
        e1.backward(loss)
        e1.step()
    e1.save_checkpoint(str(tmp_path / "mics"))
    before = jax.device_get(e1.params)

    reset_topology()
    e2 = make_engine(mics=-1)      # flat ZeRO-3
    e2.load_checkpoint(str(tmp_path / "mics"))
    jax.tree.map(np.testing.assert_array_equal, before,
                 jax.device_get(e2.params))
    assert e2.global_steps == 2
    loss = e2(b)
    e2.backward(loss)
    e2.step()
    e2.save_checkpoint(str(tmp_path / "flat"))

    reset_topology()
    e3 = make_engine(mics=4)       # different group size
    e3.load_checkpoint(str(tmp_path / "flat"))
    jax.tree.map(np.testing.assert_array_equal, jax.device_get(e2.params),
                 jax.device_get(e3.params))
    assert e3.global_steps == 3
    loss = e3(b)
    e3.backward(loss)
    e3.step()
    assert np.isfinite(float(jax.device_get(loss)))


def test_mics_invalid_shard_size_raises():
    reset_topology()
    with pytest.raises(ValueError):
        make_engine(mics=3)        # 3 does not divide the 8-device DP world
