"""tpu-lint — framework-aware static analysis for TPU hazards.

Rules (each suppressible per line or per function via
``# tpu-lint: disable=<rule> -- reason``):

* **TL001** host transfer (``.item()``, ``float()``, ``np.asarray``,
  ``jax.device_get``, ``block_until_ready``) on a registered hot path
* **TL002** ``jax.jit``/``pjit`` over large buffers without donation
* **TL003** Python side effects (print / logging / global writes) inside a
  jitted function
* **TL004** unhashable or array-valued static args
* **TL005** per-step config/dict string lookups on a hot path
* **TL006** jit-signature instability (weak-typed scalars into traced
  positions, identity-hashed statics, shape-dependent host branches) —
  paired with the runtime retrace counter
  (:mod:`deepspeed_tpu.tools.lint.retrace_check`)
* **TL007** variable read after being passed in a donated position
* **TL008** lock-guarded serving field accessed outside ``with
  self._lock`` (or a ``# lock-held:`` annotated method) — declared via
  the ``GUARDED_FIELDS`` registry / ``# guarded-by:`` comments; paired
  with the ``DSTPU_CONCURRENCY_CHECKS=1`` runtime assertions and the
  interleaving stress harness
  (:mod:`deepspeed_tpu.tools.lint.interleave_check`)
* **TL009** lock-taking engine call on the asyncio loop thread not
  routed through ``run_in_executor``, or an owner-bound driving method
  (``step``/``drain``/``preempt``) in a context that can never be the
  scheduler owner
* **TL010** implicit replication at mesh boundaries (unspecced
  ``shard_map``/mesh-context jit, bare ``P()`` on batch/sequence-scaling
  arrays) — paired with the byte-level comm budgets in ``PROGRAMS.lock``
* **TL011** implicit resharding seams (``device_put`` /
  ``with_sharding_constraint`` inside hot paths, literal mesh-axis names
  outside the canonical topology)

CLI: ``python -m deepspeed_tpu.tools.lint [paths]`` (or ``bin/ds_lint``);
exits non-zero when any unsuppressed finding remains.  ``--jaxpr`` runs
the companion jaxpr harness (:mod:`deepspeed_tpu.tools.lint.jaxpr_check`),
which traces the registered hot-path entry points and verifies — at the
compiler level — that they contain no host callbacks and that declared
donations actually alias.  ``--contracts [--update]`` regenerates the
program-contract lockfile (:mod:`deepspeed_tpu.tools.lint.contract`,
``PROGRAMS.lock``) and diffs it per program — including the byte-level
comm budgets and {1,2,4,8} mesh-scaling tables
(:mod:`deepspeed_tpu.tools.lint.comm_contract`).  ``--concurrency`` runs
the TL008/TL009 sweep and, when clean, the interleaving stress harness.
``--comm`` runs the TL010/TL011 sharding sweep and, when clean, the
mesh-scaling prover (per-chip byte volumes must not grow with mesh size
unless declared).
"""

from deepspeed_tpu.tools.lint.core import Finding, RULES, run_lint  # noqa: F401
from deepspeed_tpu.tools.lint.hotpath import hot_path  # noqa: F401
