"""The bench harness must be crash-proof: each phase runs in its own
subprocess, a failed phase is retried once with a safe config, and a
double failure records an ``error`` field instead of erasing the record
(the reference's per-workload process isolation, ``launcher/runner.py:377``;
our round-3 driver capture was lost to exactly this failure mode).

The subprocess-spawning tests here are ``slow`` (nightly tier): each one
boots a full bench parent + calibration child (~15 s calibration on the
1-core container, ~2 min for the module), and the calibration-floor
timing guard still raced the box under tier-1 load — the known flake.
Tier-1 keeps the pure-host scheduling/annotation logic (phase order,
regression thresholds, record normalization), which is where every
actual harness regression so far has been caught."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
BENCH = os.path.join(REPO, "bench.py")


def run_bench(extra_env, out_dir):
    env = dict(os.environ)
    env.update({
        "DSTPU_ACCELERATOR": "cpu",
        "JAX_PLATFORMS": "cpu",
        # the parent never imports jax; children resolve the cpu platform
        # through the DSTPU_ACCELERATOR hook in run_phase
        "BENCH_PHASE_TIMEOUT": "600",
        # keep scratch/partial files away from a possibly-live real run
        "BENCH_OUT_DIR": str(out_dir),
    })
    env.pop("BENCH_MODEL", None)
    env.update(extra_env)
    proc = subprocess.run([sys.executable, BENCH], env=env,
                          capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    return json.loads(line), proc.stderr


@pytest.fixture(scope="module")
def calibrate_run(tmp_path_factory):
    """One calibrate-only bench run, shared by the contract test and —
    as a MEASURED floor for the calibration phase's wall clock — by the
    timeout test below (whose budget was a constant 15 s that the slow
    container's ~15 s calibration raced, the known flake)."""
    out = tmp_path_factory.mktemp("calibrate_floor")
    result, stderr = run_bench({"BENCH_PHASES": "calibrate"}, out)
    return result, stderr, out


@pytest.mark.slow
def test_bench_single_phase_json_contract(calibrate_run):
    """One phase on the CPU backend: rc 0, one final JSON line with the
    driver contract fields, calibration populated with measured peaks."""
    result, _, out_dir = calibrate_run
    for field in ("metric", "value", "unit", "vs_baseline"):
        assert field in result, result
    cal = result["calibration"]
    assert cal["platform"] == "cpu"
    assert cal["measured_hbm_gbps"] > 0
    assert cal["measured_mxu_tflops"] > 0
    assert cal["datasheet_hbm_gbps"] > 0
    assert "phase_errors" not in result
    # incremental record exists and holds the phase
    with open(out_dir / ".bench_partial.json") as f:
        partial = json.load(f)
    assert "calibration" in partial


@pytest.mark.slow
def test_bench_fallback_retry_recovers(tmp_path):
    """A phase that dies on its primary attempt is retried with the safe
    config and lands in the record with ``fallback: true``."""
    result, stderr = run_bench({"BENCH_PHASES": "calibrate",
                                "BENCH_TEST_FAIL_PRIMARY": "calibrate"},
                               tmp_path)
    cal = result["calibration"]
    assert cal.get("fallback") is True, cal
    assert cal["measured_hbm_gbps"] > 0
    assert "phase_errors" not in result
    assert "retrying with safe config" in stderr


@pytest.mark.slow
def test_bench_double_failure_records_error_and_continues(tmp_path):
    """A phase that dies on BOTH attempts records an ``error`` field; the
    suite still exits 0 and later phases still run (round-3 regression:
    one late-phase OOM converted the whole record into a stack trace)."""
    result, _ = run_bench({"BENCH_PHASES": "calibrate",
                           "BENCH_TEST_FAIL_ALWAYS": "calibrate"},
                          tmp_path)
    cal = result["calibration"]
    assert "error" in cal
    assert "injected unconditional failure" in cal["error"]
    assert "phase_errors" in result
    # the harness survived: the contract line still came out on stdout
    assert result["unit"] == "tokens/s/chip"


@pytest.mark.slow
def test_bench_parent_never_initializes_backend():
    """The parent orchestrator must never create a jax device client — a
    dead phase's HBM can only be pinned by a process holding the device,
    and the parent must not be one (the round-3 retry-inside-except kept
    1.3B params alive through the traceback frames).  The environment's
    sitecustomize imports jax in every interpreter, so the check is on
    backend CLIENTS, not on the import."""
    code = ("import sys; sys.argv=['bench.py']; "
            "import bench; "
            "from jax._src import xla_bridge; "
            "assert not xla_bridge._backends, 'parent created a backend'; "
            "print('CLEAN')")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CLEAN" in proc.stdout


@pytest.mark.slow
def test_bench_timeout_skips_and_records_prior_phases(calibrate_run,
                                                      tmp_path):
    """A phase that exceeds its wall-clock budget is skipped-and-recorded
    (NO fallback retry — a safe config fixes an OOM, not slowness) and
    every already-finished phase survives in BOTH incremental records
    (the round-5 regression: one 40-min phase starved the whole suite and
    the record was rc=124 with zero numbers).

    The budget is scaled off the calibration phase's MEASURED wall clock,
    not a constant: on the slow container calibration takes ~15 s, so a
    flat 15 s budget made this test race its own setup phase (the known
    pre-existing flake) — calibration must comfortably fit while the
    hanging phase still times out quickly."""
    floor = calibrate_run[0]["calibration"]["phase_wall_s"]
    budget = max(15, int(floor * 2.5) + 5)
    result, stderr = run_bench({"BENCH_PHASES": "calibrate,north",
                                "BENCH_TEST_HANG": "north",
                                "BENCH_PHASE_TIMEOUT": str(budget)},
                               tmp_path)
    # the completed phase's numbers survive the later overrun
    assert result["calibration"]["measured_hbm_gbps"] > 0
    ns = result["north_star"]
    assert ns.get("timeout") is True
    assert "timeout" in ns["error"]
    assert "exceeded its" in stderr and "budget" in stderr
    assert "retrying with safe config" not in stderr     # no doubled damage
    # incremental final-format record on disk holds the same story
    with open(tmp_path / "BENCH_partial.json") as f:
        rec = json.load(f)
    assert rec["calibration"]["measured_hbm_gbps"] > 0


@pytest.mark.slow
def test_bench_suite_budget_skips_and_records(tmp_path):
    """BENCH_SUITE_BUDGET caps every phase's timeout at what the suite can
    still afford and records out-of-budget phases as skipped — the suite
    always finishes inside the budget with the contract JSON intact (the
    round-5 rc=124: the budget was only checked between phases, so one
    phase blew straight through the wrapping driver's window)."""
    result, stderr = run_bench({"BENCH_PHASES": "calibrate,north",
                                "BENCH_SUITE_BUDGET": "1"}, tmp_path)
    assert "skipped" in result["calibration"]
    assert "skipped" in result["north_star"]
    assert "suite budget exhausted" in stderr
    assert result["unit"] == "tokens/s/chip"      # contract line survived
    with open(tmp_path / "BENCH_partial.json") as f:
        rec = json.load(f)
    assert "skipped" in rec["calibration"]


def test_bench_round_robin_phase_order(tmp_path, monkeypatch):
    """Under BENCH_SUITE_BUDGET phase order rotates by staleness across
    rounds (the r05 blackout: a fixed cheap-first order measured the same
    3 leading phases every round): phases starved in earlier rounds run
    before phases measured last round, calibration stays pinned first,
    and a fresh machine (no BENCH_r* trail) keeps the registry's
    cheap-first order.  Pure host logic — no jax, no subprocess."""
    monkeypatch.setenv("BENCH_OUT_DIR", str(tmp_path))
    monkeypatch.syspath_prepend(REPO)
    import bench
    base = [k for k, _, _ in bench.PHASES]
    assert "serving_paged" in base          # the paged phase is registered
    # no trail: the pinned head (calibration, memory_snapshot, then the
    # paged-kernel acceptance phase) comes first, the rest keep the
    # registry's cheap-first order verbatim
    head = ["calibration", "memory_snapshot", "serving_paged"]
    assert [k for k, _, _ in bench._phase_order(bench.PHASES)] \
        == head + [k for k in base if k not in head]

    # round 1's budget afforded calibration + guard + north; offload was
    # skipped, decode timed out, the rest never ran
    r1 = {"metric": "m", "unit": "tokens/s/chip",
          "calibration": {"measured_hbm_gbps": 1.0},
          "sft_350m_guard": {"mfu": 0.3},
          "north_star": {"mfu": 0.4},
          "optimizer_offload": {"skipped": "suite budget exhausted"},
          "generation": {"error": "timeout after 900s", "timeout": True}}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(r1))
    # a corrupt trail file must be skipped, never wedge scheduling
    (tmp_path / "BENCH_r02.json").write_text("{half a reco")
    order = [k for k, _, _ in bench._phase_order(bench.PHASES)]
    assert order[0] == "calibration"
    # the memory micro-phase is pinned right behind calibration: the
    # per-program memory record commits before any heavy phase can
    # starve it (the r05-blackout lesson on the memory axis)
    assert order[1] == "memory_snapshot"
    # serving_paged is pinned third: it carries the paged-kernel
    # acceptance story and must land in the NEXT record (BENCH_r06)
    # rather than wait out a starvation rotation
    assert order[2] == "serving_paged"
    assert sorted(order) == sorted(base)    # nothing dropped or invented
    measured = {"sft_350m_guard", "__headline__"}
    pinned = {"calibration", "memory_snapshot", "serving_paged"}
    starved = [k for k in base
               if k not in measured and k not in pinned]
    # every starved phase (incl. the skipped + timed-out ones) runs
    # before anything measured in round 1...
    assert max(order.index(k) for k in starved) \
        < min(order.index(k) for k in measured)
    # ...and starved phases keep their cheap-first relative order
    assert [k for k in order if k in starved] \
        == [k for k in base if k in starved]

    # round 2 measures what starved; round 3 then prioritizes round 1's
    # leaders again — full rotation, every phase measured every K rounds
    r2 = {k: {"ok": 1} for k in starved}
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(r2))
    order3 = [k for k, _, _ in bench._phase_order(bench.PHASES)]
    assert order3.index("sft_350m_guard") \
        < min(order3.index(k) for k in starved)


@pytest.mark.slow
def test_bench_interrupt_emits_partial_record(tmp_path):
    """SIGINT mid-suite (a user's Ctrl-C, or a wrapping driver giving up):
    the parent must still emit the driver-contract JSON with every
    completed phase, exit 0, and leave the incremental record on disk."""
    import signal
    import time as _time
    env = dict(os.environ)
    env.update({"DSTPU_ACCELERATOR": "cpu", "JAX_PLATFORMS": "cpu",
                "BENCH_OUT_DIR": str(tmp_path),
                "BENCH_PHASES": "calibrate,north",
                "BENCH_TEST_HANG": "north",
                "BENCH_PHASE_TIMEOUT": "600"})
    env.pop("BENCH_MODEL", None)
    proc = subprocess.Popen([sys.executable, BENCH], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    try:
        # wait until phase 1 (calibrate) has landed in the incremental
        # record, i.e. the suite is inside the hanging phase 2
        partial = tmp_path / "BENCH_partial.json"
        deadline = _time.monotonic() + 300
        while _time.monotonic() < deadline:
            if partial.exists() and "calibration" in partial.read_text():
                break
            _time.sleep(0.5)
        else:
            raise AssertionError("calibrate never finished")
        _time.sleep(1.0)
        proc.send_signal(signal.SIGINT)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, err[-2000:]
    assert "interrupted during north" in err
    record = json.loads(out.strip().splitlines()[-1])
    assert record["calibration"]["measured_hbm_gbps"] > 0
    assert record["interrupted_during"] == "north"
    assert record["unit"] == "tokens/s/chip"


# --------------------------------------------------------------------- #
# Per-phase regression thresholds vs the previous BENCH_r* record
# (warn-and-annotate; ROADMAP item 5 leftover).  Pure host logic.
# --------------------------------------------------------------------- #
def test_bench_regression_annotation(tmp_path, monkeypatch):
    """A phase metric that dropped beyond the threshold vs the newest
    previous record is annotated in the phase record; small wobbles and
    non-perf numbers are not."""
    monkeypatch.setenv("BENCH_OUT_DIR", str(tmp_path))
    monkeypatch.syspath_prepend(REPO)
    import bench
    prev = {"decode": {"decode_tokens_per_sec_chip": 1000.0, "mfu": 0.40,
                       "e2e_time_s": 2.0, "batch_size": 64,
                       "sub": {"speedup_vs_sequential": 3.0}}}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(prev))

    phase = {"decode_tokens_per_sec_chip": 700.0, "mfu": 0.39,
             "e2e_time_s": 2.6, "batch_size": 32,
             "sub": {"speedup_vs_sequential": 3.1}}
    bench._annotate_regressions("decode", phase)
    regs = {r["metric"]: r for r in phase["regressions"]}
    # 30% throughput drop and 23% slowdown (lower-is-better) annotated...
    assert "decode_tokens_per_sec_chip" in regs
    assert regs["decode_tokens_per_sec_chip"]["drop_pct"] == 30.0
    assert "e2e_time_s" in regs
    # ...the 2.5% mfu wobble, the improved speedup, and the non-perf
    # batch_size change are not
    assert "mfu" not in regs and "batch_size" not in regs
    assert "sub.speedup_vs_sequential" not in regs

    # within threshold: no annotation key at all
    ok_phase = {"decode_tokens_per_sec_chip": 950.0, "mfu": 0.41,
                "e2e_time_s": 2.1, "batch_size": 64}
    bench._annotate_regressions("decode", ok_phase)
    assert "regressions" not in ok_phase

    # threshold is tunable; 0 disables
    tight = {"decode_tokens_per_sec_chip": 950.0}
    bench._annotate_regressions("decode", tight, threshold=0.01)
    assert tight["regressions"][0]["drop_pct"] == 5.0
    off = {"decode_tokens_per_sec_chip": 10.0}
    bench._annotate_regressions("decode", off, threshold=0)
    assert "regressions" not in off

    # skipped/errored phases and never-measured phases are untouched
    skipped = {"skipped": "suite budget exhausted"}
    bench._annotate_regressions("decode", skipped)
    assert "regressions" not in skipped
    fresh = {"tokens_per_sec_chip": 1.0}
    bench._annotate_regressions("never_measured_phase", fresh)
    assert "regressions" not in fresh


def test_bench_record_normalization(tmp_path, monkeypatch):
    """The BENCH_r* trail accepts final-format records AND driver
    wrappers ({n, cmd, rc, tail, parsed}): the record is recovered from
    `parsed` or from the last stdout line in `tail`; a tail truncated
    mid-record is skipped rather than wedging the trail."""
    monkeypatch.setenv("BENCH_OUT_DIR", str(tmp_path))
    monkeypatch.syspath_prepend(REPO)
    import bench
    final = {"decode": {"decode_tokens_per_sec_chip": 5.0}}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(final))
    wrapper = {"n": 2, "cmd": "python bench.py", "rc": 0, "parsed": None,
               "tail": "[INFO] noise\n" + json.dumps(
                   {"decode": {"decode_tokens_per_sec_chip": 7.0}})}
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(wrapper))
    clipped = {"n": 3, "cmd": "python bench.py", "rc": 124, "parsed": None,
               "tail": '_per_sec_chip": 8.0}}'}      # cut from the left
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(clipped))
    parsed = {"n": 4, "cmd": "python bench.py", "rc": 0, "tail": "x",
              "parsed": {"decode": {"decode_tokens_per_sec_chip": 9.0}}}
    (tmp_path / "BENCH_r04.json").write_text(json.dumps(parsed))

    trail = bench._round_trail()
    vals = [r["decode"]["decode_tokens_per_sec_chip"] for r in trail]
    assert vals == [5.0, 7.0, 9.0]          # clipped r03 skipped

    # regression annotation uses the NEWEST recovered record (r04)
    phase = {"decode_tokens_per_sec_chip": 6.0}
    bench._annotate_regressions("decode", phase, trail=trail)
    assert phase["regressions"][0]["prev"] == 9.0
