from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.runtime.data_pipeline.data_sampler import DeepSpeedDataSampler, DataAnalyzer
from deepspeed_tpu.runtime.data_pipeline.data_routing import (
    RandomLTDScheduler, random_ltd_layer, sample_kept_indices,
    gather_tokens, scatter_tokens)
