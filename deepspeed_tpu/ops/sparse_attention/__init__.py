from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
    VariableSparsityConfig, BigBirdSparsityConfig, BSLongformerSparsityConfig,
    LocalSlidingWindowSparsityConfig)
from deepspeed_tpu.ops.sparse_attention.block_sparse import (
    block_sparse_attention, sparse_attention_reference, layout_tables)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention, SparseAttentionFn)
