from .elasticity import (compute_elastic_config, get_compatible_gpus_v01,
                         get_compatible_gpus_v02, ElasticityError,
                         ElasticityConfigError, ElasticityIncompatibleWorldSize)
