"""TP↔EP tensor redistributions — reference ``deepspeed/moe/mappings.py``
(``_GatherTokens``/``_DropTokens`` autograd functions over the tensor-model-
parallel group).

Why they exist: with TP active, every TP rank holds the same tokens; sending
all of them through the expert all_to_all would route duplicates.  The
reference drops 1/tp of the tokens before MoE dispatch and gathers them back
after.  Here the pair are differentiable functions usable inside
``shard_map`` over a mesh axis — the vjp of a gather is a drop and vice
versa, so jax.grad recreates the reference's custom backward passes.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _axis_size(axis):
    return lax.psum(1, axis)


def _gather(x, axis, dim):
    return lax.all_gather(x, axis, axis=dim, tiled=True)


def _drop(x, axis, dim):
    W = _axis_size(axis)
    r = lax.axis_index(axis)
    size = x.shape[dim] // W
    return lax.dynamic_slice_in_dim(x, r * size, size, axis=dim)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_tokens(x, axis="tp", dim=0):
    return _gather(x, axis, dim)


def _gather_fwd(x, axis, dim):
    return _gather(x, axis, dim), None


def _gather_bwd(axis, dim, _res, g):
    return (_drop(g, axis, dim),)


gather_tokens.defvjp(_gather_fwd, _gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def drop_tokens(x, axis="tp", dim=0):
    """Keep this rank's 1/tp slice of ``dim`` (reference ``_DropTokens``)."""
    return _drop(x, axis, dim)


def _drop_fwd(x, axis, dim):
    return _drop(x, axis, dim), None


def _drop_bwd(axis, dim, _res, g):
    return (_gather(g, axis, dim),)


drop_tokens.defvjp(_drop_fwd, _drop_bwd)
