"""REAL multi-process bootstrap: two OS processes rendezvous through
``jax.distributed.initialize`` (``comm/backend.py``) into one 8-device mesh,
launched through ``launcher/runner.py``'s host fan-out — the analog of the
reference's process-spawning distributed test harness
(``tests/unit/common.py:89-186``) and per-host env bootstrap
(``launcher/launch.py:216``).  Unlike ``test_data_launcher.py`` (command
construction only), these tests execute the full path: launcher → per-host
env injection → coordinator rendezvous → cross-process ZeRO-2 step."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "mp_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(out, local_devices):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("XLA_", "JAX_", "DSTPU_"))}
    env.update({"DSTPU_REPO_ROOT": REPO, "WORKER_OUT": out,
                "WORKER_LOCAL_DEVICES": str(local_devices)})
    return env


def _read_losses(path):
    with open(path) as f:
        return [float(x) for x in f.read().split()]




def _launch_and_compare(tmp_path, variant=None, local_devices=4):
    """Run the worker through the launcher on two local 'hosts', assert
    both ranks produced identical losses, then reproduce them with a
    single process on the same global mesh size."""
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n127.0.0.1 slots=1\n")
    port = _free_port()
    out = str(tmp_path / "losses")
    env = _worker_env(out, local_devices=local_devices)
    if variant:
        env["WORKER_VARIANT"] = variant
    result = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "-H", str(hostfile), "--master_addr", "127.0.0.1",
         "--master_port", str(port), WORKER],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert result.returncode == 0, \
        f"launcher failed\nstdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    l0 = _read_losses(f"{out}.rank0")
    l1 = _read_losses(f"{out}.rank1")
    np.testing.assert_allclose(l0, l1, rtol=0, atol=0)

    ref_out = str(tmp_path / "ref")
    env = _worker_env(ref_out, local_devices=2 * local_devices)
    if variant:
        env["WORKER_VARIANT"] = variant
    ref = subprocess.run(
        [sys.executable, WORKER], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=600)
    assert ref.returncode == 0, \
        f"reference run failed\nstdout:\n{ref.stdout}\nstderr:\n{ref.stderr}"
    np.testing.assert_allclose(l0, _read_losses(f"{ref_out}.rank0"),
                               rtol=1e-4)
    return l0

@pytest.mark.slow
def test_two_process_bootstrap_through_launcher(tmp_path):
    """Two launcher-spawned OS processes (4 devices each) rendezvous via
    jax.distributed.initialize into one 8-device mesh, run ZeRO-2 steps,
    and match the single-process 8-device run."""
    _launch_and_compare(tmp_path)


@pytest.mark.slow
def test_single_host_local_launch_path():
    """The launcher's single-host path (no hostfile → exec locally) runs the
    worker unchanged (reference ``launcher/runner.py:377`` local branch)."""
    result = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "-H", "/nonexistent/hostfile", WORKER],
        cwd=REPO, env=_worker_env("", local_devices=8),
        capture_output=True, text=True, timeout=600)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert "process 0/1" in result.stdout


@pytest.mark.slow
def test_checkpoint_across_world_sizes(tmp_path):
    """The reference's DistributedFixture pattern for real
    (``tests/unit/common.py:215``): a checkpoint produced by TWO processes
    (4 devices each) resumes in ONE process (8 devices) and continues the
    exact trajectory — cross-world-size save/load through the launcher-
    bootstrapped ``jax.distributed`` mesh."""
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n127.0.0.1 slots=1\n")
    port = _free_port()
    out = str(tmp_path / "losses")
    ckpt = str(tmp_path / "ckpt")

    env = _worker_env(out, local_devices=4)
    env["WORKER_SAVE_DIR"] = ckpt
    result = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "-H", str(hostfile), "--master_addr", "127.0.0.1",
         "--master_port", str(port), WORKER],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert result.returncode == 0, \
        f"save run failed\nstdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    two_proc = _read_losses(f"{out}.rank0")
    assert len(two_proc) == 3           # 2 pre-save + 1 post-save

    # resume single-process on the same global mesh size
    env = _worker_env(str(tmp_path / "resume"), local_devices=8)
    env["WORKER_LOAD_DIR"] = ckpt
    result = subprocess.run(
        [sys.executable, WORKER], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=600)
    assert result.returncode == 0, \
        f"resume run failed\nstdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert "resumed at global_steps=2" in result.stdout
    resumed = _read_losses(str(tmp_path / "resume") + ".rank0")
    # the resumed first step must reproduce the 2-process run's post-save
    # step exactly (same data stream, same fold_in(step) rng)
    np.testing.assert_allclose(resumed[0], two_proc[2], rtol=1e-4)


@pytest.mark.slow
def test_pipeline_across_processes(tmp_path):
    """3D parallelism with the pipeline axis CROSSING the process boundary:
    pp=2 x tp=2 x dp=2 on 2 launcher-spawned processes (4 devices each) —
    the pp ppermutes ride the inter-process (DCN-tier) link, the way a real
    multi-host pipeline maps stages to nodes (reference
    ``runtime/pipe/topology.py`` 3D axis order).  Losses must match the
    single-process 8-device run exactly."""
    _launch_and_compare(tmp_path, variant="pp")


@pytest.mark.slow
def test_ring_attention_across_processes(tmp_path):
    """Sequence parallelism with the sp axis spanning both processes (sp=8 —
    a narrower ring would nest inside one process, since edp is outer to
    sp in the mesh): ring attention's KV-rotation ppermutes cross the
    process boundary
    (context parallelism at the DCN tier — the reference scales long
    sequences with its sparse-attention kernels; ring attention is this
    framework's SP superset, SURVEY §2.3).  Losses must match the
    single-process 8-device run."""
    _launch_and_compare(tmp_path, variant="sp")


@pytest.mark.slow
def test_ulysses_attention_across_processes(tmp_path):
    """DeepSpeed-Ulysses sequence parallelism with sp=8 spanning both
    processes: the head-scatter/gather all-to-alls cross the process
    boundary (reference deepspeed-ulysses maps this exchange onto the
    inter-node fabric).  Losses must match the single-process 8-device
    run."""
    _launch_and_compare(tmp_path, variant="ulysses")


@pytest.mark.slow
def test_moe_expert_parallel_across_processes(tmp_path):
    """Expert parallelism with ep=8 spanning both processes: the MoE
    dispatch/combine all-to-alls cross the process boundary — multi-node
    expert placement (reference ``moe/sharded_moe.py`` all_to_all over the
    expert group).  Losses must match the single-process 8-device run."""
    _launch_and_compare(tmp_path, variant="moe")
