from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    checkpoint, checkpoint_wrapper, configure, is_configured)
