"""Pipeline schedules — API parity with reference ``runtime/pipe/schedule.py``
(``PipeSchedule:11``, ``InferenceSchedule:135``, ``TrainSchedule:189`` 1F1B,
``DataParallelSchedule:301`` and the instruction dataclasses ``:327-489``).

On TPU the schedule is *compiled into* the SPMD pipeline program
(``parallel/pipeline.py``): one scan tick executes what the reference's
interpreter dispatches as Recv→Forward→Send instruction triples.  These
classes remain for (a) user code that introspects schedules, (b) tests that
verify wavefront math, and (c) documentation of the instruction semantics the
compiled program implements."""

from dataclasses import dataclass


# ---- instructions (reference schedule.py:327-489) -------------------- #
@dataclass(frozen=True)
class PipeInstruction:
    stage_id: int = 0
    micro_batch_id: int = -1

    def __repr__(self):
        return f"{type(self).__name__}(stage={self.stage_id}, mb={self.micro_batch_id})"


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass


class ForwardPass(PipeInstruction):
    pass


class BackwardPass(PipeInstruction):
    pass


class SendActivation(PipeInstruction):
    pass


class RecvActivation(PipeInstruction):
    pass


class SendGrad(PipeInstruction):
    pass


class RecvGrad(PipeInstruction):
    pass


# ---- schedules ------------------------------------------------------- #
class PipeSchedule:
    """Iterable of per-step instruction lists (reference ``schedule.py:11``)."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self):
        raise NotImplementedError

    def __iter__(self):
        return iter(self.steps())

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages


class InferenceSchedule(PipeSchedule):
    """Forward-only wavefront (reference ``schedule.py:135``)."""

    def steps(self):
        total = self.micro_batches + self.stages - 1
        out = []
        for t in range(total):
            cmds = []
            m = t - self.stage_id
            if self._valid_micro_batch(m):
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(self.stage_id, m))
                else:
                    cmds.append(RecvActivation(self.stage_id, m))
                cmds.append(ForwardPass(self.stage_id, m))
                if not self.is_last_stage:
                    cmds.append(SendActivation(self.stage_id, m))
            out.append(cmds)
        return out


class TrainSchedule(PipeSchedule):
    """Fill-drain training wavefront with interleaved backward — the
    instruction stream whose dataflow the compiled scan reproduces
    (reference 1F1B ``schedule.py:189``)."""

    def steps(self):
        fwd = InferenceSchedule(self.micro_batches, self.stages,
                                self.stage_id).steps()
        total = self.micro_batches + self.stages - 1
        bwd = []
        # backward wavefront runs in reverse stage order
        rev = self.stages - 1 - self.stage_id
        for t in range(total):
            cmds = []
            m = t - rev
            if self._valid_micro_batch(m):
                if not self.is_last_stage:
                    cmds.append(RecvGrad(self.stage_id, m))
                cmds.append(BackwardPass(self.stage_id, m))
                if not self.is_first_stage:
                    cmds.append(SendGrad(self.stage_id, m))
            bwd.append(cmds)
        tail = [[ReduceTiedGrads(self.stage_id), ReduceGrads(self.stage_id),
                 OptimizerStep(self.stage_id)]]
        return fwd + bwd + tail


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference ``schedule.py:301``)."""

    def steps(self):
        out = []
        for m in range(self.micro_batches):
            out.append([LoadMicroBatch(0, m), ForwardPass(0, m),
                        BackwardPass(0, m)])
        out.append([ReduceGrads(0), OptimizerStep(0)])
        return out
