"""Kernel-injected inference + greedy/sampled generation from an HF
checkpoint (reference ``deepspeed.init_inference`` + DS-kernel generate).

    python examples/generate.py --model facebook/opt-125m --tp 1
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# a sitecustomize may pin a hardware platform before this script runs; the
# live jax config must be updated before first device use (env is too late)
if os.environ.get("DSTPU_ACCELERATOR") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="facebook/opt-125m")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--prompt", default="DeepSpeed on TPU is")
    ap.add_argument("--max_new_tokens", type=int, default=32)
    args = ap.parse_args()

    import numpy as np
    import deepspeed_tpu
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(args.model)
    engine = deepspeed_tpu.init_inference(
        args.model,
        config={"dtype": "bfloat16",
                "tensor_parallel": {"tp_size": args.tp},
                "replace_with_kernel_inject": True})
    ids = np.asarray(tok(args.prompt, return_tensors="np")["input_ids"],
                     dtype=np.int32)
    out = engine.generate(ids, max_new_tokens=args.max_new_tokens)
    print(tok.decode(np.asarray(out)[0]))


if __name__ == "__main__":
    main()
