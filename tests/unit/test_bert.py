"""BERT encoder family tests: exact logit parity against HF torch BERT
(analog of the reference's BERT-heavy ``tests/unit/inference/test_inference.py``
matrix), masking semantics, and classification head."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.bert import (BertConfig, BertForMaskedLM,
                                       BertForSequenceClassification)


def _tiny_hf_bert(seed=0):
    import torch
    import transformers
    torch.manual_seed(seed)
    cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_act="gelu", hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    return transformers.BertForMaskedLM(cfg).eval()


def test_bert_logit_parity_with_hf():
    import torch
    from deepspeed_tpu.module_inject.replace_module import convert_hf_model
    hf = _tiny_hf_bert()
    model, params = convert_hf_model(hf, dtype="float32")
    assert isinstance(model, BertForMaskedLM)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = hf(input_ids=torch.tensor(ids.astype(np.int64))).logits.numpy()
    got = np.asarray(model.apply(params, {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_bert_mlm_trains_through_engine():
    """BERT MLM fine-tuning through the engine (the reference's
    bert-finetuning/bert-pretraining tutorials drive exactly this stack):
    encoder-family training — not just inference parity — with ZeRO-2 and
    the fused train step.  Loss on a fixed masked-token batch decreases."""
    import flax.linen as nn
    import deepspeed_tpu

    cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                     num_heads=4, intermediate_size=64,
                     max_position_embeddings=32, dtype="float32")
    MASK = 63

    class MLMTrain(nn.Module):
        @nn.compact
        def __call__(self, batch):
            logits = BertForMaskedLM(cfg, name="bert")(
                batch["input_ids"],
                attention_mask=batch.get("attention_mask"))
            labels = batch["labels"]
            mask = (batch["input_ids"] == MASK).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            nll = -jnp.take_along_axis(logp, labels[..., None], -1)[..., 0]
            return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    engine, *_ = deepspeed_tpu.initialize(
        model=MLMTrain(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
                "zero_optimization": {"stage": 2}})
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 63, (4 * engine.topology.dp, 32)).astype(np.int32)
    ids = labels.copy()
    ids[rng.random(ids.shape) < 0.3] = MASK
    batch = {"input_ids": ids, "labels": labels}
    losses = []
    for _ in range(16):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses


def test_bert_attention_mask_semantics():
    import torch
    from deepspeed_tpu.module_inject.replace_module import convert_hf_model
    hf = _tiny_hf_bert(seed=1)
    model, params = convert_hf_model(hf, dtype="float32")
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 128, (1, 8)).astype(np.int32)
    mask = np.ones((1, 8), np.int32)
    mask[:, 5:] = 0
    with torch.no_grad():
        want = hf(input_ids=torch.tensor(ids.astype(np.int64)),
                  attention_mask=torch.tensor(mask.astype(np.int64))
                  ).logits.numpy()
    got = np.asarray(model.apply(params, {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.asarray(mask)}))
    # unmasked positions must agree (masked positions' outputs are
    # padding-dependent garbage in both frameworks)
    np.testing.assert_allclose(got[:, :5], want[:, :5], rtol=2e-4, atol=2e-4)


def test_bert_token_type_embeddings_used():
    cfg = BertConfig(vocab_size=64, hidden_size=16, num_layers=1,
                     num_heads=2, max_position_embeddings=16)
    m = BertForMaskedLM(cfg)
    ids = jnp.zeros((1, 6), jnp.int32)
    params = m.init(jax.random.key(0), {"input_ids": ids})
    a = m.apply(params, {"input_ids": ids,
                         "token_type_ids": jnp.zeros((1, 6), jnp.int32)})
    b = m.apply(params, {"input_ids": ids,
                         "token_type_ids": jnp.ones((1, 6), jnp.int32)})
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_bert_sequence_classification():
    cfg = BertConfig(vocab_size=64, hidden_size=16, num_layers=1,
                     num_heads=2, max_position_embeddings=16, num_labels=3)
    m = BertForSequenceClassification(cfg)
    ids = jnp.zeros((2, 6), jnp.int32)
    params = m.init(jax.random.key(0), {"input_ids": ids})
    out = m.apply(params, {"input_ids": ids})
    assert out.shape == (2, 3)


def test_bert_headless_encoder_conversion():
    """A BertModel (no MLM head) converts onto BertEncoder and returns
    hidden states matching HF."""
    import torch
    import transformers
    from deepspeed_tpu.models.bert import BertEncoder
    from deepspeed_tpu.module_inject.replace_module import convert_hf_model
    torch.manual_seed(3)
    cfg = transformers.BertConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    hf = transformers.BertModel(cfg).eval()
    model, params = convert_hf_model(hf, dtype="float32")
    assert isinstance(model, BertEncoder)
    ids = np.random.default_rng(3).integers(0, 96, (1, 9)).astype(np.int32)
    with torch.no_grad():
        want = hf(input_ids=torch.tensor(ids.astype(np.int64))
                  ).last_hidden_state.numpy()
    got = np.asarray(model.apply(params, {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_bert_through_init_inference():
    """The public init_inference path must route a BERT model through the
    policy and answer forward() with vocab logits."""
    import deepspeed_tpu
    hf = _tiny_hf_bert(seed=2)
    engine = deepspeed_tpu.init_inference(hf, dtype="float32")
    ids = jnp.asarray(np.random.default_rng(2).integers(0, 128, (1, 8)),
                      jnp.int32)
    out = engine.forward(ids)
    assert out.shape == (1, 8, 128)


def test_distilbert_logit_parity_with_hf():
    """DistilBERT (no token-type embeddings, vocab_transform/-projector MLM
    head) converts onto the same fused encoder stack."""
    import torch
    import transformers
    from deepspeed_tpu.module_inject.replace_module import convert_hf_model
    torch.manual_seed(3)
    cfg = transformers.DistilBertConfig(
        vocab_size=128, dim=32, n_layers=2, n_heads=4, hidden_dim=64,
        max_position_embeddings=64, dropout=0.0, attention_dropout=0.0)
    hf = transformers.DistilBertForMaskedLM(cfg).eval()
    model, params = convert_hf_model(hf, dtype="float32")
    assert isinstance(model, BertForMaskedLM)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = hf(input_ids=torch.tensor(ids.astype(np.int64))).logits.numpy()
    got = np.asarray(model.apply(params, {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
