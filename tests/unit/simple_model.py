"""Test model zoo — analog of reference ``tests/unit/simple_model.py``
(SimpleModel / SimpleMoEModel / linear stacks) in flax."""

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn


class SimpleModel(nn.Module):
    """Linear stack returning cross-entropy-ish loss (reference SimpleModel)."""
    hidden_dim: int = 16
    nlayers: int = 2

    @nn.compact
    def __call__(self, batch):
        x, y = batch["x"], batch["y"]
        for i in range(self.nlayers):
            x = nn.Dense(self.hidden_dim, name=f"linear_{i}")(x)
            x = nn.relu(x)
        logits = nn.Dense(self.hidden_dim, name="head")(x)
        one_hot = jax.nn.one_hot(y, self.hidden_dim)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * one_hot, axis=-1))


class SimpleMLPRegressor(nn.Module):
    hidden_dim: int = 16

    @nn.compact
    def __call__(self, batch):
        x, y = batch["x"], batch["y"]
        h = nn.Dense(self.hidden_dim)(x)
        h = nn.tanh(h)
        out = nn.Dense(x.shape[-1])(h)
        return jnp.mean((out - y) ** 2)


def random_dataset(n=64, dim=16, classes=16, seed=0):
    rng = np.random.default_rng(seed)
    return [{"x": rng.standard_normal(dim).astype(np.float32),
             "y": np.int32(rng.integers(0, classes))} for _ in range(n)]


def random_batch(batch_size=8, dim=16, classes=16, seed=0):
    rng = np.random.default_rng(seed)
    return {"x": rng.standard_normal((batch_size, dim)).astype(np.float32),
            "y": rng.integers(0, classes, size=(batch_size,)).astype(np.int32)}
