"""Persistent compile/executable cache (runtime/compile_cache.py): hit/miss
accounting, executable round-trips, fingerprint-mismatch fallback, and the
acceptance contract — a warm-cache second invocation of the train-step +
prefill + decode compile paths skips XLA compilation, asserted via the
framework's cache-hit counters on CPU."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime import compile_cache as cc
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig

from simple_model import SimpleModel, random_batch


@pytest.fixture
def cache_dir(tmp_path):
    """tmp cache dir + guaranteed restore: the persistent XLA cache is
    process-wide and the suite's own cache dir (tests/conftest.py) must
    come back for the tests that run after this module."""
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    yield str(tmp_path)
    jax.config.update("jax_compilation_cache_dir", prev_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)
    cc._configured_dir = prev_dir


def _snap():
    return cc.stats().snapshot()


def _delta(after, before, key):
    return after[key] - before[key]


# --------------------------------------------------------------------- #
# ExecutableStore unit behavior
# --------------------------------------------------------------------- #
def test_executable_store_roundtrip_and_accounting(cache_dir):
    store = cc.ExecutableStore(cache_dir)
    x = jnp.arange(8.0)
    compiled = jax.jit(lambda v: v * 2 + 1).lower(x).compile()
    key = cc.cache_key("roundtrip", cc.abstract_signature((x,)))

    s0 = _snap()
    assert store.load(key) is None                  # cold → miss
    s1 = _snap()
    assert _delta(s1, s0, "executable_misses") == 1
    assert store.save(key, compiled)
    s2 = _snap()
    assert _delta(s2, s1, "executable_saves") == 1

    reloaded = store.load(key)
    assert reloaded is not None
    s3 = _snap()
    assert _delta(s3, s2, "executable_hits") == 1
    np.testing.assert_array_equal(np.asarray(reloaded(x)),
                                  np.asarray(compiled(x)))


def test_fingerprint_mismatch_falls_back_to_fresh_compile(cache_dir):
    store = cc.ExecutableStore(cache_dir)
    x = jnp.arange(4.0)
    compiled = jax.jit(lambda v: v + 1).lower(x).compile()
    key = cc.cache_key("fp-mismatch", cc.abstract_signature((x,)))
    assert store.save(key, compiled)

    # a cache written by a different jaxlib build must be IGNORED, not
    # deserialized into a crash
    meta_path = os.path.join(cache_dir, key + ".json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["fingerprint"]["jaxlib"] = "0.0.0-other-build"
    with open(meta_path, "w") as f:
        json.dump(meta, f)

    s0 = _snap()
    assert store.load(key) is None
    s1 = _snap()
    assert _delta(s1, s0, "executable_mismatches") == 1
    assert _delta(s1, s0, "executable_misses") == 1
    # the graceful path end-to-end: get_or_compile recompiles and reports
    # a miss, never an error to the caller
    pc = cc.ProgramCache(cc.CompileCacheConfig(
        enabled=True, cache_dir=cache_dir, min_compile_time_secs=0.0))
    exe, secs, hit = pc.get_or_compile(
        "fp-mismatch-recompile", (cc.abstract_signature((x,)),),
        lambda: jax.jit(lambda v: v + 1).lower(x).compile())
    assert not hit and secs > 0
    np.testing.assert_array_equal(np.asarray(exe(x)), np.asarray(x + 1))


def test_corrupt_payload_is_a_miss_not_a_crash(cache_dir):
    store = cc.ExecutableStore(cache_dir)
    x = jnp.arange(4.0)
    key = cc.cache_key("corrupt", cc.abstract_signature((x,)))
    assert store.save(key, jax.jit(lambda v: v * 3).lower(x).compile())
    with open(os.path.join(cache_dir, key + ".bin"), "wb") as f:
        f.write(b"\x00garbage")
    s0 = _snap()
    assert store.load(key) is None
    s1 = _snap()
    assert _delta(s1, s0, "executable_errors") == 1
    assert _delta(s1, s0, "executable_misses") == 1


def test_cache_key_separates_shapes_and_tags():
    fp = {"pin": "fixed"}
    a = cc.cache_key("t", ((4,), "float32"), fingerprint=fp)
    assert a == cc.cache_key("t", ((4,), "float32"), fingerprint=fp)
    assert a != cc.cache_key("t", ((8,), "float32"), fingerprint=fp)
    assert a != cc.cache_key("other", ((4,), "float32"), fingerprint=fp)
    assert a != cc.cache_key("t", ((4,), "float32"), fingerprint={"pin": "x"})


# --------------------------------------------------------------------- #
# Acceptance: warm second invocation skips XLA compilation
# --------------------------------------------------------------------- #
def _train_config(cache_dir):
    return {"train_micro_batch_size_per_gpu": 2,   # x 8 virtual devices
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "compile_cache": {"enabled": True, "cache_dir": cache_dir,
                              "min_compile_time_secs": 0.0}}


def test_train_step_warm_cache_skips_compile(cache_dir):
    """Two fresh engines, same config: the second's fused train step must
    come from the executable store (hit counter), not an XLA compile."""
    batch = jax.tree.map(lambda x: x[None], random_batch(batch_size=16))

    def run():
        engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(),
                                              config=_train_config(cache_dir))
        loss = engine.train_batch(batch=batch)
        return float(jax.device_get(engine.train_batch(batch=batch)))

    s0 = _snap()
    l1 = run()
    s1 = _snap()
    assert _delta(s1, s0, "executable_saves") >= 1     # cold: compiled+saved
    assert "train_step" in s1["compile_seconds"]
    l2 = run()
    s2 = _snap()
    assert _delta(s2, s1, "executable_hits") >= 1      # warm: reloaded
    assert _delta(s2, s1, "executable_saves") == 0     # nothing recompiled
    assert l1 == l2                                    # identical trajectory


def _tiny_model():
    cfg = TransformerConfig(vocab_size=97, hidden_size=64, num_layers=2,
                            num_heads=4, max_seq_len=64,
                            use_flash_attention=False, dtype="float32")
    model = Transformer(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    return model, params, ids


def test_prefill_decode_warm_cache_skips_compile(cache_dir):
    """Two fresh inference engines on the split-prefill path (prefill-chunk
    executable + decode-only program): the second generates entirely from
    store hits and reproduces the first's tokens."""
    model, params, ids = _tiny_model()

    def run():
        eng = deepspeed_tpu.init_inference(
            model, config={"dtype": "float32", "prefill_chunk_size": 8,
                           "compile_cache": {"enabled": True,
                                             "cache_dir": cache_dir,
                                             "min_compile_time_secs": 0.0}})
        eng.set_params(params)
        return np.asarray(eng.generate(ids, max_new_tokens=4))

    s0 = _snap()
    out1 = run()
    s1 = _snap()
    # split path = two programs, both persisted cold
    assert _delta(s1, s0, "executable_saves") >= 2
    out2 = run()
    s2 = _snap()
    assert _delta(s2, s1, "executable_hits") >= 2
    assert _delta(s2, s1, "executable_saves") == 0
    np.testing.assert_array_equal(out1, out2)


def test_warmup_precompiles_and_reports(cache_dir):
    """warmup() compiles every bucket up front (with per-program compile
    times), generate() then compiles nothing, and a second engine's warmup
    is all store hits (0.0s entries)."""
    model, params, ids = _tiny_model()
    conf = {"dtype": "float32", "prefill_chunk_size": 8,
            "compile_cache": {"enabled": True, "cache_dir": cache_dir,
                              "min_compile_time_secs": 0.0}}

    eng = deepspeed_tpu.init_inference(model, config=conf)
    eng.set_params(params)
    report = eng.warmup(12, 4, batch_sizes=(2,))
    # split-prefill bucket: the chunk program AND the decode-only program
    assert any(k.startswith("prefill_chunk:") for k in report)
    assert any(k.startswith("decode:") for k in report)
    assert all(dt > 0 for dt in report.values())       # cold: real compiles

    s0 = _snap()
    out = np.asarray(eng.generate(ids, max_new_tokens=4))
    s1 = _snap()
    # generate after warmup touches NO compile path at all
    assert _delta(s1, s0, "executable_hits") == 0
    assert _delta(s1, s0, "executable_misses") == 0
    assert _delta(s1, s0, "executable_saves") == 0
    assert out.shape == (2, 16)

    eng2 = deepspeed_tpu.init_inference(model, config=conf)
    eng2.set_params(params)
    report2 = eng2.warmup(12, 4, batch_sizes=(2,))
    assert report2                                     # same buckets
    s2 = _snap()
    assert _delta(s2, s1, "executable_hits") >= 2      # warm: all hits
    np.testing.assert_array_equal(
        out, np.asarray(eng2.generate(ids, max_new_tokens=4)))


def test_engine_warmup_reports_through_monitor(cache_dir, tmp_path):
    """DeepSpeedEngine.warmup: compile time lands in the monitor stream
    (Compile/train_step_secs) and train_batch() reuses the warmed
    executable."""
    config = _train_config(cache_dir)
    config["csv_monitor"] = {"enabled": True, "output_path": str(tmp_path),
                             "job_name": "warmup_test"}
    config["steps_per_print"] = 1
    engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(), config=config)
    batch = jax.tree.map(lambda x: x[None], random_batch(batch_size=16))
    report = engine.warmup(batch=batch)
    assert "train_step" in report
    csv = os.path.join(str(tmp_path), "warmup_test",
                       "Compile_train_step_secs.csv")
    assert os.path.exists(csv)
    s0 = _snap()
    engine.train_batch(batch=batch)
    s1 = _snap()
    assert s1["compile_seconds"] == s0["compile_seconds"]  # nothing new


def test_disabled_cache_keeps_plain_jit_path(tmp_path):
    """compile_cache off (the default): no store traffic, engines behave
    exactly like the seed."""
    s0 = _snap()
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    assert engine._program_cache is None
    batch = jax.tree.map(lambda x: x[None], random_batch(batch_size=16))
    engine.train_batch(batch=batch)
    s1 = _snap()
    for k in ("executable_hits", "executable_misses", "executable_saves"):
        assert _delta(s1, s0, k) == 0


# --------------------------------------------------------------------- #
# Opt-in minimal repro: the serving executable-reload corruption
# (ROADMAP item 4) — a harness for the future root-cause PR, skipped by
# default and xfail(non-strict) when opted in because the corruption is
# NONDETERMINISTIC (~50% of warm runs in the serving kill-harness).
# --------------------------------------------------------------------- #
@pytest.mark.skipif(
    os.environ.get("DSTPU_RUN_CACHE_CORRUPTION_REPRO") != "1",
    reason="opt-in repro harness (ROADMAP item 4): set "
           "DSTPU_RUN_CACHE_CORRUPTION_REPRO=1 to run")
@pytest.mark.xfail(
    strict=False,
    reason="ROADMAP item 4: donated dynamic_update_slice programs reloaded "
           "through jax.stages.Compiled serialization nondeterministically "
           "corrupt the donated workspace (serving opts out of both cache "
           "layers as mitigation; see docs/compile_cache.md)")
def test_repro_donated_dus_chain_through_executable_serialization(tmp_path):
    """Minimal distillation of the serving corruption: TWO donated
    programs chained over ONE workspace — an admit-like
    ``dynamic_update_slice`` lane insert (slot index traced) and a
    decode-like per-row scatter write — both run from
    ``ExecutableStore``-reloaded (serialize/deserialize round-tripped)
    executables, against a fresh-jit reference.  Greedy-deterministic
    math: any divergence is the reload corrupting the donated buffer."""
    N, S, D, ROUNDS = 4, 16, 8, 12

    def admit(big, lane, slot):
        return jax.lax.dynamic_update_slice(big, lane, (slot, 0, 0))

    def decode_step(big, tok, pos):
        row = jnp.arange(N)
        big = big.at[row, pos, :].set(tok)
        out = big.sum(axis=(1, 2))
        return big, out

    store = cc.ExecutableStore(str(tmp_path / "exe"))

    def reloaded(fn, donate, args):
        compiled = jax.jit(fn, donate_argnums=donate).lower(
            *jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args)
        ).compile()
        key = cc.cache_key(fn.__name__, "repro")
        assert store.save(key, compiled)
        exe = store.load(key)
        assert exe is not None, "executable did not round-trip the store"
        return exe

    rng = np.random.default_rng(0)
    lane0 = jnp.asarray(rng.standard_normal((1, S, D)), jnp.float32)
    big0 = jnp.zeros((N, S, D), jnp.float32)
    tok0 = jnp.asarray(rng.standard_normal((N, D)), jnp.float32)

    warm_admit = reloaded(admit, (0,), (big0, lane0, jnp.asarray(0)))
    warm_decode = reloaded(decode_step, (0,),
                           (big0, tok0, jnp.asarray(0, jnp.int32)))
    ref_admit = jax.jit(admit, donate_argnums=(0,))
    ref_decode = jax.jit(decode_step, donate_argnums=(0,))

    def drive(admit_fn, decode_fn):
        big = jnp.zeros((N, S, D), jnp.float32)
        outs = []
        r = np.random.default_rng(7)
        for i in range(ROUNDS):
            lane = jnp.asarray(r.standard_normal((1, S, D)), jnp.float32)
            big = admit_fn(big, lane, jnp.asarray(i % N))
            tok = jnp.asarray(r.standard_normal((N, D)), jnp.float32)
            big, out = decode_fn(big, tok,
                                 jnp.asarray((2 * i) % S, jnp.int32))
            outs.append(np.asarray(out))
        return np.stack(outs), np.asarray(big)

    ref_outs, ref_big = drive(ref_admit, ref_decode)
    warm_outs, warm_big = drive(warm_admit, warm_decode)
    np.testing.assert_array_equal(warm_outs, ref_outs)
    np.testing.assert_array_equal(warm_big, ref_big)
