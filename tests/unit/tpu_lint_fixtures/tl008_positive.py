"""TL008 positive fixture — lock-guarded fields touched outside their
lock.  Expect >= 6 findings.  The module opts into non-self checks:
# tpu-lint: concurrency-scope
"""
import threading


class MiniEngine:
    GUARDED_FIELDS = {"_queue": "_lock", "stats": "_lock"}

    def __init__(self):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._queue = []                 # __init__ writes are exempt
        self.stats = {"n": 0}
        self._mirror = {}                # guarded-by: _lock

    def submit(self, x):
        with self._lock:
            self._queue.append(x)
        self.stats["n"] += 1             # FINDING: after the with block

    def peek(self):
        return len(self._queue)          # FINDING: no lock at all

    def drain_helper(self):              # no caller-holds annotation
        self._mirror.clear()             # FINDING: comment-declared field

    def racy_branch(self):
        if self._queue:                  # FINDING: read
            self._mirror["x"] = 1        # FINDING: write (distinct field)

    def suppressed_monitor(self):
        # a reasoned escape hatch still counts as suppressed, not found
        return len(self._queue)  # tpu-lint: disable=TL008 -- fixture: benign racy monitor read


def metrics(srv):
    # non-self access to a canonical ServingEngine guarded field
    return dict(srv.stats)               # FINDING: no `with srv._lock`
