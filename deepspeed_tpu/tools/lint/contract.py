"""Program-contract extraction and the ``PROGRAMS.lock`` lockfile.

Where the AST rules see source and ``jaxpr_check`` sees pass/fail, this
module extracts a MACHINE-CHECKABLE CONTRACT from what the compiler is
actually handed for every registered hot-path entry point, and locks it in
a committed artifact:

* **primitive multiset** (and its sha256) of the traced jaxpr — a new host
  callback, a surprise sort, a dropped fused scatter all change it;
* **donation-alias count** from the lowered module (``tf.aliasing_output``
  / ``jax.buffer_donor``) — a lost donation shows up as a smaller count,
  not as an HBM cliff three rounds later;
* **collective-op counts** — jaxpr-level (psum / all_gather /
  reduce_scatter / ppermute / all_to_all) for the single-chip programs,
  optimized-HLO-level for the ``parallel/`` sharding plans (pp / tp / edp /
  MiCS via :mod:`deepspeed_tpu.parallel.plans`), so the MULTICHIP dry-run's
  re-measured totals become a statically locked schedule;
* **input/output abstract signatures** — a shape or dtype drift in a
  donated workspace is a contract break, not a runtime surprise;
* **communication-cost budgets** (:mod:`.comm_contract`) — per-collective
  byte volumes per step parsed from the optimized HLO, and a
  ``mesh_scaling`` section locking bytes-per-chip for every sharding plan
  at mesh sizes {1, 2, 4, 8}: "all-gather bytes: 2.1MB -> 67MB" is a
  reviewable regression where a bare count change is not, and a per-chip
  volume that GROWS with mesh size is the replicated-tensor smell the
  ``ds_lint --comm`` prover fails on;
* **memory/FLOP budgets** (:mod:`.mem_contract`, format 3) —
  ``compiled.memory_analysis()`` byte footprints (argument / output /
  temp / alias / live total) and ``cost_analysis()`` flops +
  bytes-accessed for every program and plan: "decode_step temp HBM:
  96MB -> 612MB" fails at lock-diff time instead of surfacing as an OOM
  or an HBM-utilization cliff rounds later, and ``--update`` refuses
  undeclared growth (the ``ds_lint --mem`` gate).  Memory needs a
  compile, so the FAST gate diffs program contracts without it (plans
  carry memory for free on their schedule compile); the per-program
  memory regen is the ``slow``-marked half of the contract tests.

``PROGRAMS.lock`` (repo root, committed) is regenerated-and-diffed by a
tier-1 gate and by ``ds_lint --contracts`` (``--update`` rewrites it); a
contract break fails with a readable per-program diff.

The contracts are defined UNDER THE TIER-1 HARNESS: ``JAX_PLATFORMS=cpu``
with 8 virtual devices (the CLI forces the same environment).  A jax
upgrade may legitimately shift primitive multisets — regenerate with
``--update`` and review the diff like any other lockfile bump.
"""

import hashlib
import json
import os
import re
from typing import List

_ALIAS_ATTRS = ("tf.aliasing_output", "jax.buffer_donor")

# jaxpr-level collective primitives (single-program contracts)
JAXPR_COLLECTIVES = ("psum", "all_gather", "reduce_scatter", "ppermute",
                     "all_to_all", "pmax", "pmin", "pbroadcast")
# optimized-HLO collective ops (sharding-plan schedules) — the same names
# the MULTICHIP dry-run counts (__graft_entry__._collectives_since)
HLO_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                   "all-to-all", "collective-permute")

LOCKFILE_NAME = "PROGRAMS.lock"


def lockfile_path():
    """``PROGRAMS.lock`` next to the package (the repo root)."""
    import deepspeed_tpu
    pkg = os.path.dirname(os.path.abspath(deepspeed_tpu.__file__))
    return os.path.join(os.path.dirname(pkg), LOCKFILE_NAME)


def ensure_harness_env():
    """Force the tier-1 trace environment (CPU platform, 8 virtual
    devices) — a no-op when the backend is already initialized that way;
    raises when it is initialized differently (contracts extracted on
    another topology would never match the lockfile)."""
    os.environ.setdefault("DSTPU_ACCELERATOR", "cpu")
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            flags + " --xla_force_host_platform_device_count=8"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    if jax.default_backend() != "cpu" or jax.device_count() < 8:
        raise RuntimeError(
            f"contract extraction needs the tier-1 harness (CPU backend, "
            f">= 8 virtual devices); got {jax.default_backend()!r} with "
            f"{jax.device_count()} device(s) — the JAX backend was "
            f"initialized before ensure_harness_env() could force it")


# --------------------------------------------------------------------- #
# Extraction
# --------------------------------------------------------------------- #
def _walk_counts(jaxpr, out):
    # a param holding a ClosedJaxpr exposes ``.jaxpr``; remat2 and
    # pallas_call carry a RAW Jaxpr (``.eqns``, no ``.jaxpr``) — missing
    # that second shape would leave every rematerialized attention body
    # (and the Pallas kernel bodies inside it) out of the multiset
    for eqn in jaxpr.eqns:
        out[eqn.primitive.name] = out.get(eqn.primitive.name, 0) + 1
        for v in eqn.params.values():
            sub = getattr(v, "jaxpr", None)
            if sub is None and hasattr(v, "eqns"):
                sub = v
            if sub is not None:
                _walk_counts(sub, out)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    sub = getattr(item, "jaxpr", None)
                    if sub is None and hasattr(item, "eqns"):
                        sub = item
                    if sub is not None:
                        _walk_counts(sub, out)
    return out


def primitive_counts_of(fn, *args):
    """Full primitive multiset {name: count} of the traced program."""
    import jax
    closed = jax.make_jaxpr(fn)(*args)
    return _walk_counts(closed.jaxpr, {}), closed


def _multiset_hash(counts):
    blob = json.dumps(sorted(counts.items()), separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def contract_of_entry_point(ep, with_memory=False):
    """Machine-checkable contract of one :class:`entry_points.EntryPoint`:
    traced primitive multiset + hash, host-callback count, jaxpr-level
    collective counts, lowered donation-alias count, the abstract
    input/output signatures, and the byte-level comm budget (``{}`` for a
    program whose lowering mentions no collective — the single-chip hot
    paths answer without paying for a compile; a mesh-aware program is
    compiled and its optimized HLO costed).

    ``with_memory=True`` additionally compiles the program and locks its
    memory/FLOP budget (:mod:`.mem_contract`) — the expensive half, paid
    by ``--update``/``--mem``/the slow contract test, never by the fast
    tier-1 per-program diff (whose diff skips the memory sections when
    the fresh side omits them)."""
    import jax
    from deepspeed_tpu.tools.lint import comm_contract, mem_contract
    from deepspeed_tpu.tools.lint.jaxpr_check import FORBIDDEN_PRIMITIVES
    counts, closed = primitive_counts_of(ep.fn, *ep.args)
    lowered = ep.fn.lower(*ep.args)
    text = lowered.as_text()
    aliased = sum(text.count(a) for a in _ALIAS_ATTRS)
    comm = {}
    compiled = None
    if with_memory:
        # memory analysis is only trustworthy on a REAL compile — a
        # persistent-cache reload reports degenerate alias bytes
        with mem_contract.fresh_compile_env():
            compiled = lowered.compile()
    elif comm_contract.lowered_has_collectives(text):
        compiled = lowered.compile()
    if comm_contract.lowered_has_collectives(text):
        hlo = compiled.as_text() or ""
        comm = comm_contract.parse_hlo_comm(hlo, jax.device_count())
    c = {
        "kind": "program",
        "primitives": dict(sorted(counts.items())),
        "primitives_sha256": _multiset_hash(counts),
        "host_callbacks": sum(c for p, c in counts.items()
                              if p in FORBIDDEN_PRIMITIVES),
        "collectives": {p: counts[p] for p in JAXPR_COLLECTIVES
                        if p in counts},
        "comm": comm,
        "donation": {"declared": bool(ep.expect_donation),
                     "aliased": aliased,
                     "min_aliased": int(getattr(ep, "min_aliased", 0))},
        "in_avals": [str(a) for a in closed.in_avals],
        "out_avals": [str(a) for a in closed.out_avals],
    }
    if with_memory:
        mem_contract.attach_memory_contract(c, ep.name, compiled)
    return c


def contract_of_plan(plan):
    """Collective-schedule contract of one
    :class:`parallel.plans.PlanProgram`: the counts AND byte volumes of
    every collective op in the OPTIMIZED HLO the plan's fused train step
    compiles to on the 8-device mesh (what the MULTICHIP dry-run measures
    at runtime).  The one compile feeds the count schedule, the comm
    budget AND the memory/FLOP budget — plans pay no extra compile for
    their memory contract.  The compile runs cache-bypassed
    (``fresh_compile_env``): a persistent-cache reload would report the
    plan's donated-alias bytes as 0 and corrupt the locked footprint."""
    from deepspeed_tpu.tools.lint import comm_contract, mem_contract
    with mem_contract.fresh_compile_env():
        compiled = plan.fn.lower(*plan.args).compile()
    text = compiled.as_text() or ""
    counts = {}
    for op in HLO_COLLECTIVES:
        n = len(re.findall(rf"\b{op}(?:-start)?\(", text))
        if n:
            counts[op] = n
    c = {
        "kind": "collective_schedule",
        "mesh": {k: int(v) for k, v in sorted(plan.mesh.items())},
        "world": int(plan.world),
        "collectives": counts,
        "comm": comm_contract.parse_hlo_comm(text, plan.world),
        "expect": sorted(plan.expect),
        "reduction": bool(plan.reduction),
    }
    return mem_contract.attach_memory_contract(c, plan.name, compiled)


def validate_plan_contract(contract):
    """Semantic invariants of a plan schedule (on top of the exact locked
    counts): every expected collective present; reduction plans carry at
    least one all-reduce/reduce-scatter; the comm budget's instance counts
    agree with the count schedule (the two parsers walk the same HLO)."""
    problems = []
    c = contract.get("collectives", {})
    for op in contract.get("expect", []):
        if not c.get(op):
            problems.append(f"expected collective {op!r} absent: {c}")
    if contract.get("reduction") and not (
            c.get("all-reduce", 0) + c.get("reduce-scatter", 0)):
        problems.append(f"no gradient-reduction collective scheduled: {c}")
    comm = contract.get("comm")
    if comm is not None:
        counted = {op: v.get("count", 0) for op, v in comm.items()}
        if counted != c:
            problems.append(
                f"comm-budget instance counts disagree with the count "
                f"schedule: {counted} vs {c}")
    return problems


# --------------------------------------------------------------------- #
# Building the full lockfile
# --------------------------------------------------------------------- #
def program_names():
    from deepspeed_tpu.tools.lint import entry_points
    return [b.__name__ for b in entry_points.BUILDERS]


def build_program_contract(builder_name, with_memory=False):
    """Contract for one entry point, with the global topology reset around
    the engine build (same discipline as the jaxpr-harness tests).
    ``with_memory`` opts into the compile the memory/FLOP budget costs."""
    from deepspeed_tpu.parallel.topology import reset_topology
    from deepspeed_tpu.tools.lint import entry_points
    reset_topology()
    try:
        ep = getattr(entry_points, builder_name)()
        return ep.name, contract_of_entry_point(ep,
                                                with_memory=with_memory)
    finally:
        reset_topology()


def build_plan_contract(plan_builder_name):
    from deepspeed_tpu.parallel import plans
    from deepspeed_tpu.parallel.topology import reset_topology
    reset_topology()
    try:
        plan = getattr(plans, plan_builder_name)()
        return plan.name, contract_of_plan(plan)
    finally:
        reset_topology()


def build_plan_scaling_contract(plan_builder_name, full_contract=None):
    """The mesh-scaling contract of one plan family.  ``full_contract``
    optionally supplies the already-compiled full-mesh (world=8) schedule
    contract so its point is derived instead of re-compiled — the gate and
    ``build_all`` both reuse the canonical compile, which also makes the
    table's top row definitionally consistent with the locked schedule."""
    from deepspeed_tpu.parallel import plans
    from deepspeed_tpu.tools.lint import comm_contract
    builder = getattr(plans, plan_builder_name)
    reuse_rows = {}
    if full_contract is not None:
        reuse_rows[full_contract["world"]] = comm_contract.scaling_entry(
            full_contract["world"], full_contract["mesh"],
            full_contract.get("comm", {}))
    return comm_contract.build_scaling_contract(builder,
                                                reuse_rows=reuse_rows)


def build_all(progress=None, with_memory=True):
    """Regenerate every contract.  Returns the lockfile dict.
    ``with_memory=True`` (the default — ``--update`` and the CLI gates
    want the full format-3 artifact) compiles every program for its
    memory/FLOP budget; the fast tier-1 tests never call this."""
    import jax
    import jaxlib
    from deepspeed_tpu.parallel import plans
    programs, schedules, scaling = {}, {}, {}
    for bname in program_names():
        if progress:
            progress(f"tracing {bname}"
                     + (" (+memory compile)" if with_memory else ""))
        name, c = build_program_contract(bname, with_memory=with_memory)
        programs[name] = c
    for build in plans.PLAN_BUILDERS:
        if progress:
            progress(f"compiling plan {build.__name__}")
        name, c = build_plan_contract(build.__name__)
        schedules[name] = c
        if progress:
            progress(f"scaling {build.__name__} over mesh "
                     f"{plans.MESH_POINTS}")
        sname, sc = build_plan_scaling_contract(build.__name__,
                                                full_contract=c)
        scaling[sname or name] = sc
    return {
        "_meta": {
            "format": 3,
            "harness": "JAX_PLATFORMS=cpu, 8 virtual devices (tier-1)",
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "regenerate": "bin/ds_lint --contracts --update",
        },
        "programs": programs,
        "collective_schedules": schedules,
        "mesh_scaling": scaling,
    }


def load_lockfile(path=None):
    path = path or lockfile_path()
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def write_lockfile(lock, path=None):
    path = path or lockfile_path()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(lock, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


# --------------------------------------------------------------------- #
# Readable per-program diffs
# --------------------------------------------------------------------- #
def _diff_counts(label, old, new, out):
    keys = sorted(set(old) | set(new))
    changed = [(k, old.get(k, 0), new.get(k, 0)) for k in keys
               if old.get(k, 0) != new.get(k, 0)]
    for k, o, n in changed:
        out.append(f"  {label}.{k}: {o} -> {n}")
    return bool(changed)


def _diff_comm(locked, fresh, out):
    """Byte-level comm-budget diff lines — the readable half of a comm
    regression: 'all-gather bytes: 2.1MB -> 67MB per step'."""
    from deepspeed_tpu.tools.lint.comm_contract import fmt_bytes
    for op in sorted(set(locked) | set(fresh)):
        lo = locked.get(op, {})
        fr = fresh.get(op, {})
        lb, fb = lo.get("bytes_per_step", 0), fr.get("bytes_per_step", 0)
        if lb != fb:
            out.append(f"  {op} bytes: {fmt_bytes(lb)} -> {fmt_bytes(fb)} "
                       f"per step")
        if lo.get("count", 0) != fr.get("count", 0):
            out.append(f"  comm.{op} instances: {lo.get('count', 0)} -> "
                       f"{fr.get('count', 0)}")


def _diff_mem(locked, fresh, out):
    """Memory/FLOP budget diff (tolerance-banded byte stories) — only
    when the FRESH side carries the sections: the fast tier-1 gate
    regenerates contracts without the memory compile and must not read
    a locked budget as a break (``ds_lint --mem`` and the slow contract
    test regenerate WITH memory and do diff it)."""
    from deepspeed_tpu.tools.lint import mem_contract
    if "memory" not in fresh and "cost" not in fresh:
        return
    out.extend(mem_contract.diff_memory("", locked, fresh))


def _schedule_summary(contract):
    """One-line schedule rendering (counts + bytes when budgeted) for the
    side-by-side view of a changed schedule."""
    from deepspeed_tpu.tools.lint.comm_contract import fmt_bytes
    counts = contract.get("collectives", {})
    comm = contract.get("comm", {})
    parts = []
    for op in sorted(counts):
        b = comm.get(op, {}).get("bytes_per_step")
        parts.append(f"{op} x{counts[op]}"
                     + (f" ({fmt_bytes(b)})" if b is not None else ""))
    return "{" + ", ".join(parts) + "}" if parts else "{none}"


def diff_program(name, locked, fresh):
    """Readable field-by-field diff of one program's contract.  Empty list
    = contracts match."""
    out: List[str] = []
    if locked.get("kind") != fresh.get("kind"):
        out.append(f"  kind: {locked.get('kind')} -> {fresh.get('kind')}")
    if locked.get("kind") == "collective_schedule" or \
            fresh.get("kind") == "collective_schedule":
        changed = _diff_counts("collectives", locked.get("collectives", {}),
                               fresh.get("collectives", {}), out)
        _diff_comm(locked.get("comm", {}) or {},
                   fresh.get("comm", {}) or {}, out)
        _diff_mem(locked, fresh, out)
        for field in ("mesh", "expect", "reduction", "world"):
            if locked.get(field) != fresh.get(field):
                out.append(f"  {field}: {locked.get(field)} -> "
                           f"{fresh.get(field)}")
        if changed:
            # a schedule change is easier to review whole than as field
            # paths: print the old and new schedules side by side
            out.append(f"  schedule: {_schedule_summary(locked)}")
            out.append(f"         -> {_schedule_summary(fresh)}")
        return [f"{name}:"] + out if out else []
    if locked.get("primitives_sha256") != fresh.get("primitives_sha256"):
        _diff_counts("primitives", locked.get("primitives", {}),
                     fresh.get("primitives", {}), out)
        out.append(f"  primitives_sha256: "
                   f"{locked.get('primitives_sha256')} -> "
                   f"{fresh.get('primitives_sha256')}")
    if locked.get("host_callbacks", 0) != fresh.get("host_callbacks", 0):
        out.append(f"  host_callbacks: {locked.get('host_callbacks', 0)} "
                   f"-> {fresh.get('host_callbacks', 0)} (a host callback "
                   f"stalls every dispatch on the host link)")
    _diff_counts("collectives", locked.get("collectives", {}),
                 fresh.get("collectives", {}), out)
    _diff_comm(locked.get("comm", {}) or {}, fresh.get("comm", {}) or {},
               out)
    _diff_mem(locked, fresh, out)
    ld, fd = locked.get("donation", {}), fresh.get("donation", {})
    if ld != fd:
        out.append(f"  donation: declared={ld.get('declared')} "
                   f"aliased={ld.get('aliased')} -> "
                   f"declared={fd.get('declared')} "
                   f"aliased={fd.get('aliased')}"
                   + (" (LOST donation: input and output copies now both "
                      "live)" if fd.get("aliased", 0) < ld.get("aliased", 0)
                      else ""))
    for field in ("in_avals", "out_avals"):
        lo, fr = locked.get(field, []), fresh.get(field, [])
        if lo != fr:
            if len(lo) != len(fr):
                out.append(f"  {field}: {len(lo)} -> {len(fr)} leaves")
            for i, (a, b) in enumerate(zip(lo, fr)):
                if a != b:
                    out.append(f"  {field}[{i}]: {a} -> {b}")
    return [f"{name}:"] + out if out else []


def diff_lockfiles(locked, fresh):
    """Full diff: per-program field diffs plus added/removed programs.
    Empty list = lockfile up to date."""
    from deepspeed_tpu.tools.lint.comm_contract import diff_scaling
    out: List[str] = []
    for section in ("programs", "collective_schedules", "mesh_scaling"):
        lsec = locked.get(section, {})
        fsec = fresh.get(section, {})
        for name in sorted(set(lsec) | set(fsec)):
            if name not in fsec:
                out.append(f"{name}: locked but no longer extracted — "
                           f"remove via --contracts --update")
            elif name not in lsec:
                out.append(f"{name}: not in {LOCKFILE_NAME} — new program; "
                           f"add via --contracts --update")
            elif section == "mesh_scaling":
                out.extend(diff_scaling(name, lsec[name], fsec[name]))
            else:
                out.extend(diff_program(name, lsec[name], fsec[name]))
    return out


def check_against_lockfile(path=None, progress=None):
    """(ok, diff_lines).  Regenerates every contract and diffs against the
    committed lockfile."""
    path = path or lockfile_path()
    if not os.path.exists(path):
        return False, [f"{path} missing — generate with "
                       f"ds_lint --contracts --update"]
    locked = load_lockfile(path)
    fresh = build_all(progress=progress)
    diff = diff_lockfiles(locked, fresh)
    for name, c in sorted(fresh.get("collective_schedules", {}).items()):
        for problem in validate_plan_contract(c):
            diff.append(f"{name}: plan invariant broken — {problem}")
    from deepspeed_tpu.tools.lint import mem_contract
    from deepspeed_tpu.tools.lint.comm_contract import \
        validate_scaling_contract
    for name, c in sorted(fresh.get("mesh_scaling", {}).items()):
        diff.extend(validate_scaling_contract(name, c))
    for section in ("programs", "collective_schedules"):
        for name, c in sorted(fresh.get(section, {}).items()):
            diff.extend(mem_contract.validate_memory_contract(name, c))
    return not diff, diff


def main(update=False):
    ensure_harness_env()
    progress = lambda msg: print(f"[contracts] {msg}", flush=True)
    if update:
        lock = build_all(progress=progress)
        # memory-growth ratchet: an --update that would lock a byte
        # footprint grown beyond tolerance over the COMMITTED artifact
        # is refused unless the program declares the growth with a
        # reason (mem_contract.DECLARED_GROWTH) — memory bloat cannot
        # land through a routine lockfile bump
        from deepspeed_tpu.tools.lint import mem_contract
        try:
            old = load_lockfile()
        except FileNotFoundError:
            old = {}
        problems = []
        for section in ("programs", "collective_schedules"):
            for name, fresh_c in sorted(lock.get(section, {}).items()):
                problems.extend(mem_contract.growth_problems(
                    name, old.get(section, {}).get(name), fresh_c))
        if problems:
            print(f"[contracts] UPDATE REFUSED — memory growth beyond "
                  f"the {mem_contract.MEM_TOLERANCE:.0%} tolerance:")
            for p in problems:
                print(f"  {p}")
            return 1
        path = write_lockfile(lock)
        n = len(lock["programs"]) + len(lock["collective_schedules"])
        print(f"[contracts] wrote {n} contracts to {path}")
        return 0
    ok, diff = check_against_lockfile(progress=progress)
    if ok:
        print(f"[contracts] OK — {LOCKFILE_NAME} matches every extracted "
              f"contract")
        return 0
    print(f"[contracts] CONTRACT BREAK — {LOCKFILE_NAME} does not match "
          f"the extracted contracts:")
    for line in diff:
        print(f"  {line}")
    print("[contracts] intentional? regenerate with "
          "ds_lint --contracts --update and commit the diff")
    return 1


if __name__ == "__main__":
    import sys
    sys.exit(main(update="--update" in sys.argv))
