"""DeepSpeedTransformerInference — the stateful decode wrapper.

Reference parity: ``model_implementations/transformers/ds_transformer.py:19``
(the module the reference injects per layer, holding fused kernels + the KV
workspace).  TPU-native version: holds the whole converted flax
``Transformer`` plus its KV cache, exposing a torch-like stateful
``forward`` for incremental decoding.  The per-step program is one jitted
XLA computation with the cache donated, so repeated calls replay a compiled
executable — the analog of the reference's CUDA-graph path.
"""

from functools import partial

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer import Transformer, TransformerConfig


class DeepSpeedTransformerInference:

    def __init__(self, config: TransformerConfig, params=None, max_batch=1,
                 max_seq_len=None):
        self.config = config
        self.module = Transformer(config)
        self.params = params
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len or config.max_seq_len
        self._cache = None
        self._pos = 0

        @partial(jax.jit, donate_argnums=(2,))
        def _step(params, ids, cache, start_pos):
            return self.module.apply(params, ids, cache, start_pos,
                                     method=Transformer.decode)
        self._step = _step

    def reset_cache(self, batch_size=None):
        self._cache = self.module.init_cache(batch_size or self.max_batch,
                                             self.max_seq_len)
        self._pos = 0

    def forward(self, input_ids):
        """Incremental forward: feed the prompt once, then one token at a
        time; returns logits for the fed positions.  Raises on cache
        overflow — call ``reset_cache`` to start a new sequence."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if self._cache is None:
            self.reset_cache(input_ids.shape[0])
        if self._pos + input_ids.shape[1] > self.max_seq_len:
            raise ValueError(
                f"KV cache overflow: {self._pos} + {input_ids.shape[1]} "
                f"tokens > max_seq_len={self.max_seq_len}; reset_cache() to "
                f"start a new sequence")
        logits, self._cache = self._step(self.params, input_ids, self._cache,
                                         jnp.int32(self._pos))
        self._pos += input_ids.shape[1]
        return logits

    __call__ = forward
