"""Universal checkpoint + offline tools — the analog of reference
``tests/unit/checkpoint/test_zero_optimizer.py`` elastic-resize tests and
``zero_to_fp32`` merge tests: save at one topology, inspect offline, convert
to universal, reload at a different topology, continue training."""

import os

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.checkpoint import (
    DeepSpeedCheckpoint, ZeROCheckpoint, convert_to_universal,
    load_hp_checkpoint_state, load_universal_into_engine,
    reshape_flat_state_dict, split_tp_shards, merge_tp_shards)
from deepspeed_tpu.utils.zero_to_fp32 import (
    get_fp32_state_dict_from_zero_checkpoint,
    convert_zero_checkpoint_to_fp32_state_dict)

from simple_model import SimpleModel, random_batch


def make_engine(stage=1, tp=1):
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": stage},
            "tensor_parallel": {"tp_size": tp},
        })
    return engine


def train(engine, steps=3, seed=0):
    for i in range(steps):
        loss = engine(random_batch(seed=seed + i))
        engine.backward(loss)
        engine.step()
    return loss


def flat_params(engine):
    from deepspeed_tpu.runtime.zero.partition import path_to_str
    return {path_to_str(p): np.asarray(jax.device_get(l)) for p, l in
            jax.tree_util.tree_flatten_with_path(engine.params)[0]}


def test_offline_inspection(tmp_path):
    engine = make_engine(stage=2)
    train(engine, steps=2)
    engine.save_checkpoint(tmp_path)

    ckpt = DeepSpeedCheckpoint(str(tmp_path))
    assert ckpt.tag == "global_step2"
    assert ckpt.global_steps == 2
    live = flat_params(engine)
    assert set(ckpt.parameter_names()) == set(live.keys())
    for name, arr in ckpt.flat_parameters().items():
        np.testing.assert_allclose(arr, live[name], rtol=1e-6)

    zck = ZeROCheckpoint(str(tmp_path))
    moments = zck.flat_optimizer_moments()
    assert moments, "no optimizer moments found in checkpoint"
    for field, per_param in moments.items():
        assert set(per_param.keys()) == set(live.keys())


def test_universal_roundtrip_and_resharding(tmp_path):
    # Save while running pure-DP over 8 devices...
    src = make_engine(stage=2, tp=1)
    train(src, steps=3)
    src_params = flat_params(src)
    src.save_checkpoint(tmp_path / "ckpt")
    convert_to_universal(tmp_path / "ckpt", tmp_path / "uni")

    state = load_hp_checkpoint_state(tmp_path / "uni",
                                     sorted(src_params.keys())[0])
    assert state["fp32"].dtype == np.float32

    # ...reload into an engine running tp=2 (different mesh layout).
    dst = make_engine(stage=1, tp=2)
    dst(random_batch())  # materialise params
    load_universal_into_engine(dst, tmp_path / "uni")
    assert dst.global_steps == 3
    for name, arr in flat_params(dst).items():
        np.testing.assert_allclose(arr, src_params[name], rtol=1e-5,
                                   err_msg=name)
    # still trainable at the new topology
    train(dst, steps=1, seed=100)
    assert dst.global_steps == 4


def test_zero_to_fp32(tmp_path):
    engine = make_engine(stage=3)
    train(engine, steps=2)
    engine.save_checkpoint(tmp_path)

    sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
    live = flat_params(engine)
    assert set(sd.keys()) == set(live.keys())
    for name, arr in sd.items():
        assert arr.dtype == np.float32
        np.testing.assert_allclose(arr, live[name].astype(np.float32),
                                   rtol=1e-6)

    out = tmp_path / "pytorch_model.bin"
    convert_zero_checkpoint_to_fp32_state_dict(str(tmp_path), str(out))
    import torch
    loaded = torch.load(str(out))
    assert set(loaded.keys()) == set(live.keys())


def test_tp_reshape_roundtrip():
    rng = np.random.default_rng(0)
    full_col = rng.standard_normal((8, 32)).astype(np.float32)   # [in, out]
    full_row = rng.standard_normal((32, 8)).astype(np.float32)
    flat = {
        "layers.attn.q_proj.kernel": split_tp_shards(full_col, 2, dim=-1),
        "layers.attn.o_proj.kernel": split_tp_shards(full_row, 2, dim=0),
        "final_norm.scale": [rng.standard_normal(8).astype(np.float32)] * 2,
    }
    out = reshape_flat_state_dict(flat, source_degree=2, target_degree=4)
    assert len(out["layers.attn.q_proj.kernel"]) == 4
    np.testing.assert_allclose(
        merge_tp_shards(out["layers.attn.q_proj.kernel"], dim=-1), full_col)
    np.testing.assert_allclose(
        merge_tp_shards(out["layers.attn.o_proj.kernel"], dim=0), full_row)
    np.testing.assert_allclose(out["final_norm.scale"][3],
                               flat["final_norm.scale"][0])


def test_engine_checkpoint_reshards_across_topologies(tmp_path):
    """The DistributedFixture elastic-resize analog (reference
    ``tests/unit/checkpoint/test_zero_optimizer.py``): save under
    ZeRO-3/dp=8, load into a FRESH engine on tp=2 x dp=4 — values identical,
    params re-placed under the new plan (tp-sharded), training continues."""
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
    from deepspeed_tpu.parallel.topology import reset_topology

    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=16, dtype="float32",
                            use_flash_attention=False, remat=False)
    base = {"train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (8, 16)).astype(np.int32)

    try:
        reset_topology()
        e1, *_ = deepspeed_tpu.initialize(
            model=Transformer(cfg),
            config={**base, "zero_optimization": {"stage": 3}})
        for _ in range(2):
            loss = e1({"input_ids": ids})
            e1.backward(loss)
            e1.step()
        e1.save_checkpoint(str(tmp_path))
        before = jax.device_get(e1.params)

        reset_topology()
        e2, *_ = deepspeed_tpu.initialize(
            model=Transformer(cfg),
            config={**base, "zero_optimization": {"stage": 1},
                    "tensor_parallel": {"tp_size": 2}})
        e2.load_checkpoint(str(tmp_path))
        jax.tree.map(np.testing.assert_array_equal, before,
                     jax.device_get(e2.params))
        assert e2.global_steps == e1.global_steps
        tp_leaves = [l for _, l in
                     jax.tree_util.tree_leaves_with_path(e2.params)
                     if "tp" in str(l.sharding.spec)]
        assert tp_leaves, "no leaf tp-sharded after reshard-on-load"
        loss = e2({"input_ids": ids})
        e2.backward(loss)
        e2.step()
        assert np.isfinite(float(jax.device_get(loss)))
    finally:
        reset_topology()
