"""CompiledGraphModule — the CUDA-graph feature mixin, TPU-native.

Reference parity: ``model_implementations/features/cuda_graph.py`` (the
``CUDAGraph`` ABC mixed into DSVAE/DSUNet/DSClipEncoder: capture once, replay
per call).  On TPU a jitted function IS a captured graph — XLA compiles one
executable per input shape and replays it; this mixin adds the reference's
explicit shape-keyed executable cache and enable/disable switch.
"""

import jax


class CompiledGraphModule:
    """Wraps an ``apply(params, *args)`` callable in the capture/replay
    contract of the reference mixin.  jax.jit itself keys compiled
    executables by input shape/dtype, so replay is one dispatch per call and
    capture happens implicitly on the first call per shape."""

    def __init__(self, apply_fn, enable_cuda_graph=True, donate_argnums=()):
        self._apply_fn = apply_fn
        self.enable_cuda_graph = enable_cuda_graph
        self._jitted = jax.jit(apply_fn, donate_argnums=donate_argnums)
        self.iter_count = 0

    def _graph_replay(self, params, *args, **kwargs):
        return self._jitted(params, *args, **kwargs)

    def __call__(self, params, *args, **kwargs):
        self.iter_count += 1
        if self.enable_cuda_graph:
            return self._graph_replay(params, *args, **kwargs)
        return self._apply_fn(params, *args, **kwargs)
