"""Elastic training config solver.

Parity with reference ``elasticity/elasticity.py`` (v0.1 ``:83``, v0.2
``:126``, ``compute_elastic_config:233``): given candidate micro-batch sizes
and a chip-count range, find configurations where

    global_batch = micro_batch × gradient_accumulation × world_size

stays constant as the world resizes — so a preempted/resized TPU slice
resumes with identical optimization dynamics.  TPU specifics: valid world
sizes are the slice shapes (multiples of the ICI topology), handled via the
``valid_world_sizes`` hook.
"""

import json

from deepspeed_tpu.utils.logging import logger

ELASTICITY = "elasticity"
LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


def get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    """All batch sizes b = base * 2^k ≤ max (reference v0.1 candidate gen)."""
    candidates = set()
    for base in base_list:
        b = base
        while b <= max_acceptable_batch_size:
            candidates.add(b)
            b *= 2
    return sorted(candidates)


def get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size,
                            min_gpus=1, max_gpus=10000):
    """v0.1: find (final_batch, valid_world_sizes) (reference ``:83``)."""
    candidates = get_candidate_batch_sizes(micro_batches,
                                           max_acceptable_batch_size)
    if not candidates:
        raise ElasticityConfigError(
            f"no candidate batch size ≤ {max_acceptable_batch_size} "
            f"from micro batches {micro_batches}")
    final_batch = max(candidates)
    valid = set()
    for w in range(min_gpus, max_gpus + 1):
        for mb in micro_batches:
            if final_batch % (mb * w) == 0:
                valid.add(w)
                break
    return final_batch, sorted(valid)


def get_compatible_gpus_v02(micro_batches, max_acceptable_batch_size,
                            current_num_gpus, min_gpus=1, max_gpus=10000,
                            prefer_larger=True, num_gpus_per_node=1):
    """v0.2: node-granular worlds (reference ``:126``) — on TPU, 'node'
    granularity = hosts in a slice."""
    candidates = get_candidate_batch_sizes(micro_batches,
                                           max_acceptable_batch_size)
    valid_worlds = []
    for n_nodes in range(max(1, min_gpus // num_gpus_per_node),
                         max_gpus // num_gpus_per_node + 1):
        w = n_nodes * num_gpus_per_node
        if any(b % (mb * w) == 0 for b in candidates for mb in micro_batches):
            valid_worlds.append(w)
    if not valid_worlds:
        raise ElasticityConfigError("no compatible world sizes found")
    final_batch, _ = get_compatible_gpus_v01(micro_batches,
                                             max_acceptable_batch_size,
                                             min_gpus, max_gpus)
    return final_batch, valid_worlds


def _get_microbatch_gas(final_batch, micro_batches, world_size, prefer_larger):
    options = []
    for mb in micro_batches:
        if final_batch % (mb * world_size) == 0:
            options.append((mb, final_batch // (mb * world_size)))
    if not options:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} incompatible with global batch "
            f"{final_batch} and micro batches {micro_batches}")
    options.sort(key=lambda t: t[0], reverse=prefer_larger)
    return options[0]


def compute_elastic_config(ds_config, target_deepspeed_version=None,
                           world_size=0, return_microbatch=False):
    """Resolve the elastic config (reference ``compute_elastic_config:233``).

    Returns (final_batch_size, valid_world_sizes[, micro_batch]) and, when
    ``world_size`` > 0, asserts compatibility and computes the
    (micro_batch, gas) pair.
    """
    if isinstance(ds_config, str):
        with open(ds_config) as f:
            ds_config = json.load(f)
    elastic = ds_config.get(ELASTICITY)
    if not elastic or not elastic.get("enabled", False):
        raise ElasticityConfigError("'elasticity' block missing or disabled")
    micro_batches = elastic.get("micro_batch_sizes", [2, 4, 6])
    max_batch = elastic.get("max_train_batch_size", 2000)
    min_gpus = elastic.get("min_gpus", 1)
    max_gpus = elastic.get("max_gpus", 10000)
    prefer_larger = elastic.get("prefer_larger_batch", True)
    version = elastic.get("version", LATEST_ELASTICITY_VERSION)

    if float(version) >= 0.2:
        gpus_per_node = elastic.get("num_gpus_per_node", 1)
        final_batch, valid = get_compatible_gpus_v02(
            micro_batches, max_batch, world_size, min_gpus, max_gpus,
            prefer_larger, gpus_per_node)
    else:
        final_batch, valid = get_compatible_gpus_v01(
            micro_batches, max_batch, min_gpus, max_gpus)

    if world_size > 0:
        if world_size not in valid:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in valid sizes {valid[:20]}...")
        mb, gas = _get_microbatch_gas(final_batch, micro_batches, world_size,
                                      prefer_larger)
        logger.info(f"elastic config: global={final_batch} micro={mb} gas={gas} "
                    f"world={world_size}")
        if return_microbatch:
            return final_batch, valid, mb
        return final_batch, valid
    if return_microbatch:
        return final_batch, valid, None
    return final_batch, valid


def ensure_immutable_elastic_config(runtime_elastic_config_dict, ref_dict=None):
    """Reference ``:208``: the elasticity block must not change between
    resumes (it defines the invariant)."""
    if ref_dict is not None and runtime_elastic_config_dict != ref_dict:
        raise ElasticityConfigError(
            "elasticity config changed across restarts; the global batch "
            "invariant would break")
    return True
