"""DSClipEncoder — reference
``model_implementations/transformers/clip_encoder.py`` (``DSClipEncoder``):
wraps a CLIP text encoder for diffusion pipelines, managing the causal mask
and graph capture.  TPU version: shape-keyed jit replay + the CLIP-style
additive causal mask builder the reference constructs by hand."""

import jax.numpy as jnp

from deepspeed_tpu.model_implementations.features.cuda_graph import (
    CompiledGraphModule)


def build_causal_attention_mask(bsz, seq_len, dtype=jnp.float32):
    """CLIP's additive causal mask (reference ``_build_causal_attention_mask``)."""
    mask = jnp.full((seq_len, seq_len), jnp.finfo(dtype).min, dtype)
    mask = jnp.triu(mask, k=1)
    return jnp.broadcast_to(mask[None, None], (bsz, 1, seq_len, seq_len))


class DSClipEncoder:

    def __init__(self, enc, params=None, enable_cuda_graph=True):
        self.enc = enc
        self.params = params
        self.config = getattr(enc, "config", None)
        apply = (lambda p, ids: enc.apply(p, ids)) if hasattr(enc, "apply") \
            else (lambda p, ids: enc(ids))
        self._forward = CompiledGraphModule(apply, enable_cuda_graph)

    def __call__(self, input_ids, params=None, **kwargs):
        return self._forward(params if params is not None else self.params,
                             input_ids)
