"""Inference engine tests — analog of reference
``tests/unit/inference/test_inference.py``: KV-cached decode must agree with
the full forward pass, generation must run jitted with TP sharding."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig


def tiny_cfg(**over):
    base = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, use_flash_attention=False, dtype="float32")
    base.update(over)
    return TransformerConfig(**base)


@pytest.fixture
def model_and_params():
    model = Transformer(tiny_cfg())
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (2, 12)), jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    return model, params, ids


def test_cached_decode_matches_full_forward(model_and_params):
    """Prefill+decode with KV cache must reproduce teacher-forced logits."""
    model, params, ids = model_and_params
    full_logits = model.apply(params, ids, method=Transformer.logits)

    cache = model.init_cache(2, 12)
    # prefill first 8 tokens, then decode one at a time
    logits_p, cache = model.apply(params, ids[:, :8], cache, 0,
                                  method=Transformer.decode)
    np.testing.assert_allclose(np.asarray(logits_p), np.asarray(full_logits[:, :8]),
                               atol=2e-4, rtol=2e-4)
    pos = 8
    for t in range(8, 12):
        step_logits, cache = model.apply(params, ids[:, t:t + 1], cache, pos,
                                         method=Transformer.decode)
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full_logits[:, t]),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"decode step {t} diverged")
        pos += 1


def test_carry_params_variants_agree(model_and_params):
    """``make_generate_fn``'s two scan structures — params riding the
    carry (materializing dequants) vs closed over as argument buffers
    (fusable/no dequant, the bs128 HBM fix) — must produce identical
    tokens; only where the weight buffers live differs."""
    from deepspeed_tpu.inference.engine import make_generate_fn
    model, params, ids = model_and_params
    rng = jax.random.key(7)
    outs = []
    for carry in (False, True):
        fn = make_generate_fn(model, jnp.float32, ids.shape[1], 8,
                              False, 1.0, 0, 1.0, carry_params=carry)
        cache = model.init_cache(ids.shape[0], ids.shape[1] + 8,
                                 dtype=jnp.float32)
        outs.append(np.asarray(fn(params, cache, ids, rng, -1)[0]))
    np.testing.assert_array_equal(outs[0], outs[1])
    # and the masked (padded-prompt) variant, sampled, both ways
    mask = np.ones(ids.shape, np.int32)
    mask[1, -3:] = 0
    outs = []
    for carry in (False, True):
        fn = make_generate_fn(model, jnp.float32, ids.shape[1], 8,
                              True, 0.8, 0, 0.9, with_mask=True,
                              carry_params=carry)
        cache = model.init_cache(ids.shape[0], ids.shape[1] + 8,
                                 dtype=jnp.float32)
        outs.append(np.asarray(fn(params, cache, ids, rng, -1,
                                  jnp.asarray(mask))[0]))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_greedy_generation_deterministic(model_and_params):
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    engine.set_params(params)
    out1 = engine.generate(ids, max_new_tokens=8)
    out2 = engine.generate(ids, max_new_tokens=8)
    assert out1.shape == (2, 20)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_greedy_matches_no_cache_rollout(model_and_params):
    """Greedy generate must equal the naive re-forward argmax rollout."""
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    engine.set_params(params)
    gen = np.asarray(engine.generate(ids, max_new_tokens=6))

    seq = np.asarray(ids)
    for _ in range(6):
        logits = model.apply(params, jnp.asarray(seq), method=Transformer.logits)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(gen, seq)


def test_sampled_generation_runs(model_and_params):
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    engine.set_params(params)
    out = engine.generate(ids, max_new_tokens=5, do_sample=True,
                          temperature=0.8, top_k=10, top_p=0.9, seed=7)
    assert out.shape == (2, 17)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < 97))


def test_eos_early_stop(model_and_params):
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    engine.set_params(params)
    # force eos = whatever greedy emits first → everything after must be eos
    first = int(np.asarray(engine.generate(ids, max_new_tokens=1))[0, -1])
    out = np.asarray(engine.generate(ids, max_new_tokens=6, eos_token_id=first))
    assert np.all(out[0, ids.shape[1]:] == first)


def test_inference_tp_sharding(model_and_params):
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32",
                       "tensor_parallel": {"tp_size": 2}})
    engine.set_params(params)
    assert engine.topology.tp == 2
    leaves = jax.tree.leaves(engine.params)
    assert any("tp" in str(l.sharding.spec) for l in leaves), \
        "no inference param sharded over tp"
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (2, 16)


def test_weight_quantized_inference():
    """INT8-at-rest inference (reference ``runtime/weight_quantizer.py``
    WeightQuantization): params stored int8+scales, dequantized in-trace;
    logits stay close to the fp32 path and generate still runs greedily."""
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.runtime.weight_quantizer import QuantizedWeight

    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=32, dtype="float32",
                            use_flash_attention=False, remat=False)
    model = Transformer(cfg)
    ids = np.random.default_rng(0).integers(0, 64, (1, 8)).astype(np.int32)
    params = model.init(jax.random.key(0), {"input_ids": jnp.asarray(ids)})

    ref = InferenceEngine(model, DeepSpeedInferenceConfig(dtype="float32"),
                          params=params)
    want = np.asarray(ref.forward(ids))

    qcfg = DeepSpeedInferenceConfig(dtype="float32",
                                    quant={"enabled": True, "bits": 8,
                                           "group_size": 32})
    eng = InferenceEngine(model, qcfg, params=params)
    # storage really is int8 for matrices
    q_leaves = [l for l in jax.tree.leaves(
        eng.params, is_leaf=lambda x: isinstance(x, QuantizedWeight))
        if isinstance(l, QuantizedWeight)]
    assert q_leaves and all(l.q.dtype == jnp.int8 for l in q_leaves)

    got = np.asarray(eng.forward(ids))
    # int8 groupwise: small relative error on logits
    assert np.mean(np.abs(got - want)) / (np.mean(np.abs(want)) + 1e-9) < 0.1
    # top-1 agreement on most positions (greedy decoding quality proxy)
    agree = np.mean(np.argmax(got, -1) == np.argmax(want, -1))
    assert agree >= 0.7, agree
    out = eng.generate(ids, max_new_tokens=4)
    assert np.asarray(out).shape == (1, 12)

    # int4: payload really is nibble-packed (half the int8 bytes)
    q4cfg = DeepSpeedInferenceConfig(dtype="float32",
                                     quant={"enabled": True, "bits": 4,
                                            "group_size": 32})
    eng4 = InferenceEngine(model, q4cfg, params=params)
    q4 = [l for l in jax.tree.leaves(
        eng4.params, is_leaf=lambda x: isinstance(x, QuantizedWeight))
        if isinstance(l, QuantizedWeight)]
    assert q4 and all(l.q.dtype == jnp.uint8 for l in q4 if l.bits == 4)
    i8 = {id(l): l.q.nbytes for l in q_leaves}
    assert sum(l.q.nbytes for l in q4) < sum(i8.values())
    got4 = np.asarray(eng4.forward(ids))
    assert np.isfinite(got4).all()


def test_init_inference_kv_cache_quant_knob():
    """``quant.kv_cache`` through init_inference flips the model-config
    int8-KV knob on decoder models and warns (not fails) on models
    without one."""
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
    import deepspeed_tpu

    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=64, dtype="float32",
                            use_flash_attention=False, scan_layers=False)
    eng = deepspeed_tpu.init_inference(
        Transformer(cfg),
        config={"dtype": "float32", "quant": {"kv_cache": True}})
    assert eng.module.config.kv_cache_quant
    eng.init_params()
    ids = np.random.default_rng(0).integers(0, 64, (2, 8)).astype(np.int32)
    out = np.asarray(eng.generate(ids, max_new_tokens=6))
    assert out.shape == (2, 14)
    assert (out >= 0).all() and (out < 64).all()


def test_untrusted_pickle_checkpoint_gated(model_and_params, tmp_path,
                                           monkeypatch):
    """Single-file checkpoint probing must never execute pickled code
    (reference loads checkpoints via torch.load; here weights_only probing
    plus an explicit opt-in gate for legacy pickled pytrees)."""
    import os
    import pickle
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    model, params, ids = model_and_params
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(dtype="float32"))

    marker = tmp_path / "pwned"
    class Evil:
        def __reduce__(self):
            return (os.system, (f"touch {marker}",))
    evil = tmp_path / "evil.pt"
    with open(evil, "wb") as f:
        pickle.dump({"x": Evil()}, f)
    monkeypatch.delenv("DSTPU_ALLOW_PICKLE_CHECKPOINTS", raising=False)
    with pytest.raises(ValueError, match="weights_only"):
        eng.load_checkpoint(str(evil))
    assert not marker.exists(), "pickled code executed during probing"

    # a trusted legacy pickled pytree loads only with the explicit opt-in
    legacy = tmp_path / "legacy.pkl"
    with open(legacy, "wb") as f:
        pickle.dump(jax.tree.map(np.asarray, params), f)
    with pytest.raises(ValueError, match="DSTPU_ALLOW_PICKLE_CHECKPOINTS"):
        eng.load_checkpoint(str(legacy))
    monkeypatch.setenv("DSTPU_ALLOW_PICKLE_CHECKPOINTS", "1")
    eng.load_checkpoint(str(legacy))
    assert np.asarray(eng.forward(ids)).shape[0] == ids.shape[0]


def test_per_channel_int8_inference():
    """Per-output-channel symmetric INT8 (the decode-path mode: dequant is a
    bare convert*scale that XLA fuses into the consuming matmul — no bf16
    weight copy per decode step).  Logits must stay close to fp and greedy
    decoding must agree with the groupwise mode's quality bar."""
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.runtime.weight_quantizer import QuantizedWeight

    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=32, dtype="float32",
                            use_flash_attention=False, remat=False)
    model = Transformer(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (1, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    want = np.asarray(InferenceEngine(
        model, DeepSpeedInferenceConfig(dtype="float32"),
        params=params).forward(ids))

    qcfg = DeepSpeedInferenceConfig(
        dtype="float32", quant={"enabled": True, "bits": 8,
                                "per_channel": True})
    eng = InferenceEngine(model, qcfg, params=params)
    q_leaves = [l for l in jax.tree.leaves(
        eng.params, is_leaf=lambda x: isinstance(x, QuantizedWeight))
        if isinstance(l, QuantizedWeight)]
    assert q_leaves and all(l.q.dtype == jnp.int8 and l.per_channel
                            for l in q_leaves)
    # scales are one-per-output-channel: leading (contraction) axis is 1
    assert all(l.scale.shape[0] == 1 and l.scale.shape[1:] == l.q.shape[1:]
               for l in q_leaves)
    got = np.asarray(eng.forward(ids))
    assert np.mean(np.abs(got - want)) / (np.mean(np.abs(want)) + 1e-9) < 0.1
    agree = np.mean(np.argmax(got, -1) == np.argmax(want, -1))
    assert agree >= 0.7, agree
    out = eng.generate(ids, max_new_tokens=4)
    assert np.asarray(out).shape == (1, 16)

    # per-channel int4 is rejected (fusable dequant needs bare int8)
    with pytest.raises(ValueError, match="per_channel"):
        from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization
        WeightQuantization(bits=4, per_channel=True)


def test_padded_prompt_generation_matches_per_row():
    """Right-padded batched generation (attention_mask) must produce, for
    every row, exactly the tokens that an unpadded single-row generate
    produces (reference ``engine._generate`` handles HF padded batches)."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    cfg = tiny_cfg()
    model = Transformer(cfg)
    rng = np.random.default_rng(3)
    lens = [5, 12, 9]
    P = max(lens)
    rows = [rng.integers(1, 97, (n,)).astype(np.int32) for n in lens]
    ids = np.zeros((len(rows), P), np.int32)
    mask = np.zeros((len(rows), P), np.int32)
    for i, r in enumerate(rows):
        ids[i, :len(r)] = r
        mask[i, :len(r)] = 1
    params = model.init(jax.random.key(0),
                        {"input_ids": jnp.asarray(ids)})
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(dtype="float32"),
                          params=params)

    out = np.asarray(eng.generate(ids, max_new_tokens=6,
                                  attention_mask=mask))
    assert out.shape == (3, P + 6)
    # prompt columns (incl. pads) unchanged
    np.testing.assert_array_equal(out[:, :P], ids)
    for i, r in enumerate(rows):
        solo = np.asarray(eng.generate(r[None], max_new_tokens=6))
        np.testing.assert_array_equal(
            out[i, P:], solo[0, len(r):],
            err_msg=f"row {i} (len {len(r)}) diverges from unpadded run")


def test_left_padded_mask_rejected(model_and_params):
    """LEFT padding (HF's decoder-only default) silently corrupts the
    right-pad decode layout — it must be rejected loudly."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    model, params, ids = model_and_params
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(dtype="float32"),
                          params=params)
    mask = np.ones(ids.shape, np.int32)
    mask[0, :3] = 0                      # left padding on row 0
    with pytest.raises(ValueError, match="RIGHT-padded"):
        eng.generate(ids, max_new_tokens=2, attention_mask=mask)


def test_chunked_prefill_matches_one_pass(model_and_params):
    """Chunked prefill (nn.scan over chunks + the Pallas chunk kernel)
    must generate the same greedy tokens as the one-pass flash prefill —
    including a chunk size that does not divide the prompt."""
    from deepspeed_tpu.inference.engine import make_generate_fn
    model, params, ids = model_and_params              # prompt len 12
    rng = jax.random.key(3)
    outs = {}
    for chunk in (None, 4, 5):
        fn = make_generate_fn(model, jnp.float32, ids.shape[1], 6,
                              False, 1.0, 0, 1.0, prefill_chunk=chunk)
        cache = model.init_cache(ids.shape[0], ids.shape[1] + 6,
                                 dtype=jnp.float32)
        outs[chunk] = np.asarray(fn(params, cache, ids, rng, -1)[0])
    np.testing.assert_allclose(outs[4], outs[None], atol=0, rtol=0)
    np.testing.assert_allclose(outs[5], outs[None], atol=0, rtol=0)


def test_chunked_prefill_int8_kv(model_and_params):
    """Chunked prefill over the int8 KV cache: same quantized ints land in
    the cache as the one-pass path writes, so greedy tokens agree."""
    from deepspeed_tpu.inference.engine import make_generate_fn
    model0, params, ids = model_and_params
    model = Transformer(tiny_cfg(kv_cache_quant=True))
    rng = jax.random.key(3)
    outs = {}
    for chunk in (None, 4):
        fn = make_generate_fn(model, jnp.float32, ids.shape[1], 6,
                              False, 1.0, 0, 1.0, prefill_chunk=chunk)
        cache = model.init_cache(ids.shape[0], ids.shape[1] + 6,
                                 dtype=jnp.float32)
        outs[chunk] = np.asarray(fn(params, cache, ids, rng, -1)[0])
    np.testing.assert_array_equal(outs[4], outs[None])


def test_auto_prefill_chunk_policy():
    from deepspeed_tpu.inference.engine import auto_prefill_chunk
    assert auto_prefill_chunk(64, 256) is None          # fits the budget
    assert auto_prefill_chunk(128, 256) == 128          # bs128 serving point
    assert auto_prefill_chunk(16, 3968) == 512          # 4k long-cache point
    assert auto_prefill_chunk(1, 512) is None           # tiny batch


def test_serving_memory_guardrail(model_and_params, monkeypatch, caplog):
    """Compile-time serving guardrail: a program whose argument+temp bytes
    exceed ``memory_guard_fraction`` of the device budget warns — and
    refuses under ``strict_memory`` (reference analog: workspace bounds
    checks in ``inference_context.h``)."""
    from deepspeed_tpu.inference import engine as eng_mod
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    model, params, ids = model_and_params
    # a deliberately tiny "device": everything is over-threshold
    monkeypatch.setenv("DSTPU_HBM_BYTES_OVERRIDE", "1000")
    warned = []
    monkeypatch.setattr(eng_mod.logger, "warning",
                        lambda msg, *a, **k: warned.append(str(msg)))
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(dtype="float32"),
                          params=params)
    out = eng.generate(ids, max_new_tokens=4)          # warns, still runs
    assert out.shape == (2, 16)
    assert any("above" in m and "device memory" in m for m in warned), warned
    strict = InferenceEngine(
        model, DeepSpeedInferenceConfig(dtype="float32", strict_memory=True),
        params=params)
    with pytest.raises(RuntimeError, match="strict_memory"):
        strict.generate(ids, max_new_tokens=8)
    # a sane budget passes silently
    monkeypatch.setenv("DSTPU_HBM_BYTES_OVERRIDE", str(10 ** 12))
    ok = InferenceEngine(
        model, DeepSpeedInferenceConfig(dtype="float32", strict_memory=True),
        params=params)
    assert ok.generate(ids, max_new_tokens=4).shape == (2, 16)


def test_strict_memory_bucket_downshift(model_and_params):
    """Graceful degradation (fault.bucket_downshift): a generation batch
    refused by the strict_memory guard is served as two sequential
    half-batches instead of failing the request; greedy tokens must match
    an unconstrained engine's row for row."""
    from deepspeed_tpu.inference import engine as eng_mod
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    model, params, ids = model_and_params
    ref = InferenceEngine(model,
                          DeepSpeedInferenceConfig(dtype="float32"),
                          params=params)
    want = np.asarray(ref.generate(ids, max_new_tokens=4))

    eng = InferenceEngine(
        model,
        DeepSpeedInferenceConfig(dtype="float32", strict_memory=True,
                                 fault={"enabled": True,
                                        "bucket_downshift": True}),
        params=params)
    # deterministic batch-aware refusal: the first compiled program (the
    # full batch-2 bucket) is over budget, the downshifted batch-1
    # programs pass — the real byte-threshold path is covered by
    # test_serving_memory_guardrail
    refused = []

    def guard_once(compiled):
        if not refused:
            refused.append(True)
            raise eng_mod.MemoryGuardExceeded("strict_memory: test bucket")
    eng._guard_memory = guard_once
    out = eng.generate(ids, max_new_tokens=4)
    assert eng.fault_stats["bucket_downshifts"] == 1
    np.testing.assert_array_equal(np.asarray(out), want)

    # without the fault block the refusal stays a hard error (seed
    # behavior)
    strict = InferenceEngine(
        model, DeepSpeedInferenceConfig(dtype="float32",
                                        strict_memory=True),
        params=params)
    refused.clear()
    strict._guard_memory = guard_once
    with pytest.raises(RuntimeError, match="strict_memory"):
        strict.generate(ids, max_new_tokens=4)


def test_transient_executable_load_retries(model_and_params):
    """fault.max_retries bounds retry/backoff around transient executable
    load failures; exhaustion degrades to the plain jit path instead of
    failing generation."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.runtime.fault import inject
    model, params, ids = model_and_params
    inject.reset_injection()
    try:
        eng = InferenceEngine(
            model,
            DeepSpeedInferenceConfig(
                dtype="float32",
                fault={"enabled": True, "max_retries": 3,
                       "backoff_base_secs": 0.01,
                       "backoff_max_secs": 0.05}),
            params=params)
        specs = inject.configure_injection(
            {"point": "infer.executable_load", "action": "raise",
             "times": 2})
        out = eng.generate(ids, max_new_tokens=4)
        assert out.shape == (2, 16)
        assert specs[0].fired == 2
        assert eng.fault_stats["exec_load_retries"] == 2
        inject.reset_injection()

        # exhaustion: every attempt fails -> plain-jit degradation, the
        # request still completes
        eng2 = InferenceEngine(
            model,
            DeepSpeedInferenceConfig(
                dtype="float32",
                fault={"enabled": True, "max_retries": 1,
                       "backoff_base_secs": 0.01,
                       "backoff_max_secs": 0.02}),
            params=params)
        inject.configure_injection(
            {"point": "infer.executable_load", "action": "raise",
             "times": 0})
        out = eng2.generate(ids, max_new_tokens=4)
        assert out.shape == (2, 16)
    finally:
        inject.reset_injection()


def test_kv_workspace_reuse_and_release(model_and_params):
    """The engine-owned KV workspace is donated and reused across calls
    (same shape -> same buffer lineage), reallocated on shape change, and
    freed by release_workspace()."""
    model, params, ids = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    engine.set_params(params)
    out1 = engine.generate(ids, max_new_tokens=8)
    ws = engine._workspace
    assert ws._cache is not None             # reclaimed from the program
    k1 = ws._key
    out2 = engine.generate(ids, max_new_tokens=8)    # same shape: reuse
    assert ws._key == k1
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    engine.generate(ids, max_new_tokens=4)           # shape change: realloc
    assert ws._key != k1
    engine.release_workspace()
    assert ws._cache is None and ws._key is None


def test_chunked_prefill_pad_overflow(model_and_params):
    """P % C != 0 with max_new_tokens smaller than the pad: the padded
    last chunk writes past prompt+new, so the workspace must be sized by
    required_cache_len — a clamped write would silently corrupt real
    prompt K/V (regression: review finding on transformer.prefill_chunked)."""
    from deepspeed_tpu.inference.engine import (make_generate_fn,
                                                required_cache_len)
    model, params, ids = model_and_params          # prompt len 12
    rng = jax.random.key(5)
    new = 2                                        # 12+2=14 < padded 15
    assert required_cache_len(12, new, 5) == 16    # padded 15, 8-rounded
    ref_fn = make_generate_fn(model, jnp.float32, 12, new,
                              False, 1.0, 0, 1.0, prefill_chunk=None)
    cache = model.init_cache(2, required_cache_len(12, new, None),
                             dtype=jnp.float32)
    want = np.asarray(ref_fn(params, cache, ids, rng, -1)[0])
    fn = make_generate_fn(model, jnp.float32, 12, new,
                          False, 1.0, 0, 1.0, prefill_chunk=5)
    cache = model.init_cache(2, required_cache_len(12, new, 5),
                             dtype=jnp.float32)
    got = np.asarray(fn(params, cache, ids, rng, -1)[0])
    np.testing.assert_array_equal(got, want)
    # and through the public engine path with a forced chunk
    engine = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 5})
    engine.set_params(params)
    out = np.asarray(engine.generate(ids, max_new_tokens=new))
    np.testing.assert_array_equal(out, want)


def test_split_prefill_generation_matches_one_pass(model_and_params):
    """The engine split-prefill path (n_chunks > 2: per-chunk donated
    executable + decode-only program) must match one-pass generation,
    masked and unmasked."""
    model, params, ids = model_and_params          # prompt len 12
    ref = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    ref.set_params(params)
    want = np.asarray(ref.generate(ids, max_new_tokens=6))

    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 3})
    eng.set_params(params)
    got = np.asarray(eng.generate(ids, max_new_tokens=6))   # 4 chunks
    np.testing.assert_array_equal(got, want)

    mask = np.ones(ids.shape, np.int32)
    mask[1, -5:] = 0
    want_m = np.asarray(ref.generate(ids, max_new_tokens=4,
                                     attention_mask=mask))
    got_m = np.asarray(eng.generate(ids, max_new_tokens=4,
                                    attention_mask=mask))
    np.testing.assert_array_equal(got_m, want_m)


def test_decode_early_exit_matches_scan(model_and_params):
    """The bounded-while-loop decode form (early_exit=True, the default)
    must emit BITWISE the scan form's tokens — greedy, with an eos that
    stops every row early, and sampled with a padded-prompt mask."""
    from deepspeed_tpu.inference.engine import make_generate_fn
    model, params, ids = model_and_params
    rng = jax.random.key(7)

    def run(early, eos=-1, with_mask=False, do_sample=False):
        fn = make_generate_fn(model, jnp.float32, ids.shape[1], 10,
                              do_sample, 0.8 if do_sample else 1.0, 0,
                              0.9 if do_sample else 1.0,
                              with_mask=with_mask, early_exit=early)
        cache = model.init_cache(ids.shape[0], ids.shape[1] + 10,
                                 dtype=jnp.float32)
        args = (params, cache, ids, rng, jnp.asarray(eos))
        if with_mask:
            mask = np.ones(ids.shape, np.int32)
            mask[1, -3:] = 0
            args += (jnp.asarray(mask),)
        return np.asarray(fn(*args)[0])

    np.testing.assert_array_equal(run(True), run(False))
    # eos = whatever greedy emits 2 tokens in: every row stops early, the
    # while form exits, and the eos-prefilled tail must match the scan's
    eos = int(run(False)[0, ids.shape[1] + 2])
    np.testing.assert_array_equal(run(True, eos=eos), run(False, eos=eos))
    np.testing.assert_array_equal(run(True, with_mask=True, do_sample=True),
                                  run(False, with_mask=True, do_sample=True))


def test_decode_early_exit_engine_flag(model_and_params):
    """``decode_early_exit`` plumbs through the engine; both settings
    generate identical tokens (the flag only changes the loop form)."""
    model, params, ids = model_and_params
    outs = []
    for flag in (True, False):
        eng = deepspeed_tpu.init_inference(
            model, config={"dtype": "float32", "decode_early_exit": flag})
        eng.set_params(params)
        first = int(np.asarray(eng.generate(ids, max_new_tokens=1))[0, -1])
        outs.append(np.asarray(eng.generate(ids, max_new_tokens=8,
                                            eos_token_id=first)))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_kv_workspace_dead_buffer_not_reused(model_and_params):
    """take()/give_back() liveness (serving + generate share this): a
    buffer donated into a program that FAILED after donation comes back
    dead — take() must reallocate, never hand a deleted array out."""
    from deepspeed_tpu.inference.engine import KVCacheWorkspace
    model, params, ids = model_and_params
    ws = KVCacheWorkspace(model)
    cache = ws.take(2, 32, jnp.float32)
    # simulate a post-donation failure: every leaf buffer is dead
    for leaf in jax.tree.leaves(cache):
        leaf.delete()
    ws.give_back(cache)
    fresh = ws.take(2, 32, jnp.float32)
    assert all(not l.is_deleted() for l in jax.tree.leaves(fresh))
    np.testing.assert_array_equal(np.asarray(fresh["k"]),
                                  np.zeros_like(np.asarray(fresh["k"])))

    # a LIVE give-back of the same shape is reused (buffer lineage kept)
    ws.give_back(fresh)
    again = ws.take(2, 32, jnp.float32)
    assert again is fresh["k"] or again["k"] is fresh["k"]
    # shape change reallocates; release() drops everything
    ws.give_back(again)
    other = ws.take(2, 48, jnp.float32)
    assert other["k"].shape[-2] == 48
    ws.release()
    assert ws._cache is None and ws._key is None


def test_kv_workspace_partial_death_reallocates(model_and_params):
    """Even ONE dead leaf (quantized caches carry four) poisons the
    buffer: take() must treat the whole cache as dead."""
    from deepspeed_tpu.inference.engine import KVCacheWorkspace
    model = Transformer(tiny_cfg(kv_cache_quant=True))
    ws = KVCacheWorkspace(model)
    cache = ws.take(1, 16, jnp.float32)
    assert set(cache) == {"k", "v", "k_scale", "v_scale"}
    cache["v_scale"].delete()
    ws.give_back(cache)
    fresh = ws.take(1, 16, jnp.float32)
    assert all(not l.is_deleted() for l in jax.tree.leaves(fresh))


def test_prefill_chunk_size_alignment(model_and_params):
    """User-specified prefill_chunk_size is rounded UP to a multiple of 8
    (floor 8, cap 512 — the Mosaic chunk kernel's alignment and VMEM
    bounds, mirroring the fused-write checks) before reaching the kernel;
    auto/off behavior is untouched (ADVICE round 5)."""
    model, params, ids = model_and_params

    def chunk_for(cfg_value, batch=2, prompt=2048):
        eng = deepspeed_tpu.init_inference(
            model, config={"dtype": "float32",
                           "prefill_chunk_size": cfg_value})
        return eng._prefill_chunk_for(batch, prompt)

    assert chunk_for(5) == 8            # rounded up from below the floor
    assert chunk_for(100) == 104        # next multiple of 8
    assert chunk_for(128) == 128        # already aligned: untouched
    assert chunk_for(1000) == 512       # capped at the kernel's VMEM bound
    assert chunk_for(0) is None         # 0/None/"off" still disable
    assert chunk_for(None) is None
    assert chunk_for("off") is None
    assert chunk_for(16, prompt=12) is None   # chunk >= prompt → one-pass
    # the rounded chunk still generates correctly end-to-end (prompt 12,
    # chunk 5 → 8 → 2-chunk split prefill)
    ref = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    ref.set_params(params)
    want = np.asarray(ref.generate(ids, max_new_tokens=4))
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 5})
    eng.set_params(params)
    assert eng._prefill_chunk_for(*ids.shape) == 8
    np.testing.assert_array_equal(
        np.asarray(eng.generate(ids, max_new_tokens=4)), want)
