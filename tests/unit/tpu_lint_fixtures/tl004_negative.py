"""TL004 negative fixture: hashable static args."""
import jax
import jax.numpy as jnp


def run(shape, x):
    return x.reshape(shape)


run_jit = jax.jit(run, static_argnums=(0,))
out = run_jit((4, 4), jnp.ones(16))          # tuple: hashable, stable

no_static = jax.jit(run)
no_static_out = no_static([4, 4], jnp.ones(16))   # not a static position
