"""Paged-KV serving tests (``inference/serving/paging.py``,
``docs/serving.md`` "Paged KV cache").

The paged acceptance contract: with the slot lanes replaced by a shared
page pool + block tables, greedy serving outputs stay BITWISE-identical
to solo ``generate()`` runs, tokens are invariant to the page size, a
shared prompt prefix is prefilled exactly once (copy-on-write at page
granularity), pool exhaustion degrades into admission backpressure
(``QueueFull`` / stalls — never corruption), paged snapshots
preempt→restore bitwise, and the whole lifecycle still mints exactly ONE
decode executable per server."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference.serving.paging import (PagePool, PrefixIndex,
                                                    compact_page_str,
                                                    expand_page_str)
from deepspeed_tpu.inference.serving.slo import QueueFull, RequestStatus
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig


def tiny_cfg(**over):
    base = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, use_flash_attention=False, dtype="float32")
    base.update(over)
    return TransformerConfig(**base)


PAGED = {"enabled": True, "num_slots": 3, "max_cache_len": 64,
         "prefill_chunk": 8, "prefill_token_budget": 16,
         "decode_block": 2, "paged": True, "page_size": 16}


def _build_engine(model_cfg=None, serving=None):
    model = Transformer(model_cfg or tiny_cfg())
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 8,
                       "serving": serving or PAGED})
    eng.set_params(params)
    return eng


@pytest.fixture(scope="module")
def paged_engine():
    return _build_engine()


def _mixed_workload(rng, n=7):
    lens = rng.integers(9, 21, (n,))
    news = rng.integers(3, 13, (n,))
    prompts = [rng.integers(1, 97, (int(p),)).astype(np.int32)
               for p in lens]
    return prompts, [int(x) for x in news]


def _assert_bitwise(eng, outs, rids, prompts, news, eos=None):
    for i, (rid, p, n) in enumerate(zip(rids, prompts, news)):
        e = -1 if eos is None else eos[i]
        want = np.asarray(eng.generate(p[None], max_new_tokens=n,
                                       eos_token_id=e))[0]
        np.testing.assert_array_equal(
            outs[rid], want,
            err_msg=f"request {rid} (P={len(p)}, new={n}) diverges from "
                    f"its solo generate() run")


def test_paged_serving_matches_solo_generate(paged_engine):
    """The PR 4 equivalence contract in paged mode: num_slots(3) <
    num_requests(7), mid-stream EOS retirements, slot churn — every
    output bitwise-equal to solo generate(), ONE decode executable."""
    eng = paged_engine
    rng = np.random.default_rng(3)
    prompts, news = _mixed_workload(rng)
    eos_ids = []
    for i, (p, n) in enumerate(zip(prompts, news)):
        if i % 2 == 0:
            probe = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
            eos_ids.append(int(probe[len(p) + n // 2]))
        else:
            eos_ids.append(-1)
    srv = eng.serve()
    assert srv.paged and srv.page == 16
    rids = [srv.submit(p, max_new_tokens=n, eos_token_id=e)
            for p, n, e in zip(prompts, news, eos_ids)]
    outs = srv.drain()
    assert sorted(outs) == sorted(rids)
    _assert_bitwise(eng, outs, rids, prompts, news, eos_ids)
    # every slot's pages returned to the pool; only the prefix index may
    # still hold references
    assert not srv._slot_pages
    assert (srv._page_table == 0).all()
    n_decode_sigs = sum(1 for sig in eng._aot
                        if sig and sig[0] == id(srv._decode_fn))
    assert n_decode_sigs == 1, n_decode_sigs


def test_paged_page_size_invariance(paged_engine):
    """Same tokens for page_size in {16, 64, 128}: the page size only
    changes where K/V rows physically live, never what is attended."""
    eng = paged_engine
    rng = np.random.default_rng(5)
    prompts, news = _mixed_workload(rng, n=5)
    ref = None
    for ps in (16, 64, 128):
        srv = eng.serve(page_size=ps)
        rids = [srv.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, news)]
        outs = srv.drain()
        got = [outs[r] for r in rids]
        if ref is None:
            ref = got
            _assert_bitwise(eng, outs, rids, prompts, news)
        else:
            for a, b in zip(ref, got):
                np.testing.assert_array_equal(a, b)


def test_paged_prefix_cow_divergence(paged_engine):
    """Copy-on-write prefix sharing: requests with a common 2-page
    prefix and divergent tails share the prefix pages (prefilled once —
    later admissions hit the index) yet produce bitwise-solo outputs;
    the divergent tail re-prefills at most one page of tokens."""
    eng = paged_engine
    rng = np.random.default_rng(11)
    pre = rng.integers(1, 97, (32,)).astype(np.int32)      # 2 full pages
    reqs = [np.concatenate([pre,
                            rng.integers(1, 97, (5,)).astype(np.int32)])
            for _ in range(4)]
    srv = eng.serve()
    rids = [srv.submit(q, max_new_tokens=6) for q in reqs]
    outs = srv.drain()
    _assert_bitwise(eng, outs, rids, reqs, [6] * 4)
    # request 1..3 each matched the 2 shared pages request 0 registered
    assert srv.stats["prefix_hits"] >= 3, srv.stats
    assert srv.stats["prefix_tokens_reused"] >= 3 * 32
    # the shared prefill really was skipped: without sharing 4 requests
    # of 37 tokens cost 4*ceil(37/8)*8 = 160 prefill tokens; with 2
    # shared pages the 3 hits each saved 32 tokens
    assert srv.stats["prefill_tokens"] <= 160 - 3 * 32


def test_paged_prefix_chunk_unaligned_boundary():
    """A prefix match whose page boundary is NOT chunk-aligned must be
    rounded DOWN to a chunk-aligned start: chunk ci writes the full
    padded span [s0+ci*C, s0+(ci+1)*C), so a page-aligned-only s0 can
    pad past the table row (page 16, chunk 64, P=120, m=7 matched pages:
    112 + 64 = 176 > the 8-page lane — a host-side broadcast crash
    mid-admission before the fix).  Outputs stay bitwise-solo."""
    eng = _build_engine(
        model_cfg=tiny_cfg(max_seq_len=128),
        serving={"enabled": True, "num_slots": 2, "max_cache_len": 128,
                 "prefill_chunk": 64, "prefill_token_budget": 128,
                 "decode_block": 2, "paged": True, "page_size": 16})
    eng._config.prefill_chunk_size = 64      # solo replays the same chunk
    rng = np.random.default_rng(31)
    p = rng.integers(1, 97, (120,)).astype(np.int32)
    want = np.asarray(eng.generate(p[None], max_new_tokens=8))[0]
    srv = eng.serve()
    r1 = srv.submit(p, max_new_tokens=8)     # registers the prefix
    outs = srv.drain()
    r2 = srv.submit(p, max_new_tokens=8)     # matches 7 pages -> round to 4
    outs.update(srv.drain())
    np.testing.assert_array_equal(outs[r1], want)
    np.testing.assert_array_equal(outs[r2], want)
    assert srv.stats["prefix_hits"] == 1
    # the trimmed match really started the second prefill chunk-aligned
    assert srv.stats["prefix_tokens_reused"] == 64


def test_paged_prefix_stats_count_admissions_not_stalls():
    """Prefix stats count ADMISSIONS: a request stalled at the queue
    head under pool pressure retries _start_prefill_paged every step and
    must not record a lookup/hit per retry (hit-rate inflation)."""
    eng = _build_engine()
    rng = np.random.default_rng(37)
    pre = rng.integers(1, 97, (32,)).astype(np.int32)      # 2 pages
    reqs = [np.concatenate([pre,
                            rng.integers(1, 97, (5,)).astype(np.int32)])
            for _ in range(4)]
    # 4 allocatable pages vs 3 pages/request: concurrency is page-bound,
    # so admissions stall while earlier requests decode
    srv = eng.serve(num_pages=5)
    rids = [srv.submit(q, max_new_tokens=6) for q in reqs]
    outs = srv.drain()
    assert srv.stats["admission_stalls"] > 0
    assert srv.stats["prefix_lookups"] == 4, srv.stats
    _assert_bitwise(eng, outs, rids, reqs, [6] * 4)


def test_paged_pool_exhaustion_backpressure(paged_engine):
    """Refcount/pool exhaustion shows up as admission BACKPRESSURE —
    a bounded queue rejects with QueueFull, an unbounded one stalls
    admission until retirements free pages — and everything admitted
    still completes bitwise-correct (no corruption, no deadlock)."""
    eng = paged_engine
    rng = np.random.default_rng(13)
    prompts, news = _mixed_workload(rng, n=8)

    # bounded queue: pool of 8 allocatable pages fills, queue backs up,
    # submit() rejects with QueueFull
    srv = eng.serve(num_pages=9, max_queue_depth=2, queue_policy="reject")
    accepted = []
    with pytest.raises(QueueFull):
        for i in range(8):
            accepted.append(
                (srv.submit(prompts[i], max_new_tokens=news[i]), i))
    outs = srv.drain()
    _assert_bitwise(eng, outs, [r for r, _ in accepted],
                    [prompts[i] for _, i in accepted],
                    [news[i] for _, i in accepted])

    # unbounded queue: admission stalls at the queue head under pool
    # pressure and resumes as slots retire — all 8 complete.  3
    # allocatable pages vs (mostly) 2-page requests: two can never run
    # concurrently even though 3 slots are free
    srv = eng.serve(num_pages=4, prefix_cache=False)
    rids = [srv.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    outs = srv.drain()
    assert srv.stats["admission_stalls"] > 0
    _assert_bitwise(eng, outs, rids, prompts, news)
    # nothing leaked: the pool drains back to empty
    assert srv._pool.in_use == 0

    # a request the pool can NEVER hold is rejected at submit, not
    # queued into a deadlock
    with pytest.raises(ValueError, match="pages"):
        srv.submit(rng.integers(1, 97, (40,)).astype(np.int32),
                   max_new_tokens=20)


def test_paged_preempt_restore_bitwise(paged_engine, tmp_path):
    """Graceful preemption of a paged server: snapshot mid-flight,
    restore on a fresh paged server, stitched outputs bitwise-identical
    to uninterrupted runs; the snapshot stores page tables as compact
    range strings (diagnostics), never one JSON int per entry."""
    import json
    import os
    eng = paged_engine
    rng = np.random.default_rng(17)
    prompts, news = _mixed_workload(rng, n=6)
    srv = eng.serve()
    rids = [srv.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    outs = {}
    for _ in range(4):
        outs.update(srv.step())
    tag, snapped, fin = srv.preempt(str(tmp_path), drain_budget_s=0.0)
    outs.update(fin)
    assert snapped, "nothing was left to snapshot — weak test setup"
    with open(os.path.join(str(tmp_path), tag, "serving_state.json")) as f:
        state = json.load(f)
    in_slot = [r for r in state["requests"] if r.get("pages")]
    assert in_slot, "no in-slot request carried a compact page table"
    for r in in_slot:
        assert isinstance(r["pages"], str)
        assert expand_page_str(r["pages"])          # parses back
    srv2 = eng.serve()
    restored = srv2.restore(str(tmp_path))
    assert sorted(restored) == sorted(snapped)
    outs.update(srv2.drain())
    _assert_bitwise(eng, outs, rids, prompts, news)


def test_paged_restore_onto_smaller_pool_aborts(paged_engine, tmp_path):
    """A snapshot from a big-pool server restored onto a server whose
    pool can never hold a request ABORTs it with a clear reason (the
    paged mirror of the PR 5 lane-capacity check)."""
    eng = paged_engine
    rng = np.random.default_rng(19)
    big = rng.integers(1, 97, (20,)).astype(np.int32)
    srv = eng.serve()
    rid = srv.submit(big, max_new_tokens=20)        # 40 positions
    srv.preempt(str(tmp_path), drain_budget_s=0.0)
    srv2 = eng.serve(num_pages=3)                   # 2 pages = 32 positions
    assert srv2.restore(str(tmp_path)) == []
    res = srv2.result(rid)
    assert res.status == RequestStatus.ABORTED
    assert "page" in res.detail and "num_pages" in res.detail


def test_paged_int8_kv_serving_matches_solo(tmp_path):
    """int8 KV quantization through the paged pool: quantized page
    writes/gathers reproduce solo generate() (which quantizes the same
    rows into a monolithic cache) bitwise."""
    eng = _build_engine(model_cfg=tiny_cfg(kv_cache_quant=True))
    rng = np.random.default_rng(23)
    prompts, news = _mixed_workload(rng, n=5)
    srv = eng.serve()
    assert "k_scale" in srv._pool_ws.take(srv.num_pages, srv.page,
                                          eng.compute_dtype)
    srv._pool_ws.release()
    rids = [srv.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    outs = srv.drain()
    _assert_bitwise(eng, outs, rids, prompts, news)


def test_paged_overload_cycle_zero_new_decode_executables(paged_engine,
                                                         tmp_path):
    """The zero-new-executables invariant extended to paged mode
    (acceptance): an overload burst + deadline shed + cancel + preempt +
    restarted-server resume mints exactly ONE paged decode signature per
    server — page allocation, sharing, eviction and table churn all ride
    traced arguments."""
    eng = paged_engine
    rng = np.random.default_rng(29)
    prompts, news = _mixed_workload(rng, n=7)
    srv1 = eng.serve()
    rids = [srv1.submit(p, max_new_tokens=n)
            for p, n in zip(prompts[:5], news[:5])]
    r_shed = srv1.submit(prompts[5], max_new_tokens=4, deadline_s=0.0)
    r_cancel = srv1.submit(prompts[6], max_new_tokens=4)
    srv1.cancel(r_cancel)
    early = {}
    for _ in range(4):
        early.update(srv1.step())
    tag, snapped, fin = srv1.preempt(str(tmp_path), drain_budget_s=0.0)
    early.update(fin)
    assert srv1.result(r_shed).status == RequestStatus.SHED_DEADLINE
    assert srv1.result(r_cancel).status == RequestStatus.CANCELLED
    srv2 = eng.serve()
    restored = srv2.restore(str(tmp_path))
    assert sorted(restored) == sorted(snapped)
    outs = dict(early)
    outs.update(srv2.drain())
    _assert_bitwise(eng, outs, rids, prompts[:5], news[:5])
    for srv in (srv1, srv2):
        n_decode = sum(1 for sig in eng._aot
                       if sig and sig[0] == id(srv._decode_fn))
        assert n_decode == 1, n_decode


def test_paged_default_off_and_validation():
    """serving.paged defaults OFF (seed behavior: monolithic lanes,
    no pool attributes consulted), and bad paged configs fail loudly."""
    from deepspeed_tpu.inference.serving.config import ServingConfig
    assert ServingConfig().paged is False
    eng = _build_engine(serving={**PAGED, "paged": False})
    srv = eng.serve()
    assert not srv.paged and not hasattr(srv, "_pool")
    with pytest.raises(ValueError, match="num_pages"):
        _build_engine(serving={**PAGED, "num_pages": 1}).serve()


def test_page_pool_and_prefix_index_unit():
    """Host bookkeeping invariants: trash page pinned, refcounted
    alloc/free, chain-hash lookup/register, leaf-first LRU eviction,
    and the compact page-string round trip."""
    pool = PagePool(6)                       # pages 1..5 allocatable
    assert pool.allocatable == 5 and pool.free_count == 5
    got = pool.alloc(3)
    assert got is not None and 0 not in got
    assert pool.alloc(3) is None             # never a partial grab
    pool.incref(got[0])
    for p in got:
        pool.decref(p)
    assert pool.free_count == 4              # got[0] still referenced
    pool.decref(got[0])
    assert pool.free_count == 5

    idx = PrefixIndex()
    toks = np.arange(32, dtype=np.int32)
    row = pool.alloc(2)
    assert idx.register(toks, 16, row, pool, 2) == 2
    for p in row:
        pool.decref(p)          # the registering slot retires — only the
    hit = idx.lookup(toks, 16, pool, 2)     # index's references remain
    assert hit == row
    for p in hit:
        pool.decref(p)
    # divergence INSIDE block 2: only block 1 matches
    toks2 = toks.copy()
    toks2[20] = 96
    hit2 = idx.lookup(toks2, 16, pool, 2)
    assert hit2 == row[:1]
    for p in hit2:
        pool.decref(p)
    # eviction is leaf-first: the chain's tail goes before its parent
    assert idx.evict(pool, 1) == 1
    assert len(idx) == 1 and pool.refcount(row[1]) == 0
    idx.clear(pool)
    assert pool.free_count == 5

    assert compact_page_str([4, 5, 6, 9, 2]) == "4-6,9,2"
    assert expand_page_str("4-6,9,2") == [4, 5, 6, 9, 2]
    assert compact_page_str([]) == "" and expand_page_str("") == []


def test_paged_kernel_knob_ab_bitwise_and_fallback_counter():
    """serving.paged_kernel=False is the A/B switch back to the
    pre-kernel gather path: greedy outputs stay BITWISE-identical to the
    kernel path (both match solo generate()), the engine's kernel_modes
    attribution flips to reference_fallback, and every gather-path decode
    dispatch is counted in stats["paged_attention_fallback"] (the kernel
    path counts zero)."""
    eng_on = _build_engine()
    eng_off = _build_engine(serving={**PAGED, "paged_kernel": False})
    rng = np.random.default_rng(31)
    prompts, news = _mixed_workload(rng, n=5)
    outs = {}
    for tag, eng in (("on", eng_on), ("off", eng_off)):
        srv = eng.serve()
        assert srv.paged_kernel is (tag == "on")
        want = ("pallas_paged_decode" if tag == "on"
                else "reference_fallback")
        assert srv.kernel_modes["decode"] == want
        rids = [srv.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, news)]
        res = srv.drain()
        _assert_bitwise(eng, res, rids, prompts, news)
        fb = srv.stats["paged_attention_fallback"]
        if tag == "on":
            assert fb == 0, fb
        else:
            assert fb == srv.stats["decode_calls"] > 0, fb
        outs[tag] = [res[r] for r in rids]
        srv.close()
    for a, b in zip(outs["on"], outs["off"]):
        np.testing.assert_array_equal(a, b)
