from deepspeed_tpu.runtime.comm.compressed import (  # noqa: F401
    CompressedBackend, compressed_allreduce, pack_signs, unpack_signs)

# reference parity aliases (runtime/comm/nccl.py NcclBackend,
# runtime/comm/mpi.py MpiBackend): one backend serves both roles on TPU
NcclBackend = CompressedBackend
MpiBackend = CompressedBackend
