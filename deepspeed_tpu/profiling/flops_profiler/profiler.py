"""Flops profiler.

The reference monkey-patches ``torch.nn.functional`` to count flops at
runtime (``profiling/flops_profiler/profiler.py:23,441-``).  On TPU the
compiler already knows: XLA's cost analysis on the compiled executable gives
exact flop/byte counts for the *optimized* program — more accurate than
op-by-op Python counting, and free.  The profiler reads
``compiled.cost_analysis()`` plus wall-clock timing to report
flops / MACs / params / achieved TFLOPS and MFU.
"""

import time

import numpy as np

import jax

from deepspeed_tpu.utils.logging import log_dist

# Peak bf16 TFLOP/s per chip for MFU estimates (public figures).
PEAK_TFLOPS = {
    "tpu v4": 275.0,
    "tpu v5 lite": 197.0,   # v5e
    "tpu v5e": 197.0,
    "tpu v5": 459.0,        # v5p
    "tpu v6 lite": 918.0,   # trillium
    "cpu": 0.1,
}


def device_peak_tflops():
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for key, val in PEAK_TFLOPS.items():
        if kind.startswith(key):
            return val
    return PEAK_TFLOPS.get(d.platform, 100.0)


def cost_analysis_of(fn, *args, **kwargs):
    """Compile ``fn`` and return XLA's cost analysis dict (flops, bytes)."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    costs = compiled.cost_analysis()
    if isinstance(costs, list):
        costs = costs[0] if costs else {}
    return costs or {}


class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler:23``): profile one
    training step at ``profile_step`` and report totals."""

    def __init__(self, engine=None, model=None):
        self.engine = engine
        self.started = False
        self.flops = 0.0
        self.macs = 0.0
        self.params = 0
        self.step_time = 0.0

    def start_profile(self, ignore_list=None):
        self.started = True
        self._t0 = time.perf_counter()

    def stop_profile(self):
        if self.started:
            self.step_time = time.perf_counter() - self._t0
            self.started = False

    def profile_fn(self, fn, *args, **kwargs):
        """Profile an arbitrary jittable function: returns dict of metrics."""
        costs = cost_analysis_of(fn, *args, **kwargs)
        flops = float(costs.get("flops", 0.0))
        # timed execution
        f = jax.jit(fn)
        out = f(*args, **kwargs)          # warmup/compile
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        n = 3
        for _ in range(n):
            out = f(*args, **kwargs)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / n
        achieved = flops / dt / 1e12 if dt > 0 else 0.0
        peak = device_peak_tflops() * jax.device_count()
        return {
            "flops": flops,
            "latency_s": dt,
            "tflops": achieved,
            "mfu": achieved / peak if peak else 0.0,
            "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
        }

    def get_total_flops(self, as_string=False):
        return _num_to_string(self.flops) + "FLOPS" if as_string else self.flops

    def get_total_params(self, as_string=False):
        return _num_to_string(self.params) if as_string else self.params

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1,
                            detailed=True, output_file=None):
        if self.engine is not None and self.engine.params is not None:
            self.params = sum(int(np.prod(l.shape))
                              for l in jax.tree.leaves(self.engine.params))
        lines = [
            "-------------------------- DeepSpeed Flops Profiler --------------------------",
            f"params: {_num_to_string(self.params)}",
            f"profile step: {profile_step}",
            f"step latency: {self.step_time*1e3:.2f} ms",
        ]
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report)
        log_dist(report, ranks=[0])
        return report


def _num_to_string(num, precision=2):
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(num) >= div:
            return f"{num/div:.{precision}f} {unit}"
    return str(num)


def get_model_profile(model_fn, args=(), kwargs=None, print_profile=True,
                      detailed=True, warm_up=1, as_string=True):
    """Standalone API parity (reference ``profiler.py get_model_profile``)."""
    prof = FlopsProfiler()
    metrics = prof.profile_fn(model_fn, *args, **(kwargs or {}))
    flops, macs = metrics["flops"], metrics["flops"] / 2
    params = 0
    if print_profile:
        log_dist(f"flops={_num_to_string(flops)} macs={_num_to_string(macs)} "
                 f"tflops={metrics['tflops']:.2f} mfu={metrics['mfu']*100:.1f}%",
                 ranks=[0])
    if as_string:
        return _num_to_string(flops), _num_to_string(macs), str(params)
    return flops, macs, params
