"""Offline merge of a training checkpoint into a single fp32 state dict.

Reference parity: ``deepspeed/utils/zero_to_fp32.py``
(``get_fp32_state_dict_from_zero_checkpoint :459``,
``convert_zero_checkpoint_to_fp32_state_dict :508``) — runs on CPU without
instantiating the model.  Because our checkpoints are logically-global Orbax
stores, "merging ZeRO shards" is simply a host restore + fp32 cast; the
output is written with ``torch.save`` when torch is importable (the usual
consumer is a torch pipeline) and pickle otherwise.

Usage:  python -m deepspeed_tpu.utils.zero_to_fp32 <ckpt_dir> <output_file>
"""

import argparse
import pickle

import numpy as np

from deepspeed_tpu.utils.logging import logger


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None):
    """{dotted-param-path: np.float32 array} for all module parameters."""
    from deepspeed_tpu.checkpoint.deepspeed_checkpoint import DeepSpeedCheckpoint
    ckpt = DeepSpeedCheckpoint(checkpoint_dir, tag=tag)
    out = {}
    for name, arr in ckpt.flat_parameters().items():
        out[name] = arr.astype(np.float32) \
            if np.issubdtype(arr.dtype, np.floating) else arr
    return out


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file,
                                               tag=None):
    state_dict = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    try:
        import torch
        torch_sd = {k: torch.from_numpy(np.ascontiguousarray(v))
                    for k, v in state_dict.items()}
        torch.save(torch_sd, output_file)
    except ImportError:
        with open(output_file, "wb") as f:
            pickle.dump(state_dict, f)
    logger.info(f"saved fp32 state dict ({len(state_dict)} tensors) to "
                f"{output_file}")
    return output_file


def main():
    parser = argparse.ArgumentParser(
        description="Merge a deepspeed_tpu checkpoint to one fp32 state dict")
    parser.add_argument("checkpoint_dir")
    parser.add_argument("output_file")
    parser.add_argument("-t", "--tag", default=None)
    args = parser.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                               args.output_file, tag=args.tag)


if __name__ == "__main__":
    main()
