"""TL001 — host transfer on a hot path.

``.item()``, ``float()``/``int()``/``bool()`` on computed values,
``np.asarray``/``np.array``, ``jax.device_get``, ``.block_until_ready()``
and ``process_allgather`` all force the host to wait for the device and pull
data over the host link.  Inside a function reachable from a registered hot
path (``@hot_path``: train step, decode loop, prefill) that stall lands once
per step and serializes the pipeline XLA would otherwise keep async.
"""

import ast

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule

_SYNC_METHODS = {"item", "block_until_ready"}
_SYNC_CALLS = {"jax.device_get", "device_get", "np.asarray", "np.array",
               "numpy.asarray", "numpy.array", "onp.asarray",
               "multihost_utils.process_allgather", "process_allgather"}
_CAST_BUILTINS = {"float", "int", "bool"}
# casts of these are host-side shape/env/config math, never a device sync
_HOST_ONLY_CALLS = {"len", "np.prod", "math.prod", "os.environ.get",
                    "os.getenv", "prod"}


def _is_computed(node):
    """Cast args worth flagging: attribute/subscript/call chains — reads of
    engine state or device results (``float(self._scaler_state.scale)``,
    ``bool(jax.device_get(x))``).  Bare names are skipped: they are usually
    host-side API scalars (``int(max_new_tokens)``) and the device-array
    cases are caught by the explicit sync patterns instead."""
    if isinstance(node, ast.Call) and \
            dotted_name(node.func) in _HOST_ONLY_CALLS:
        return False
    return isinstance(node, (ast.Attribute, ast.Subscript, ast.Call))


@rule("TL001", "host transfer on a hot path")
def check(module):
    hot = module.hot_functions()
    if not hot:
        return
    seen = set()
    for fn in hot:
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            seen.add(id(node))
            what = None
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS \
                    and not node.args:
                what = f".{f.attr}() forces a device->host sync"
            else:
                name = dotted_name(f)
                if name in _SYNC_CALLS:
                    what = f"{name}(...) pulls device data to the host"
                elif name in _CAST_BUILTINS and node.args and \
                        _is_computed(node.args[0]):
                    what = (f"{name}(...) on a computed value blocks on the "
                            f"device result")
            if what:
                yield Finding(
                    "TL001", module.path, node.lineno, node.col_offset,
                    f"{what} inside hot path '{fn.hot_name or fn.name}' — "
                    f"move it off the per-step path or batch reads")
