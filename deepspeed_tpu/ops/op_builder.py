"""Op registry with availability probing.

Analog of the reference's ``op_builder/`` JIT-build system
(``op_builder/builder.py:102`` OpBuilder ABC + one builder per op).  On TPU
most "ops" are Pallas kernels or fused XLA programs that need no separate
build step, so a builder reports compatibility and hands back the op module;
native host libraries (async NVMe I/O, host-offload Adam) compile C++ lazily
like the reference's jit_load path.
"""

import importlib

from deepspeed_tpu.utils.logging import logger


class OpBuilder:
    BUILD_VAR = None
    NAME = "op"

    def __init__(self):
        self.name = self.NAME

    def is_compatible(self, verbose=False):
        return True

    def absolute_name(self):
        return f"deepspeed_tpu.ops.{self.name}"

    def sources(self):
        return []

    def load(self, verbose=False):
        """Import (and for native ops, lazily build) the op module."""
        return importlib.import_module(self.module_path())

    def module_path(self):
        raise NotImplementedError

    # parity alias (reference builder.py:455 jit_load)
    jit_load = load


class FusedAdamBuilder(OpBuilder):
    NAME = "fused_adam"

    def module_path(self):
        return "deepspeed_tpu.ops.adam.fused_adam"


class CPUAdamBuilder(OpBuilder):
    NAME = "cpu_adam"

    def module_path(self):
        return "deepspeed_tpu.ops.adam.cpu_adam"

    def is_compatible(self, verbose=False):
        try:
            from deepspeed_tpu.ops.adam import cpu_adam
            return cpu_adam.is_available()
        except Exception:
            return False


class FusedLambBuilder(OpBuilder):
    NAME = "fused_lamb"

    def module_path(self):
        return "deepspeed_tpu.ops.lamb.fused_lamb"


class TransformerBuilder(OpBuilder):
    NAME = "transformer"

    def module_path(self):
        return "deepspeed_tpu.ops.transformer.transformer"


class InferenceBuilder(OpBuilder):
    NAME = "transformer_inference"

    def module_path(self):
        return "deepspeed_tpu.ops.transformer.inference"


class SparseAttnBuilder(OpBuilder):
    NAME = "sparse_attn"

    def module_path(self):
        return "deepspeed_tpu.ops.sparse_attention.blocksparse_attention"


class QuantizerBuilder(OpBuilder):
    NAME = "quantizer"

    def module_path(self):
        return "deepspeed_tpu.ops.quantizer.quantizer"


class RandomLTDBuilder(OpBuilder):
    NAME = "random_ltd"

    def module_path(self):
        return "deepspeed_tpu.ops.random_ltd"


class AsyncIOBuilder(OpBuilder):
    NAME = "async_io"

    def module_path(self):
        return "deepspeed_tpu.ops.aio"

    def is_compatible(self, verbose=False):
        try:
            from deepspeed_tpu.ops import aio
            return aio.is_available()
        except Exception:
            return False


class UtilsBuilder(OpBuilder):
    NAME = "utils"

    def module_path(self):
        return "deepspeed_tpu.ops.flatten_utils"


ALL_OPS = {
    b.NAME: b for b in (FusedAdamBuilder, CPUAdamBuilder, FusedLambBuilder,
                        TransformerBuilder, InferenceBuilder, SparseAttnBuilder,
                        QuantizerBuilder, RandomLTDBuilder, AsyncIOBuilder,
                        UtilsBuilder)
}


def get_builder(name):
    name = name.lower().replace("builder", "")
    aliases = {"fusedadam": "fused_adam", "cpuadam": "cpu_adam",
               "fusedlamb": "fused_lamb", "transformerinference": "transformer_inference",
               "sparseattn": "sparse_attn", "randomltd": "random_ltd",
               "asyncio": "async_io"}
    name = aliases.get(name, name)
    if name not in ALL_OPS:
        raise ValueError(f"unknown op builder: {name}; known: {sorted(ALL_OPS)}")
    return ALL_OPS[name]()


def op_report():
    """Compatibility report (reference ``deepspeed/env_report.py`` /
    ``bin/ds_report``)."""
    lines = ["op name " + "." * 20 + " compatible"]
    for name, cls in sorted(ALL_OPS.items()):
        ok = cls().is_compatible()
        lines.append(f"{name:<28} {'[OKAY]' if ok else '[NO]'}")
    return "\n".join(lines)
