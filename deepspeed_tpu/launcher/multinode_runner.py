"""Multi-node runners — reference ``launcher/multinode_runner.py``
(``MultiNodeRunner`` ABC ``:18`` + PDSH ``:51`` / OpenMPI ``:107`` / MPICH
``:160`` / SLURM ``:217`` / MVAPICH ``:265``).

Each runner turns (hostfile resources, user script, env) into the one shell
command that fans the per-host process out.  On TPU pods the per-host
process is a single JAX controller; the env exported to every host carries
the ``jax.distributed`` coordinator triple (the analog of the reference's
MASTER_ADDR/RANK env) — DSTPU_COORDINATOR_ADDRESS / DSTPU_NUM_PROCESSES /
DSTPU_PROCESS_ID (the last is assigned per-host by the runner's rank
mechanism: pdsh %n, SLURM_PROCID, OMPI rank, …).
"""

import os
import shutil
import sys
from abc import ABC, abstractmethod

from deepspeed_tpu.utils.logging import logger


class MultiNodeRunner(ABC):

    def __init__(self, args, world_info_base64=""):
        self.args = args
        self.user_script = getattr(args, "user_script", "")
        self.user_arguments = list(getattr(args, "user_args", []))
        self.world_info_base64 = world_info_base64
        self.exports = {}

    def add_export(self, key, var):
        self.exports[key.strip()] = str(var).strip()

    @property
    def name(self):
        return type(self).__name__

    @abstractmethod
    def backend_exists(self):
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        """Build the launch argv (reference ``get_cmd``)."""

    def validate_args(self):
        if not self.user_script:
            raise ValueError(f"{self.name}: no user script to launch")


class PDSHRunner(MultiNodeRunner):
    """Reference ``:51``: pdsh -w host1,host2 '<env> python script args'."""

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        self.validate_args()
        hosts = ",".join(active_resources.keys())
        import shlex
        env_flags = [f"export {k}={shlex.quote(v)};"
                     for k, v in self.exports.items()]
        # %n is pdsh's per-host rank — becomes the jax process id
        env_flags.append("export DSTPU_PROCESS_ID=%n;")
        remote = " ".join(
            [f"cd {shlex.quote(os.getcwd())};"] + env_flags
            + [shlex.quote(c) for c in
               [sys.executable, "-u", self.user_script] + self.user_arguments])
        return ["pdsh", "-S", "-f", "1024", "-w", hosts, remote]


def _rank_wrapped_tail(user_script, user_arguments, rank_var):
    """Per-host shell that maps the backend's rank var to the jax process
    id and restores the launch cwd before exec'ing the user script."""
    import shlex
    tail = " ".join(shlex.quote(c) for c in
                    [sys.executable, "-u", user_script] + list(user_arguments))
    return ["bash", "-c",
            f"cd {shlex.quote(os.getcwd())} && "
            f"DSTPU_PROCESS_ID=${{{rank_var}}} exec {tail}"]


class OpenMPIRunner(MultiNodeRunner):
    """Reference ``:107``: mpirun with one proc per host and -x env exports."""

    rank_var = "OMPI_COMM_WORLD_RANK"

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        self.validate_args()
        total = len(active_resources)
        # --host takes the FILTERED pool (not the raw hostfile, which may
        # contain --exclude'd hosts)
        cmd = ["mpirun", "-n", str(total), "--map-by", "ppr:1:node",
               "--host", ",".join(active_resources.keys()),
               "--mca", "btl", "^openib"]
        for k, v in self.exports.items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + _rank_wrapped_tail(self.user_script, self.user_arguments,
                                        self.rank_var)


class MPICHRunner(MultiNodeRunner):
    """Reference ``:160``."""

    rank_var = "PMI_RANK"

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        self.validate_args()
        total = len(active_resources)
        cmd = ["mpirun", "-n", str(total), "-ppn", "1",
               "-hosts", ",".join(active_resources.keys())]
        for k, v in self.exports.items():
            cmd += ["-genv", k, v]
        return cmd + _rank_wrapped_tail(self.user_script, self.user_arguments,
                                        self.rank_var)


class SlurmRunner(MultiNodeRunner):
    """Reference ``:217``: srun with --export and -N nodes."""

    def backend_exists(self):
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        self.validate_args()
        total = len(active_resources)
        exports = ",".join(f"{k}={v}" for k, v in self.exports.items())
        # -w pins the FILTERED host pool (parity with the --host fix for
        # the MPI runners; --exclude'd nodes must not receive ranks)
        cmd = ["srun", "-N", str(total), "--ntasks-per-node=1",
               "-w", ",".join(active_resources.keys())]
        if exports:
            cmd.append(f"--export=ALL,{exports}")
        if getattr(self.args, "comment", ""):
            cmd += ["--comment", self.args.comment]
        return cmd + _rank_wrapped_tail(self.user_script, self.user_arguments,
                                        "SLURM_PROCID")


class MVAPICHRunner(MPICHRunner):
    """Reference ``:265`` — MVAPICH shares MPICH's cli surface for our needs."""


RUNNERS = {
    "pdsh": PDSHRunner,
    "openmpi": OpenMPIRunner,
    "mpich": MPICHRunner,
    "slurm": SlurmRunner,
    "mvapich": MVAPICHRunner,
}


def build_runner(launcher, args, world_info_base64=""):
    if launcher not in RUNNERS:
        raise ValueError(f"unknown launcher {launcher!r}; "
                         f"choices: {sorted(RUNNERS)}")
    runner = RUNNERS[launcher](args, world_info_base64)
    if not runner.backend_exists():
        logger.warning(f"{runner.name}: backend binary not found on PATH")
    return runner
