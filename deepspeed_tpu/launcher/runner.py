"""Launcher CLI — parity with reference ``launcher/runner.py:377`` (main),
``launch.py:216`` (per-node spawn), ``multinode_runner.py`` (PDSH/MPI/SLURM).

TPU launch model differs fundamentally from the GPU one: JAX is
single-controller-per-host (one Python process drives all local chips), so
the per-GPU process fan-out (``launch.py``) collapses to one process per
host.  What remains:

* single host: exec the training script directly (all local chips visible);
* TPU pods: one process per host, each calling ``jax.distributed.initialize``
  — coordinator env (DSTPU_COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID)
  is injected per-host, the analog of RANK/WORLD_SIZE env the reference sets
  (``launch.py:216``);
* multi-node over ssh: hostfile-driven remote spawn (the PDSH runner analog,
  ``multinode_runner.py:51``).
"""

import argparse
import base64
import json
import os
import shlex
import subprocess
import sys

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile: lines of '<host> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Include filter, e.g. 'host1@host2'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Exclude filter")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "local", "pdsh", "openmpi", "mpich",
                                 "slurm", "mvapich"])
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--elastic_training", action="store_true")
    parser.add_argument("--min_elastic_nodes", type=int, default=-1)
    parser.add_argument("--max_elastic_nodes", type=int, default=-1)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path):
    """Parse '<host> slots=<n>' lines (reference ``runner.py:189``)."""
    if not os.path.isfile(hostfile_path):
        return {}
    resource_pool = {}
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            if host in resource_pool:
                raise ValueError(f"host {host} repeated in hostfile")
            resource_pool[host] = slots
    return resource_pool


def _filter_hosts(resource_pool, include_str, exclude_str):
    """--include/--exclude host filters (reference ``runner.py:244``)."""
    hosts = dict(resource_pool)
    if include_str:
        keep = set(include_str.split("@"))
        hosts = {h: s for h, s in hosts.items() if h in keep}
    if exclude_str:
        drop = set(exclude_str.split("@"))
        hosts = {h: s for h, s in hosts.items() if h not in drop}
    return hosts


def encode_world_info(resource_pool):
    """b64 world info (reference ``runner.py:342``)."""
    data = json.dumps(resource_pool).encode()
    return base64.urlsafe_b64encode(data).decode()


def decode_world_info(encoded):
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)
    resource_pool = _filter_hosts(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        resource_pool = dict(list(resource_pool.items())[:args.num_nodes])

    cmd_tail = [args.user_script] + args.user_args

    if not resource_pool or args.launcher == "local":
        # single host: one controller process sees all local chips
        logger.info(f"launching locally: {' '.join(cmd_tail)}")
        env = dict(os.environ)
        result = subprocess.run([sys.executable] + cmd_tail, env=env)
        sys.exit(result.returncode)

    hosts = list(resource_pool)
    master = args.master_addr or hosts[0]
    world = len(hosts)

    if args.launcher not in ("ssh",):
        # PDSH/MPI/SLURM fan-out through the MultiNodeRunner command builders
        # (reference multinode_runner.py); one process per host, coordinator
        # env exported everywhere, per-host rank from the backend's own
        # rank mechanism
        from deepspeed_tpu.launcher.multinode_runner import build_runner
        runner = build_runner(args.launcher, args,
                              encode_world_info(resource_pool))
        runner.add_export("DSTPU_COORDINATOR_ADDRESS",
                          f"{master}:{args.master_port}")
        runner.add_export("DSTPU_NUM_PROCESSES", str(world))
        cmd = runner.get_cmd(dict(os.environ), resource_pool)
        logger.info(f"launching via {runner.name}: {' '.join(cmd)}")
        result = subprocess.run(cmd, env=dict(os.environ))
        sys.exit(result.returncode)

    procs = []
    logger.info(f"launching on {world} hosts via {args.launcher}: {hosts}")
    for pid, host in enumerate(hosts):
        env_exports = {
            "DSTPU_COORDINATOR_ADDRESS": f"{master}:{args.master_port}",
            "DSTPU_NUM_PROCESSES": str(world),
            "DSTPU_PROCESS_ID": str(pid),
        }
        export_str = " ".join(f"{k}={v}" for k, v in env_exports.items())
        remote_cmd = f"cd {shlex.quote(os.getcwd())} && {export_str} " \
                     f"{sys.executable} {' '.join(shlex.quote(c) for c in cmd_tail)}"
        if host in ("localhost", "127.0.0.1"):
            p = subprocess.Popen(["bash", "-c", remote_cmd])
        else:
            p = subprocess.Popen(["ssh", "-o", "StrictHostKeyChecking=no",
                                  host, remote_cmd])
        procs.append(p)
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
