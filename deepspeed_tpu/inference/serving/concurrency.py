"""Concurrency contract for the serving host path — the machine-checkable
registry behind tpu-lint's TL008/TL009 rules, the
``DSTPU_CONCURRENCY_CHECKS=1`` runtime prover, and the engine-lock wait
meter (``docs/serving.md`` "Network front end", ``docs/tpu_lint.md``
"Concurrency contracts").

The serving engine is genuinely multi-threaded: one engine lock, an
owner-bound scheduler thread, condvar-blocked submits, and an asyncio
loop bridging in via ``run_in_executor``.  Every piece of mutable
scheduler state is therefore DECLARED here, exactly once, as
lock-guarded — and three independent checkers consume the declaration:

* **TL008** (static): every source read/write of a guarded field must
  sit inside a ``with self._lock`` scope or a method annotated
  ``# lock-held: _lock`` (the rule parses THIS file, it never imports
  it — the registry literals below must stay pure literals).
* **TL009** (static): ``async def`` handlers and loop callbacks must
  route calls to :data:`LOCKED_METHODS` through ``run_in_executor`` and
  must never touch :data:`OWNER_BOUND_METHODS` at all.
* **Runtime** (:func:`install_concurrency_checks`): with
  ``DSTPU_CONCURRENCY_CHECKS=1`` every guarded-field access asserts the
  engine lock is held by the current thread — the dynamic half the
  interleaving stress harness (``tools/lint/interleave_check.py``)
  drives under randomized injected yields.

Deliberately NOT in the registry (each with its reason):

* ``wake`` — a ``threading.Event``, internally synchronized.
* ``_flightrec`` — the flight recorder's ring has its OWN lock
  (``flightrec.py``): readers (``/debug/flightrec``, SIGUSR2, crash
  dumps) must never contend the engine lock, and the reference is set
  once in ``__init__``.
* ``_hist`` — the serving histograms are internally locked per bucket
  set (``monitor/trace.py``): the /metrics scrape renders them without
  the engine lock.
* ``_breaker`` — mutated only under the lock; its unlocked reads are
  single-attribute monitoring probes with no compound invariant.
* ``_lock`` / ``_cond`` — the guards themselves.
* ``FairnessTracker`` internals — the tracker has no lock of its own;
  it is reachable ONLY through the engine's ``_fairness`` attribute,
  which IS guarded, so every window read/write inherits the engine
  lock transitively (``frontend/fairness.py``).
* configuration set once in ``__init__`` and never mutated
  (``num_slots``, ``cache_len``, ``paged``, ``config``, ...).
"""

import os
import threading
import time
from collections import deque

# ---------------------------------------------------------------------- #
# The registry — pure literals (tpu-lint parses this file statically)
# ---------------------------------------------------------------------- #
# class -> {field -> lock attribute that must be held to touch it}
GUARDED_FIELDS = {
    "ServingEngine": {
        # request queue + admission
        "_queue": "_lock",
        "_pending": "_lock",
        "_requests": "_lock",
        "_next_rid": "_lock",
        # host mirror of device state (the lag-one protocol)
        "_events": "_lock",
        "_slots": "_lock",
        "_free": "_lock",
        "_mirror_active": "_lock",
        "_slot_last_dispatch": "_lock",
        "_state": "_lock",
        "_cache": "_lock",
        "_rng": "_lock",
        # paged-KV host bookkeeping
        "_slot_pages": "_lock",
        "_page_table": "_lock",
        "_pool": "_lock",
        "_prefix": "_lock",
        # speculative-decoding draft mirror (the draft KV workspace
        # handle chains dispatch-to-dispatch like _cache/_state; the
        # draft lane pool hands out admission prefill lanes one event
        # behind, like _lane_pool's donated-liveness contract)
        "_draft_cache": "_lock",
        "_draft_lanes": "_lock",
        # results / lifecycle
        "_results": "_lock",
        "_pending_reports": "_lock",
        "_closed": "_lock",
        "_close_report": "_lock",
        "_it": "_lock",
        "_snap_seq": "_lock",
        "_owner_thread": "_lock",
        # token streams + fairness + counters
        "_streams": "_lock",
        "_fairness": "_lock",
        "stats": "_lock",
        "occupancy_trace": "_lock",
        # observability: the span tracer's ring is appended to at the
        # scheduler seams (lock-held) and copied whole by dump_trace()
        "_tracer": "_lock",
        # the device-memory sampler mutates its cadence/last-sample
        # state at the same scheduler seam stats is grown at (the
        # /metrics render reads its .last snapshot under the lock too)
        "_memwatch": "_lock",
    },
}

# class -> {alias lock attr -> canonical lock attr}: the engine's condvar
# wraps the engine lock, so `with self._cond:` holds `_lock` too
LOCK_ALIASES = {
    "ServingEngine": {"_cond": "_lock"},
}

# ServingEngine methods that acquire the engine lock internally — the
# thread-safe surface.  Calling one from an asyncio event-loop thread
# blocks the loop for as long as the scheduler holds the lock (a whole
# step()), so TL009 requires these to go through run_in_executor.
LOCKED_METHODS = (
    "submit", "cancel", "status", "result", "token_events", "close",
    "restore", "snapshot", "health_snapshot", "work_pending",
    "bind_owner", "release_owner",
)

# Driving methods bound to the single scheduler-owner thread — calling
# (or scheduling) one from any other context raises at runtime, so
# TL009 flags every appearance in an async handler or loop callback.
OWNER_BOUND_METHODS = ("step", "drain", "preempt")

ENV_VAR = "DSTPU_CONCURRENCY_CHECKS"


def checks_enabled():
    """True when ``DSTPU_CONCURRENCY_CHECKS`` requests the debug mode."""
    return os.environ.get(ENV_VAR, "").lower() in ("1", "true", "yes", "on")


class ConcurrencyViolation(AssertionError):
    """A guarded field was touched without its lock held — the runtime
    counterpart of a TL008 finding.  Raised at the access, so the stack
    points at the offending read/write, not at a later corruption."""


def _checked_class(base):
    """A subclass of ``base`` whose ``__getattribute__``/``__setattr__``
    assert the declared lock is held for every guarded-field access."""
    guarded = GUARDED_FIELDS["ServingEngine"]

    def _assert_held(self, name, verb):
        lock = object.__getattribute__(self, guarded[name])
        if not lock._is_owned():
            # last-gasp observability: the flight recorder (own lock —
            # safe to touch here) captures the violation and dumps the
            # ring, so the post-mortem shows what the scheduler was
            # doing when the discipline broke.  Strictly best-effort:
            # the violation must raise regardless.
            try:
                fr = object.__getattribute__(self, "_flightrec")
            except AttributeError:
                fr = None
            if fr is not None:
                try:
                    fr.record("concurrency_violation", field=name,
                              verb=verb)
                    fr.dump("concurrency_violation")
                except Exception:        # noqa: BLE001
                    pass
            raise ConcurrencyViolation(
                f"{verb} of lock-guarded field {name!r} from thread "
                f"{threading.current_thread().name!r} without holding "
                f"self.{guarded[name]} (DSTPU_CONCURRENCY_CHECKS=1; "
                f"see docs/tpu_lint.md 'Concurrency contracts')")

    class _Checked(base):
        def __getattribute__(self, name):
            if name in guarded:
                _assert_held(self, name, "read")
            return super().__getattribute__(name)

        def __setattr__(self, name, value):
            if name in guarded:
                _assert_held(self, name, "write")
            super().__setattr__(name, value)

    _Checked.__name__ = base.__name__ + "+concurrency_checks"
    _Checked.__qualname__ = _Checked.__name__
    return _Checked


_checked_cache = {}


def install_concurrency_checks(srv):
    """Flip ``srv`` (a fully-constructed :class:`ServingEngine`) into the
    held-lock-asserting debug subclass.  Idempotent; called from the
    engine's ``__init__`` tail when :func:`checks_enabled`."""
    base = type(srv)
    if getattr(base, "_dstpu_concurrency_checked", False):
        return srv
    checked = _checked_cache.get(base)
    if checked is None:
        checked = _checked_class(base)
        checked._dstpu_concurrency_checked = True
        _checked_cache[base] = checked
    srv.__class__ = checked
    return srv


class InstrumentedRLock:
    """A re-entrant lock that accounts wall time spent WAITING to acquire
    it, split by thread class (the scheduler owner vs everyone else) —
    the serving engine's lock-contention observability
    (``Serving/lock_wait_s`` monitor events,
    ``dstpu_serving_lock_wait_seconds`` in ``/metrics``, and the
    ``lock_wait_*`` percentiles in the ``serving_http`` bench phase).

    Accounting is mutated only AFTER a successful acquire — i.e. while
    holding the lock — so the totals need no extra synchronization.
    ``_owner_ref`` is set by the engine to a zero-arg callable returning
    the current scheduler-owner thread (read lock-held, so it is safe
    under ``DSTPU_CONCURRENCY_CHECKS`` too).  Delegates ``_is_owned`` /
    ``_release_save`` / ``_acquire_restore`` so ``threading.Condition``
    (the engine's blocked-submit condvar) composes; a condvar re-acquire
    after ``wait()`` counts as lock wait — that IS time the thread spent
    blocked on the lock."""

    SAMPLE_WINDOW = 4096                 # newest per-acquire waits kept

    def __init__(self):
        self._inner = threading.RLock()
        self._owner_ref = lambda: None
        self.wait_s = {"scheduler": 0.0, "handler": 0.0}
        self.acquires = {"scheduler": 0, "handler": 0}
        self.samples = {"scheduler": deque(maxlen=self.SAMPLE_WINDOW),
                        "handler": deque(maxlen=self.SAMPLE_WINDOW)}
        # optional per-acquire observer ``(thread_class, wait_s) -> None``
        # — the serving engine points it at its lock-wait histogram
        # under ``serving.tracing``.  Called lock-HELD (right after a
        # successful acquire) and must be internally synchronized and
        # non-raising; exceptions are swallowed so a broken observer
        # can never poison the lock.
        self.on_wait = None

    def _account(self, dt):
        cls = ("scheduler"
               if threading.current_thread() is self._owner_ref()
               else "handler")
        self.wait_s[cls] += dt
        self.acquires[cls] += 1
        self.samples[cls].append(dt)
        cb = self.on_wait
        if cb is not None:
            try:
                cb(cls, dt)
            except Exception:            # noqa: BLE001 — observer only
                pass

    def acquire(self, blocking=True, timeout=-1):
        if self._inner._is_owned():
            # re-entrant acquire: cannot wait by definition — keep it
            # out of the samples so the wait percentiles measure real
            # contention, not the locked monitoring properties
            # re-entering from an already-locked caller
            return self._inner.acquire(blocking, timeout)
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._account(time.perf_counter() - t0)
        return ok

    def release(self):
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # ---- threading.Condition integration ----
    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        return self._inner._release_save()

    def _acquire_restore(self, state):
        t0 = time.perf_counter()
        self._inner._acquire_restore(state)
        self._account(time.perf_counter() - t0)


__all__ = ["GUARDED_FIELDS", "LOCK_ALIASES", "LOCKED_METHODS",
           "OWNER_BOUND_METHODS", "checks_enabled",
           "ConcurrencyViolation", "install_concurrency_checks",
           "InstrumentedRLock"]
