"""Checkpoint shard loaders with TP resharding — TPU-native re-design of
reference ``runtime/state_dict_factory.py`` (``SDLoaderFactory`` /
``MegatronSDLoader``): load a checkpoint saved at one tensor-parallel degree
into an engine running at another, merging or splitting the TP-sharded
weights.

On TPU the target layout is a ``PartitionSpec``, not a rank's slice, so
"merge" = concatenate shard files along the weight's TP axis and hand the
full tensor to ``jax.device_put`` with its target sharding (XLA scatters it);
"split" = slicing is free (device_put of the full tensor against a sharded
spec).  The axis conventions mirror Megatron: qkv/intermediate weights are
column-parallel (concat on the output dim — flax kernels: last axis), output
projections are row-parallel (concat on the input dim — axis 0).
"""

import glob
import json
import os
import re

import numpy as np

import jax

from deepspeed_tpu.utils.logging import logger

AUTO_TP_VERSION = 1.0

# Megatron/HF column-parallel (output-dim-sharded) weight name patterns;
# everything else matching *_proj/dense is row-parallel
COLUMN_PARALLEL_PATTERNS = (
    r"q_proj", r"k_proj", r"v_proj", r"query", r"key", r"value",
    r"query_key_value", r"qkv", r"gate_proj", r"up_proj", r"fc1",
    r"intermediate", r"h_to_4h", r"wi", r"in_proj",
)
ROW_PARALLEL_PATTERNS = (
    r"o_proj", r"out_proj", r"down_proj", r"fc2", r"dense_4h_to_h",
    r"attention\.dense", r"attn\.dense", r"wo",
)

# fused QKV weights need version-aware merge/split (see _merge_qkv)
FUSED_QKV_RE = re.compile(r"(^|[._/])(query_key_value|qkv)([._/]|$)")
# megatron VocabParallelEmbedding shards the vocab dim; positions replicate
VOCAB_EMBED_RE = re.compile(r"word_embeddings\.weight$")


def get_sd_loader_json(json_file_or_dict):
    """Parse a DeepSpeed checkpoint description json (reference
    ``SDLoaderFactory.get_sd_loader_json``): returns (type, paths, version)."""
    if isinstance(json_file_or_dict, dict):
        data = json_file_or_dict
    else:
        with open(json_file_or_dict) as f:
            data = json.load(f)
    ckpt_type = data.get("type", "Megatron")
    ckpt_list = data.get("checkpoints", [])
    if isinstance(ckpt_list, dict):  # BLOOM-style {tp_rank: [files]}
        ckpt_list = [f for fs in ckpt_list.values()
                     for f in (fs if isinstance(fs, list) else [fs])]
    version = data.get("version", 0.0)
    base_dir = data.get("base_dir", "")
    if base_dir:
        ckpt_list = [os.path.join(base_dir, c) for c in ckpt_list]
    return ckpt_type, ckpt_list, version


def get_sd_loader(ckpt_list, sd_type="Megatron", version=None):
    """Factory (reference ``SDLoaderFactory.get_sd_loader``)."""
    return MegatronSDLoader(ckpt_list, version)


def _classify(name):
    """Classify by token-boundary-anchored match: short patterns like 'wo'
    must not fire inside unrelated names ('word_embeddings')."""
    def hit(pat):
        return re.search(rf"(^|[._/]){pat}([._/]|$)", name)

    for pat in COLUMN_PARALLEL_PATTERNS:
        if hit(pat):
            return "column"
    for pat in ROW_PARALLEL_PATTERNS:
        if hit(pat):
            return "row"
    return "replicated"


class SDLoaderBase:

    def __init__(self, ckpt_list, version=None):
        self.ckpt_list = sorted(ckpt_list)
        self.version = version

    def __len__(self):
        return len(self.ckpt_list)

    def load_shard(self, path):
        """One shard file → flat {name: np.ndarray}.  Supports .npz and
        torch .pt/.bin files (torch is cpu-importable in this image)."""
        if path.endswith(".npz"):
            with np.load(path, allow_pickle=True) as z:
                return {k: np.asarray(z[k]) for k in z.files}
        import torch
        sd = torch.load(path, map_location="cpu", weights_only=False)
        if isinstance(sd, dict) and "module" in sd:
            sd = sd["module"]
        return {k: v.numpy() if hasattr(v, "numpy") else np.asarray(v)
                for k, v in sd.items() if hasattr(v, "shape")}


class MegatronSDLoader(SDLoaderBase):
    """Merge/split Megatron-style TP shards (reference
    ``state_dict_factory.py`` ``MegatronSDLoader.merge_state_dict`` /
    ``split_state_dict``)."""

    def merge_state_dict(self, mp_world_size=1, quantize=False, **kw):
        """All shards → one full state dict (TP degree n → 1).

        Column-parallel weights concatenate on the output axis, row-parallel
        on the input axis; biases of row-parallel layers and all replicated
        tensors are taken from rank 0 (they are identical across ranks)."""
        shards = [self.load_shard(p) for p in self.ckpt_list]
        if len(shards) == 1:
            return shards[0]
        merged = {}
        for name, first in shards[0].items():
            parts = [s[name] for s in shards]
            kind = _classify(name)
            if FUSED_QKV_RE.search(name):
                # fused QKV needs version-aware merging (reference
                # ``merge_query_key_value``): v1 shards are internally
                # [q_r|k_r|v_r], so naive concat would interleave per-rank
                # q/k/v blocks.  Megatron v2 interleaves per head — plain
                # concat on the output axis is correct there.
                merged[name] = self._merge_qkv(parts, name)
            elif VOCAB_EMBED_RE.search(name) and first.ndim == 2 \
                    and (any(p.shape != first.shape for p in parts[1:])
                         or not all(np.array_equal(p, first)
                                    for p in parts[1:])):
                # megatron VocabParallelEmbedding: differing shards → the
                # vocab dim is TP-sharded, concatenate it (shape check first:
                # unevenly-split shards must not hit the elementwise compare).
                # Identical shards mean a replicated embedding
                # (inference-export checkpoints).
                merged[name] = np.concatenate(parts, axis=0)
            elif first.ndim == 0 or kind == "replicated":
                merged[name] = parts[0]
            elif first.ndim == 1:
                # column-parallel bias shards concatenate; row-parallel
                # biases are replicated across ranks.  Decide by kind, not
                # by value equality — zero-initialized column biases must
                # still concatenate.
                merged[name] = np.concatenate(parts, axis=0) \
                    if kind == "column" else parts[0]
            elif kind == "column":
                # torch Linear weight [out, in] → concat outputs on axis 0;
                # flax kernels [in, out] → axis -1.  Heuristic: torch layout
                # when name endswith 'weight'
                axis = 0 if name.endswith("weight") else -1
                merged[name] = np.concatenate(parts, axis=axis)
            else:  # row
                axis = 1 if name.endswith("weight") else 0
                merged[name] = np.concatenate(parts, axis=axis)
        return merged

    def _merge_qkv(self, parts, name):
        """Merge fused query_key_value shards (output axis 0 in torch
        layout).  ``version >= 2`` (or unset) → head-interleaved rows, plain
        concat.  ``version < 2`` → each shard is [q_r|k_r|v_r]: split every
        shard into thirds and concatenate per projection."""
        axis = 0
        if self.version is None or float(self.version) >= 2.0:
            return np.concatenate(parts, axis=axis)
        thirds = [np.split(p, 3, axis=axis) for p in parts]
        return np.concatenate(
            [np.concatenate([t[j] for t in thirds], axis=axis)
             for j in range(3)], axis=axis)

    def split_state_dict(self, mp_world_size, mp_rank, quantize=False, **kw):
        """Full state dict → this rank's TP shard (TP degree 1 → n)."""
        full = self.merge_state_dict()
        out = {}
        for name, w in full.items():
            kind = _classify(name)
            if FUSED_QKV_RE.search(name) \
                    and (self.version is not None
                         and float(self.version) < 2.0):
                # v1 fused QKV: rank r takes [q_r|k_r|v_r]
                q, k, v = np.split(w, 3, axis=0)
                out[name] = np.concatenate(
                    [np.split(t, mp_world_size, axis=0)[mp_rank]
                     for t in (q, k, v)], axis=0)
                continue
            if VOCAB_EMBED_RE.search(name) and w.ndim == 2:
                # inverse of the merge-side vocab concat: shard the vocab dim
                assert w.shape[0] % mp_world_size == 0, \
                    f"{name}: vocab {w.shape[0]} not divisible by TP degree"
                out[name] = np.split(w, mp_world_size, axis=0)[mp_rank]
                continue
            if w.ndim == 0 or kind == "replicated":
                out[name] = w
                continue
            if kind == "column":
                axis = 0 if (w.ndim > 1 and name.endswith("weight")) else \
                    (w.ndim - 1 if w.ndim > 1 else 0)
            else:
                if w.ndim == 1:
                    out[name] = w  # row-parallel bias replicates
                    continue
                axis = 1 if name.endswith("weight") else 0
            n = w.shape[axis]
            assert n % mp_world_size == 0, \
                f"{name}: dim {n} not divisible by mp_world_size={mp_world_size}"
            out[name] = np.split(w, mp_world_size, axis=axis)[mp_rank]
        return out

    def load(self, mp_world_size, mp_rank, **kw):
        """Reference ``SDLoaderBase.load``: pick merge / split / passthrough
        by comparing checkpoint TP degree to target TP degree."""
        n = len(self.ckpt_list)
        if n == mp_world_size:
            return self.load_shard(self.ckpt_list[mp_rank])
        if n > mp_world_size:
            assert n % mp_world_size == 0
            # merge each group of n/mp shards
            per = n // mp_world_size
            group = MegatronSDLoader(
                self.ckpt_list[mp_rank * per:(mp_rank + 1) * per], self.version)
            return group.merge_state_dict()
        assert mp_world_size % n == 0
        per = mp_world_size // n
        shard = MegatronSDLoader([self.ckpt_list[mp_rank // per]], self.version)
        return shard.split_state_dict(per, mp_rank % per)


SDLoaderFactory = type("SDLoaderFactory", (), {
    "get_sd_loader_json": staticmethod(get_sd_loader_json),
    "get_sd_loader": staticmethod(get_sd_loader),
})
