"""tpu-lint rules — importing this package registers every rule."""

from deepspeed_tpu.tools.lint.rules import (  # noqa: F401
    tl001_host_transfer,
    tl002_missing_donation,
    tl003_jit_side_effects,
    tl004_bad_static_args,
    tl005_hot_dict_lookup,
    tl006_retrace_drift,
    tl007_use_after_donation,
    tl008_lock_discipline,
    tl009_loop_blocking,
    tl010_replicated_sharding,
    tl011_resharding_seams,
)
