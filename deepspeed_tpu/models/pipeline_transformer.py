"""Pipelined transformer — the ``GPT2ModelPipe`` pattern for this framework
(reference ``runtime/pipe/module.py:85,353,406-427``): builds a
``PipelineModule`` from a ``TransformerConfig``.

Layer decomposition:

* ``EmbedPipe``  — token (+ learned position) embeddings, OPT-350M
  ``project_in``, Bloom ``embedding_norm``;
* ``BlockGroupPipe`` — ``group_size`` consecutive REAL ``Block``s from
  ``models/transformer.py`` (so post-LN, parallel residual, per-layer
  attention configs and MoE all behave exactly like the dense model).  The
  group size is the smallest period of any per-layer heterogeneity
  (``moe_every``, ``attention_layers`` pattern), making every group's param
  structure identical — the uniform trunk the SPMD pipeline stacks;
* ``HeadPipe`` / ``NormProjPipe`` + tied head — final norm (pre-LN only),
  OPT-350M ``project_out``, LM head.  ``tie_word_embeddings`` uses
  ``TiedLayerSpec`` (reference ``pipe/module.py:76``): the head re-uses
  ``EmbedPipe``'s parameters via ``forward_fn``.

MoE trunks thread the load-balancing aux loss through the pipeline as part
of the activation pytree ``(hidden, aux)``.
"""

import math

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.models.transformer import (TransformerConfig, Block,
                                              _norm, cross_entropy_loss,
                                              resolve_moe_offset)
from deepspeed_tpu.runtime.pipe.module import (PipelineModule, LayerSpec,
                                               TiedLayerSpec)


class EmbedPipe(nn.Module):
    """ids → hidden activations, mirroring ``Transformer.hidden_states``'s
    embedding prologue (``models/transformer.py``)."""
    config: TransformerConfig
    carry_aux: bool = False

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        embed_dim = cfg.embed_proj_dim or cfg.hidden_size
        x = nn.Embed(cfg.vocab_size, embed_dim, param_dtype=jnp.float32,
                     name="embed_tokens")(input_ids).astype(cfg.jnp_dtype)
        if cfg.embed_proj_dim is not None:
            x = nn.Dense(cfg.hidden_size, use_bias=False, dtype=cfg.jnp_dtype,
                         param_dtype=jnp.float32, name="project_in")(x)
        if cfg.position_embedding == "learned":
            B, S = input_ids.shape
            pos = jnp.broadcast_to(jnp.arange(S), (B, S))
            x = x + nn.Embed(cfg.max_seq_len, cfg.hidden_size,
                             param_dtype=jnp.float32,
                             name="embed_positions")(pos).astype(cfg.jnp_dtype)
        if cfg.embedding_norm:
            x = _norm(cfg, "embed_norm")(x)
        x = x.astype(cfg.jnp_dtype)
        if self.carry_aux:
            # rank-1, not scalar: jax 0.4.x shard_map mis-specs scalar
            # cotangents when transposing the pipeline region (_SpecError)
            return x, jnp.zeros((1,), jnp.float32)
        return x


class BlockGroupPipe(nn.Module):
    """``group_size`` consecutive dense-model ``Block``s as one pipe layer.

    Positions are recomputed from shape (the pipeline passes activations
    only; reference ``pipe/module.py`` layers are single-tensor too).
    ``layer_idx`` is group-relative — valid because the group size is a
    multiple of every per-layer pattern period (asserted in
    ``transformer_pipe``)."""
    config: TransformerConfig
    group_size: int = 1
    carry_aux: bool = False

    @nn.compact
    def __call__(self, xa, train=True):
        cfg = self.config
        if self.carry_aux:
            x, aux = xa
        else:
            x, aux = xa, None
        B, S, _ = x.shape
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        for j in range(self.group_size):
            blk = Block(cfg, layer_idx=j, name=f"layers_{j}")
            # train selects the MoE gate's capacity/noise regime (the dense
            # Transformer passes it the same way)
            x, _, a = blk(x, positions, None, None, train)
            if aux is not None:
                aux = aux + a
        return (x, aux) if self.carry_aux else x


def _head_prefix(cfg, x):
    """Shared head prologue — final norm (pre-LN only) + OPT-style
    down-projection.  Submodules attach to the CALLING module (flax
    compact), so tied and untied heads stay one implementation."""
    if cfg.pre_layer_norm:
        x = _norm(cfg, "final_norm")(x).astype(cfg.jnp_dtype)
    if cfg.embed_proj_dim is not None:
        x = nn.Dense(cfg.embed_proj_dim, use_bias=False,
                     dtype=cfg.jnp_dtype, param_dtype=jnp.float32,
                     name="project_out")(x)
    return x


class HeadPipe(nn.Module):
    """final-norm (pre-LN) → project_out (OPT-350M) → LM head."""
    config: TransformerConfig
    carry_aux: bool = False

    @nn.compact
    def __call__(self, xa):
        cfg = self.config
        x, aux = xa if self.carry_aux else (xa, None)
        x = _head_prefix(cfg, x)
        logits = nn.Dense(cfg.vocab_size, use_bias=cfg.lm_head_bias,
                          dtype=cfg.jnp_dtype, param_dtype=jnp.float32,
                          name="lm_head")(x)
        return (logits, aux) if self.carry_aux else logits


class NormProjPipe(nn.Module):
    """The head's own-parameter prefix when the LM head itself is tied to
    the embedding (the tied matmul follows as a TiedLayerSpec)."""
    config: TransformerConfig
    carry_aux: bool = False

    @nn.compact
    def __call__(self, xa):
        cfg = self.config
        x, aux = xa if self.carry_aux else (xa, None)
        x = _head_prefix(cfg, x)
        return (x, aux) if self.carry_aux else x


def _tied_head_fn(config: TransformerConfig, carry_aux: bool):
    """``forward_fn`` for the tied LM head: logits = x @ embed.T using
    EmbedPipe's parameters (reference tied-weight sync,
    ``pipe/module.py:406-427`` — here GSPMD owns the single copy, so no
    cross-stage allreduce exists to begin with)."""

    def fwd(params, xa):
        x, aux = xa if carry_aux else (xa, None)
        W = jnp.asarray(params["params"]["embed_tokens"]["embedding"],
                        config.jnp_dtype)
        logits = x @ W.T
        return (logits, aux) if carry_aux else logits

    return fwd


def _pattern_period(pattern):
    """Smallest p dividing len(pattern) with pattern[i] == pattern[i % p]."""
    n = len(pattern)
    for p in range(1, n + 1):
        if n % p == 0 and all(pattern[i] == pattern[i % p] for i in range(n)):
            return p
    return n


def _infer_group_size(cfg: TransformerConfig) -> int:
    """Layers per BlockGroupPipe: the lcm of every per-layer pattern period,
    so group-relative ``layer_idx`` reproduces the absolute pattern."""
    g = 1
    if cfg.moe_num_experts > 0:
        off = resolve_moe_offset(cfg)
        if off >= cfg.moe_every:
            # layers [0, off) form a dense prefix that breaks the period —
            # group-relative layer_idx could no longer reproduce the
            # absolute pattern (groups would silently come out all-dense)
            raise ValueError(
                f"moe_layer_offset={off} >= moe_every={cfg.moe_every}: the "
                f"MoE pattern has an aperiodic dense prefix and cannot be "
                f"stacked into a uniform pipeline trunk — use the plain "
                f"Transformer (absolute layer indices) for this layout")
        g = math.lcm(g, cfg.moe_every)
    if cfg.attention_layers is not None:
        g = math.lcm(g, _pattern_period(tuple(cfg.attention_layers)))
    if cfg.num_layers % g != 0:
        raise ValueError(
            f"num_layers={cfg.num_layers} is not divisible by the per-layer "
            f"pattern period {g} (moe_every={cfg.moe_every}, "
            f"attention_layers period) — the pipeline trunk cannot be "
            f"stacked uniformly")
    return g


def make_lm_loss(config: TransformerConfig):
    carry_aux = config.moe_num_experts > 0

    def lm_loss(out, labels):
        if carry_aux:
            logits, aux = out
            return cross_entropy_loss(logits, labels) \
                + config.moe_aux_coef * jnp.sum(aux)
        return cross_entropy_loss(out, labels)

    return lm_loss


def transformer_pipe(config: TransformerConfig, num_stages=None,
                     **pipe_kwargs) -> PipelineModule:
    """Build a PipelineModule for any ``TransformerConfig`` trunk: pre-LN
    and post-LN (OPT-350M), embed projection, MoE (aux loss threaded through
    the activation), per-layer attention patterns, tied embeddings."""
    carry_aux = config.moe_num_experts > 0
    group = _infer_group_size(config)
    n_groups = config.num_layers // group

    if config.tie_word_embeddings:
        layers = [TiedLayerSpec("embed", EmbedPipe, config,
                                carry_aux=carry_aux)]
    else:
        layers = [LayerSpec(EmbedPipe, config, carry_aux=carry_aux)]
    layers += [LayerSpec(BlockGroupPipe, config, group_size=group,
                         carry_aux=carry_aux) for _ in range(n_groups)]
    if config.tie_word_embeddings:
        layers += [LayerSpec(NormProjPipe, config, carry_aux=carry_aux),
                   TiedLayerSpec("embed", EmbedPipe, config,
                                 carry_aux=carry_aux,
                                 forward_fn=_tied_head_fn(config, carry_aux))]
    else:
        layers += [LayerSpec(HeadPipe, config, carry_aux=carry_aux)]
    return PipelineModule(layers, num_stages=num_stages,
                          loss_fn=make_lm_loss(config), **pipe_kwargs)
