"""Collective micro-benchmarks (reference ``bin/ds_bench`` → comms
benchmarks): sweep message sizes over the mesh's collectives and report
algbw/busbw."""

import argparse
import time

import numpy as np
from deepspeed_tpu.utils.jax_compat import shard_map as _shard_map


def bench_collective(op_name, sizes_mb, iters=10):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.parallel.topology import get_topology, DP_AXES
    import deepspeed_tpu.comm as dist

    topo = get_topology()
    n = topo.dp
    results = []
    for size_mb in sizes_mb:
        elems = int(size_mb * 1e6 / 4)
        elems = max(n, (elems // n) * n)
        x = jnp.ones((elems,), jnp.float32)
        if op_name == "all_reduce":
            fn = jax.jit(_shard_map(
                lambda v: dist.all_reduce(v, group=DP_AXES),
                mesh=topo.mesh, in_specs=(P(DP_AXES),), out_specs=P(DP_AXES),
                check_vma=False))
        elif op_name == "all_gather":
            fn = jax.jit(_shard_map(
                lambda v: dist.all_gather_into_tensor(v, group=DP_AXES),
                mesh=topo.mesh, in_specs=(P(DP_AXES),), out_specs=P(None),
                check_vma=False))
        elif op_name == "reduce_scatter":
            fn = jax.jit(_shard_map(
                lambda v: dist.reduce_scatter_tensor(v, group=DP_AXES),
                mesh=topo.mesh, in_specs=(P(None),), out_specs=P(DP_AXES),
                check_vma=False))
        else:
            raise ValueError(op_name)
        out = fn(x)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        nbytes = elems * 4
        algbw = nbytes / dt / 1e9
        busbw = algbw * (2 * (n - 1) / n if op_name == "all_reduce" else (n - 1) / n)
        results.append((size_mb, dt * 1e3, algbw, busbw))
    return results


def main():
    import os
    # honor a JAX_PLATFORMS override: the environment may pin the platform
    # at interpreter start (sitecustomize), so the env var alone is not
    # enough — update the live config before the backend initializes
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    parser = argparse.ArgumentParser()
    parser.add_argument("--op", default="all_reduce",
                        choices=["all_reduce", "all_gather", "reduce_scatter"])
    parser.add_argument("--sizes", default="1,8,64", help="MB sizes, comma-sep")
    parser.add_argument("--iters", type=int, default=10)
    args = parser.parse_args()
    sizes = [float(s) for s in args.sizes.split(",")]
    print(f"{'size(MB)':>10}{'lat(ms)':>12}{'algbw(GB/s)':>14}{'busbw(GB/s)':>14}")
    for size_mb, lat, algbw, busbw in bench_collective(args.op, sizes, args.iters):
        print(f"{size_mb:>10.1f}{lat:>12.3f}{algbw:>14.2f}{busbw:>14.2f}")


if __name__ == "__main__":
    main()
