"""Experiment scheduler (reference ``deepspeed/autotuning/scheduler.py:33``
``ResourceManager``).

The reference fans experiments out over multi-node GPU slots via the
launcher, polls for completion, and reaps stragglers.  The TPU analog keeps
the same scheduling machinery — a pool of named resource slots, parallel
dispatch, per-experiment status/timing files, timeouts, and an early-stop
hook that cancels still-pending experiments — with one substitution: an
"experiment" is a callable (typically a fresh jitted program) instead of a
launcher subprocess.

Concurrency note: experiments that EXECUTE on the chip should use one slot
(``num_workers=1``, the default) — concurrent device programs would contend
for HBM and corrupt each other's timings.  Compile-only prechecks, cost-model
evaluations, and simulated/multi-host ``run_fn``s parallelize safely across
slots.
"""

import json
import os
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor, wait, FIRST_COMPLETED

# Experiment lifecycle (reference scheduler's job states).
PENDING = "pending"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
TIMEOUT = "timeout"
SKIPPED = "skipped"          # cancelled by early stop before it ran


class Experiment:
    """One tuning trial: a full DeepSpeed config + results."""

    _next_id = 0

    def __init__(self, name, config):
        self.exp_id = Experiment._next_id
        Experiment._next_id += 1
        self.name = name
        self.config = config
        self.results = {}
        self.error = None
        self.status = PENDING
        self.slot = None
        self.start_time = None
        self.end_time = None

    def to_dict(self):
        return {"exp_id": self.exp_id, "name": self.name, "config": self.config,
                "results": self.results, "error": self.error,
                "status": self.status, "slot": self.slot,
                "duration_s": (round(self.end_time - self.start_time, 3)
                               if self.start_time and self.end_time else None)}


class ResourceManager:
    """Runs experiments through a caller-supplied ``run_fn(exp) -> dict``
    across a pool of resource slots, persisting each result under
    ``exps_dir`` (reference ResourceManager ``schedule_experiments`` /
    ``run_job`` / ``parse_results``).

    ``resources``: slot names (reference: ``hostname:slot`` pairs); default
    ``num_workers`` local slots.  ``exp_timeout``: seconds after which a
    finished experiment is recorded as TIMEOUT (a thread cannot be killed —
    matching the reference, which reaps the subprocess but still waits for
    the ssh session — so the wall-clock loss is bounded by the slowest
    straggler).
    """

    def __init__(self, run_fn, exps_dir=None, resources=None, num_workers=1,
                 exp_timeout=None):
        self.run_fn = run_fn
        self.exps_dir = exps_dir
        if resources is None:
            resources = [f"localhost:{i}" for i in range(max(1, num_workers))]
        self.resources = list(resources)
        self.exp_timeout = exp_timeout
        self.finished_experiments = []
        self._free = list(self.resources)
        self._lock = threading.Lock()
        if exps_dir:
            os.makedirs(exps_dir, exist_ok=True)

    # ------------------------------------------------------------------ #
    def _acquire_slot(self):
        with self._lock:
            return self._free.pop(0) if self._free else None

    def _release_slot(self, slot):
        with self._lock:
            self._free.append(slot)

    def _run_one(self, exp):
        slot = self._acquire_slot()
        exp.slot = slot
        exp.status = RUNNING
        exp.start_time = time.time()
        try:
            exp.results = self.run_fn(exp) or {}
            exp.status = DONE
        except Exception as e:  # an OOM/compile failure is a data point
            exp.error = f"{type(e).__name__}: {e}"
            exp.results = {}
            exp.status = FAILED
            traceback.print_exc()
        finally:
            exp.end_time = time.time()
            if self.exp_timeout and exp.status == DONE and \
                    exp.end_time - exp.start_time > self.exp_timeout:
                # a straggler's measurement is suspect: drop its results so
                # the tuner can never select it (the reference reaps timed-
                # out jobs, which contribute no results either)
                exp.status = TIMEOUT
                exp.results = {}
                exp.error = f"exceeded exp_timeout={self.exp_timeout}s"
            if slot is not None:
                self._release_slot(slot)
        return exp

    def _persist(self, exp):
        if self.exps_dir:
            path = os.path.join(self.exps_dir,
                                f"exp_{exp.exp_id}_{exp.name}.json")
            with open(path, "w") as f:
                json.dump(exp.to_dict(), f, indent=2, default=str)

    # ------------------------------------------------------------------ #
    def schedule_experiments(self, exps, early_stop_fn=None):
        """Dispatch ``exps`` over the slot pool; returns them with results.

        ``early_stop_fn(finished_experiments) -> bool``: consulted after
        every completion; once true, experiments not yet started are marked
        SKIPPED (the reference's cross-node early stop — pending jobs are
        never launched; running ones drain)."""
        exps = list(exps)
        if len(self.resources) == 1:
            # sequential fast path: no thread overhead, same semantics
            for i, exp in enumerate(exps):
                self._run_one(exp)
                self.finished_experiments.append(exp)
                self._persist(exp)
                if early_stop_fn and early_stop_fn(self.finished_experiments):
                    for rest in exps[i + 1:]:
                        rest.status = SKIPPED
                        self.finished_experiments.append(rest)
                        self._persist(rest)
                    break
            return exps

        stop = threading.Event()
        with ThreadPoolExecutor(max_workers=len(self.resources)) as pool:
            pending = list(exps)
            futures = {}
            while pending or futures:
                while pending and len(futures) < len(self.resources) \
                        and not stop.is_set():
                    exp = pending.pop(0)
                    futures[pool.submit(self._run_one, exp)] = exp
                if stop.is_set() and pending:
                    for exp in pending:
                        exp.status = SKIPPED
                        self.finished_experiments.append(exp)
                        self._persist(exp)
                    pending = []
                if not futures:
                    break
                done, _ = wait(list(futures), return_when=FIRST_COMPLETED)
                for fut in done:
                    exp = futures.pop(fut)
                    self.finished_experiments.append(exp)
                    self._persist(exp)
                    if early_stop_fn and \
                            early_stop_fn(self.finished_experiments):
                        stop.set()
        return exps
