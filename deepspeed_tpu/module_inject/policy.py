"""HF-architecture injection policies — base class and weight transforms.

TPU-native counterpart of reference ``module_inject/policy.py:42``
(``TransformerPolicy`` ABC) + ``module_inject/containers/*``.  The reference
describes, per architecture, where to find qkv/mlp weights so it can swap
modules for fused CUDA kernels and slice weights for TP.  Here a policy maps
an HF torch model onto the framework's flax ``Transformer`` (the single
injected implementation — "kernel injection" is XLA compilation):

* ``build_config(hf_config)`` → ``TransformerConfig`` capturing the
  architecture (activation, norm type, rope layout, residual topology);
* ``convert(state_dict, cfg)`` → flat ``{our-param-path: np.ndarray}`` with
  torch→flax layout transforms (transpose, fused-qkv split, head reshape).

TP then happens by sharding annotation (``runtime/zero/partition.py``
``DEFAULT_TP_RULES`` match the converted names), not by weight surgery.
"""

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.utils.logging import logger

# HF activation-string → TransformerConfig.activation.  HF "gelu" is the
# exact erf form; "gelu_new"/tanh variants map to flax's default tanh approx.
ACT_MAP = {
    "relu": "relu",
    "gelu": "gelu_exact",
    "gelu_new": "gelu",
    "gelu_fast": "gelu",
    "gelu_pytorch_tanh": "gelu",
    "silu": "silu",
    "swish": "silu",
    "quick_gelu": "quick_gelu",
}


def _np(t):
    """torch tensor (or array) → float32 numpy (host)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, dtype=np.float32)


def linear_kernel(w):
    """torch Linear weight [out, in] → flax kernel [in, out]."""
    return np.ascontiguousarray(_np(w).T)


def qkv_kernel(w, heads, head_dim):
    """torch [H*D, in] → flax DenseGeneral kernel [in, H, D]."""
    return np.ascontiguousarray(_np(w).T.reshape(-1, heads, head_dim))


def qkv_bias(b, heads, head_dim):
    return _np(b).reshape(heads, head_dim)


def o_kernel(w, heads, head_dim):
    """torch [hidden, H*D] → flax DenseGeneral kernel [H, D, hidden]."""
    return np.ascontiguousarray(_np(w).T.reshape(heads, head_dim, -1))


# -- inverse transforms (export: flax params → HF state dict) ----------- #
def inv_linear_kernel(k):
    """flax kernel [in, out] → torch Linear weight [out, in]."""
    return np.ascontiguousarray(np.asarray(k).T)


def inv_qkv_kernel(k):
    """flax DenseGeneral kernel [in, H, D] → torch [H*D, in]."""
    a = np.asarray(k)
    return np.ascontiguousarray(a.reshape(a.shape[0], -1).T)


def inv_qkv_bias(b):
    """flax bias [H, D] → torch [H*D]."""
    return np.ascontiguousarray(np.asarray(b).reshape(-1))


def inv_o_kernel(k):
    """flax DenseGeneral kernel [H, D, hidden] → torch [hidden, H*D]."""
    a = np.asarray(k)
    return np.ascontiguousarray(a.reshape(-1, a.shape[-1]).T)


def split_fused_qkv_headwise(w, heads, head_dim, bias=None):
    """Split a head-interleaved fused QKV (neox/bloom layout: output rows
    arranged [H, 3, D]) into per-projection flax kernels.

    Returns dict with q/k/v kernels [in, H, D] (+ biases [H, D])."""
    wn = _np(w).reshape(heads, 3, head_dim, -1)       # [H, 3, D, in]
    out = {}
    for j, name in enumerate(("q_proj", "k_proj", "v_proj")):
        out[f"attn/{name}/kernel"] = np.ascontiguousarray(
            wn[:, j].transpose(2, 0, 1))              # [in, H, D]
        if bias is not None:
            bn = _np(bias).reshape(heads, 3, head_dim)
            out[f"attn/{name}/bias"] = np.ascontiguousarray(bn[:, j])
    return out


def split_fused_qkv_columns(w_in_out, heads, head_dim, bias=None):
    """Split a column-fused QKV already in [in, 3*H*D] layout (GPT2 Conv1D)
    into per-projection flax kernels [in, H, D]."""
    h = heads * head_dim
    wn = np.asarray(w_in_out)
    out = {}
    for j, name in enumerate(("q_proj", "k_proj", "v_proj")):
        out[f"attn/{name}/kernel"] = np.ascontiguousarray(
            wn[:, j * h:(j + 1) * h].reshape(-1, heads, head_dim))
        if bias is not None:
            bn = np.asarray(bias)
            out[f"attn/{name}/bias"] = np.ascontiguousarray(
                bn[j * h:(j + 1) * h].reshape(heads, head_dim))
    return out


class HFPolicy:
    """Base policy.  Subclasses set ``model_types`` and implement
    ``build_config`` / ``layer_params`` / ``top_params``."""

    model_types = ()

    @classmethod
    def match(cls, hf_config):
        return getattr(hf_config, "model_type", None) in cls.model_types

    # -- architecture ---------------------------------------------------- #
    def build_config(self, hf_config, **overrides) -> TransformerConfig:
        raise NotImplementedError

    def build_model(self, cfg):
        """The flax module the converted weights load into (decoder families
        share ``Transformer``; encoder policies override)."""
        from deepspeed_tpu.models.transformer import Transformer
        return Transformer(cfg)

    # -- weights --------------------------------------------------------- #
    def layer_params(self, sd, i, cfg) -> dict:
        """{relative-path: array} for layer i (keys like
        'attn/q_proj/kernel', 'input_norm/scale', 'mlp/up_proj/bias')."""
        raise NotImplementedError

    def top_params(self, sd, cfg) -> dict:
        """{path: array} for embeddings / final norm / lm head."""
        raise NotImplementedError

    def export_convert(self, flat, cfg) -> dict:
        """Inverse of :meth:`convert`: flat flax params {path: array} →
        HF-named state dict {hf_key: np.ndarray} (reference
        ``save_16bit_model``'s output is consumable by HF loaders).
        Policies implement this per family."""
        raise NotImplementedError(
            f"{type(self).__name__} has no HF export mapping yet; call "
            "save_16bit_model without hf_policy for flax-named keys")

    def convert(self, sd, cfg):
        """Full flat param dict {path: np.ndarray}: scanned layers stack on a
        leading layer axis ('layers/...'); with ``scan_layers=False`` each
        layer keeps its own 'layers_{i}/...' paths."""
        flat = dict(self.top_params(sd, cfg))
        per_layer = [self.layer_params(sd, i, cfg)
                     for i in range(cfg.num_layers)]
        if not getattr(cfg, "scan_layers", True):
            for i, lp in enumerate(per_layer):
                for key, val in lp.items():
                    flat[f"layers_{i}/{key}"] = val
            return flat
        keys = set(per_layer[0].keys())
        for i, lp in enumerate(per_layer):
            if set(lp.keys()) != keys:
                raise ValueError(f"layer {i} parameter set differs: "
                                 f"{set(lp.keys()) ^ keys}")
        for key in keys:
            flat[f"layers/{key}"] = np.stack([lp[key] for lp in per_layer])
        return flat

    # -- shared pieces --------------------------------------------------- #
    @staticmethod
    def norm(sd, prefix, out_name, rms=False):
        out = {f"{out_name}/scale": _np(sd[f"{prefix}.weight"])}
        if not rms and f"{prefix}.bias" in sd:
            out[f"{out_name}/bias"] = _np(sd[f"{prefix}.bias"])
        return out

    @staticmethod
    def attn_separate(sd, prefix, cfg, src_names=None, out_name="out_proj"):
        """Separate q/k/v/out projections.  ``src_names`` maps our
        q_proj/k_proj/v_proj onto the HF names (default: same names)."""
        H, KVH, D = cfg.num_heads, cfg.kv_heads, cfg.head_dim
        src_names = src_names or {}
        out = {}
        for std, heads in (("q_proj", H), ("k_proj", KVH), ("v_proj", KVH)):
            src = src_names.get(std, std)
            out[f"attn/{std}/kernel"] = \
                qkv_kernel(sd[f"{prefix}.{src}.weight"], heads, D)
            if f"{prefix}.{src}.bias" in sd:
                out[f"attn/{std}/bias"] = \
                    qkv_bias(sd[f"{prefix}.{src}.bias"], heads, D)
        out["attn/o_proj/kernel"] = o_kernel(sd[f"{prefix}.{out_name}.weight"],
                                             H, D)
        if f"{prefix}.{out_name}.bias" in sd:
            out["attn/o_proj/bias"] = _np(sd[f"{prefix}.{out_name}.bias"])
        return out
