"""Serving SLO primitives — typed request lifecycle, admission
backpressure, and the dispatch circuit breaker (``docs/serving.md``,
"Robustness & SLOs").

A production scheduler must be able to REFUSE and RETIRE work, not just
admit it (Orca's iteration-level scheduling assumes exactly this): every
request ends in one of four typed terminal statuses, the queue is
bounded, and a sick device trips a breaker instead of being hammered
with doomed dispatches.  Everything here is host bookkeeping — SLO state
never touches a compiled program (the one-decode-executable invariant).
"""

import queue
import time
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


class RequestStatus:
    """Request lifecycle states.  Terminal outcomes (the typed status a
    client sees): ``COMPLETED`` | ``SHED_DEADLINE`` | ``CANCELLED`` |
    ``ABORTED``.  ``PREEMPTED`` marks a request snapshotted for resume on
    a graceful drain — not terminal: a restarted server finishes it."""
    QUEUED = "QUEUED"
    PREFILLING = "PREFILLING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    SHED_DEADLINE = "SHED_DEADLINE"
    CANCELLED = "CANCELLED"
    ABORTED = "ABORTED"
    PREEMPTED = "PREEMPTED"


TERMINAL_STATUSES = frozenset({
    RequestStatus.COMPLETED, RequestStatus.SHED_DEADLINE,
    RequestStatus.CANCELLED, RequestStatus.ABORTED,
})


@dataclass
class RequestResult:
    """Terminal record for one request (``ServingEngine.result(rid)``).

    ``output`` follows the ``generate()`` contract ``[prompt...,
    generated...]`` for ``COMPLETED`` requests and is ``None`` for every
    other terminal status; ``detail`` carries the human-readable reason
    (which deadline, which dispatch failure, ...).  ``ttft_s`` is
    submit-to-first-token wall time (``None`` when the request never
    produced a token).

    With ``serving.tracing`` on, the latency breakdown fields are
    populated from the request's span tree (``docs/observability.md``):
    ``queue_s`` (submit → admission start), ``prefill_s`` (admission
    start → admit dispatched), ``host_s`` (admit dispatched → first
    token PROCESSED — the lag-one event latency plus host bookkeeping),
    ``decode_s`` (first token → terminal) and ``latency_s`` (submit →
    terminal).  By construction ``queue_s + prefill_s + host_s +
    decode_s == latency_s``.  ``None`` with tracing off (seed
    behavior), and any phase the request never reached stays ``None``
    (a shed-while-queued request has only ``queue_s``/``latency_s``)."""
    rid: int
    status: str
    output: Optional[np.ndarray] = None
    detail: str = ""
    client_id: Any = None
    submitted_it: int = 0
    finished_it: Optional[int] = None
    ttft_s: Optional[float] = None
    queue_s: Optional[float] = None
    prefill_s: Optional[float] = None
    decode_s: Optional[float] = None
    host_s: Optional[float] = None
    latency_s: Optional[float] = None


class QueueFull(RuntimeError):
    """``submit()`` backpressure: the bounded queue is at
    ``max_queue_depth`` and the policy is ``reject`` (or ``block`` could
    not make progress)."""


class CircuitOpen(RuntimeError):
    """``submit()`` refused because the dispatch circuit breaker is open
    — the device failed ``breaker_threshold`` consecutive dispatches and
    admission is suspended until the cooldown's half-open probe
    succeeds."""


class DrainTimeout(RuntimeError):
    """``drain()`` exceeded ``drain_timeout_s`` without retiring the
    remaining work; the message carries per-slot diagnostics (slot id,
    request id, last dispatch age)."""


class TokenStream:
    """Thread-safe subscription to one request's per-token event stream
    (``ServingEngine.token_events(rid)``).

    The engine pushes events from the host-mirror drain point — one
    event behind the device, flushed when a ``decode_block``'s tokens
    are processed — so TTFT and time-between-tokens are observable per
    request without ever synchronizing the dispatch path.  Events are
    plain dicts:

    - ``{"event": "token", "rid": r, "index": i, "token": t}`` — the
      ``i``-th generated token (indices start at 0 with the admission
      first-token; a resumed request replays its prior-incarnation
      tokens first, so the stream is always the FULL generated
      sequence).
    - ``{"event": "end", "rid": r, "status": s, "detail": d}`` — the
      typed terminal event, exactly once, last: ``COMPLETED`` |
      ``SHED_DEADLINE`` | ``CANCELLED`` | ``ABORTED`` | ``PREEMPTED``
      (preempted streams resume on a restarted server).

    Subscribing mid-flight replays everything already generated, so the
    stream is lossless regardless of when the consumer attaches.  The
    producer side (``push``) runs under the engine lock in the
    scheduler-owner thread; consumers (``get``/``events``/``tokens``)
    may live on any thread.  ``on_event`` (optional) is invoked
    synchronously from the producer for every event — the HTTP
    transport uses it to bridge into an asyncio loop via
    ``call_soon_threadsafe``; it must never block."""

    def __init__(self, rid, on_event=None, on_drop=None):
        self.rid = rid
        self._q = queue.SimpleQueue()
        self._on_event = on_event
        self._on_drop = on_drop          # engine hook: count the drop

    def push(self, event):
        """Producer side (the serving engine, under its lock).  A dead
        consumer must never break the producer: ``on_event`` raising
        (e.g. ``call_soon_threadsafe`` into an asyncio loop that closed
        mid-shutdown) drops the bridge — the queue keeps filling for
        in-process readers, and ``close()``/``step()`` running this
        under the engine lock survive.  The drop is never silent: a
        ``warning_once`` names the rid and exception class (shutdown
        races stay diagnosable) and ``on_drop`` lets the engine count
        it in ``stats["stream_bridge_drops"]``."""
        self._q.put(event)
        cb = self._on_event
        if cb is not None:
            try:
                cb(event)
            except Exception as e:       # noqa: BLE001
                self._on_event = None
                # once per STREAM structurally (the bridge is nulled
                # right here) — warning_once's process-global seen-set
                # would retain one interned per-rid string forever on a
                # long-lived server, for no extra dedup
                logger.warning(
                    f"serving: token-event subscriber bridge for "
                    f"request {self.rid} dropped on "
                    f"{type(e).__name__}: {e} — stream queue stays "
                    f"readable; counted in stats['stream_bridge_drops']")
                if self._on_drop is not None:
                    try:
                        self._on_drop(self.rid, e)
                    except Exception:    # noqa: BLE001 — never re-raise
                        logger.warning("serving: stream-drop accounting "
                                       "hook failed; drop uncounted")

    def get(self, timeout=None):
        """The next event (blocking up to ``timeout`` seconds; raises
        :class:`queue.Empty` on expiry)."""
        return self._q.get(timeout=timeout)

    def events(self, timeout=None):
        """Yield events until — and including — the terminal ``end``
        event.  ``timeout`` bounds EACH wait, not the whole stream."""
        while True:
            ev = self._q.get(timeout=timeout)
            yield ev
            if ev.get("event") == "end":
                return

    def tokens(self, timeout=None):
        """Drain the stream to its end; returns ``(token_ids,
        end_event)`` — the convenience form the streaming-equivalence
        tests assert bitwise against the final ``RequestResult``."""
        toks, end = [], None
        for ev in self.events(timeout=timeout):
            if ev.get("event") == "token":
                toks.append(int(ev["token"]))
            else:
                end = ev
        return toks, end


class CircuitBreaker:
    """Consecutive-dispatch-failure breaker for the serving engine.

    ``threshold <= 0`` disables it entirely (seed behavior: dispatch
    failures propagate to the caller).  When enabled, every failed
    decode/admit/prefill dispatch is absorbed and counted; ``threshold``
    consecutive failures OPEN the breaker — new work is rejected with a
    reason (:class:`CircuitOpen`) and no dispatches run until
    ``cooldown_s`` elapses, when ONE half-open probe dispatch is allowed
    through: success closes the breaker, failure re-opens it (and
    re-arms the cooldown)."""

    def __init__(self, threshold, cooldown_s):
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.consecutive_failures = 0
        self.trips = 0
        self._opened_at = None           # monotonic; None = closed
        self.last_error = ""

    @property
    def enabled(self):
        return self.threshold > 0

    @property
    def open(self):
        return self._opened_at is not None

    def allow_dispatch(self):
        """True when dispatching is permitted: closed, or half-open (the
        cooldown elapsed — the next dispatch is the probe)."""
        if self._opened_at is None:
            return True
        return time.monotonic() - self._opened_at >= self.cooldown_s

    def seconds_until_half_open(self):
        if self._opened_at is None:
            return 0.0
        return max(0.0,
                   self.cooldown_s - (time.monotonic() - self._opened_at))

    def record_success(self):
        self.consecutive_failures = 0
        self._opened_at = None

    def record_failure(self, exc):
        self.consecutive_failures += 1
        self.last_error = f"{type(exc).__name__}: {exc}"
        if self._opened_at is not None:
            # a failed half-open probe: re-open and re-arm the cooldown
            self._opened_at = time.monotonic()
            self.trips += 1
        elif self.consecutive_failures >= self.threshold:
            self._opened_at = time.monotonic()
            self.trips += 1

    def check_submit(self):
        """Raise :class:`CircuitOpen` (reject-with-reason) while open.
        Once the cooldown has elapsed (half-open) submissions are
        admitted again — the next dispatch is the probe.  Without this,
        a breaker that opened with an EMPTY queue would lock the server
        out of ``submit()`` forever: the probe needs work, and work
        could never arrive."""
        if not self.enabled or self._opened_at is None:
            return
        if self.allow_dispatch():
            return
        raise CircuitOpen(
            f"serving circuit breaker OPEN after "
            f"{self.consecutive_failures} consecutive dispatch failures "
            f"(last: {self.last_error}); half-open probe in "
            f"{self.seconds_until_half_open():.1f}s")


__all__ = ["RequestStatus", "TERMINAL_STATUSES", "RequestResult",
           "QueueFull", "CircuitOpen", "DrainTimeout", "CircuitBreaker",
           "TokenStream"]
