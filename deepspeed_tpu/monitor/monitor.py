"""Experiment monitoring — parity with reference ``deepspeed/monitor/``:
``Monitor`` ABC (``monitor.py:13``), ``MonitorMaster`` fan-out
(``monitor.py:29``) over TensorBoard / WandB / CSV backends.

Events are ``(name, value, global_step)`` tuples via ``write_events``,
exactly the reference protocol, so engine-side call sites port 1:1.

Lifecycle: every backend supports ``flush()`` (push buffered events to
durable storage) and ``close()`` (flush + release handles), and the ABC
is a context manager — short-lived serving processes wrap the monitor
in ``with`` so tail events are never dropped on exit.  The CSV backend
keeps its per-series file handles OPEN between ``write_events`` calls
(no per-event open/close syscalls) and flushes each batch by default;
``csvMonitor(cfg, batch_flush=False)`` opts into full buffering, where
an explicit flush/close (or the context manager) is REQUIRED or a
process exiting right after its last write loses the buffered tail.
The serving engine calls ``monitor.flush()`` on
``close()``/``preempt()``."""

import os
import csv as _csv
from abc import ABC, abstractmethod

from deepspeed_tpu.utils.logging import logger


class Monitor(ABC):

    def __init__(self, monitor_config):
        self.monitor_config = monitor_config

    @abstractmethod
    def write_events(self, event_list):
        ...

    def flush(self):
        """Push buffered events to durable storage (default: no-op for
        backends that write through)."""

    def close(self):
        """Flush and release any handles; idempotent."""
        self.flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class TensorBoardMonitor(Monitor):

    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.enabled = tensorboard_config.enabled
        self.summary_writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter
                log_dir = os.path.join(tensorboard_config.output_path or "./runs",
                                       tensorboard_config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except ImportError:
                logger.warning("tensorboard not available; TensorBoardMonitor disabled")
                self.enabled = False

    def write_events(self, event_list, flush=True):
        if not self.enabled or self.summary_writer is None:
            return
        for event in event_list:
            self.summary_writer.add_scalar(*event)
        if flush:
            self.summary_writer.flush()

    def flush(self):
        if self.summary_writer is not None:
            self.summary_writer.flush()

    def close(self):
        if self.summary_writer is not None:
            self.summary_writer.close()
            self.summary_writer = None
            self.enabled = False


class WandbMonitor(Monitor):

    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        self.enabled = wandb_config.enabled
        if self.enabled:
            try:
                import wandb
                self.wandb = wandb
                wandb.init(project=wandb_config.project, group=wandb_config.group,
                           entity=wandb_config.team)
            except ImportError:
                logger.warning("wandb not available; WandbMonitor disabled")
                self.enabled = False

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self.wandb.log({name: value}, step=int(step))

    def close(self):
        if self.enabled:
            self.wandb.finish()
            self.enabled = False


class csvMonitor(Monitor):
    """CSV backend: one ``<series>.csv`` per event name.  File handles
    stay OPEN across ``write_events`` calls (the per-event open/append/
    close of the old implementation cost a syscall triplet per sample on
    the serving metrics path); each ``write_events`` batch ends with a
    flush of the files it touched, so durability stays per-batch like
    the old implementation — callers that never ``flush()``/``close()``
    (the training engine, the fault supervisor) keep their rows on
    disk.  ``batch_flush=False`` opts into full buffering for
    high-frequency writers that DO flush/close (or use the monitor as
    a context manager)."""

    def __init__(self, csv_config, batch_flush=True):
        super().__init__(csv_config)
        self.enabled = csv_config.enabled
        self.batch_flush = batch_flush
        self.output_path = csv_config.output_path or "./csv_monitor"
        self.job_name = csv_config.job_name
        self.filehandles = {}            # path -> (file, csv.writer)
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def _entry(self, name):
        safe = name.replace("/", "_")
        path = os.path.join(self.output_path, self.job_name, f"{safe}.csv")
        entry = self.filehandles.get(path)
        if entry is None:
            new = not os.path.exists(path)
            f = open(path, "a", newline="")
            w = _csv.writer(f)
            if new:
                w.writerow(["step", safe])
            entry = self.filehandles[path] = (f, w)
        return entry

    def write_events(self, event_list):
        if not self.enabled:
            return
        touched = []
        for name, value, step in event_list:
            f, w = self._entry(name)
            w.writerow([int(step), float(value)])
            touched.append(f)
        if self.batch_flush:
            for f in touched:
                f.flush()

    def flush(self):
        for f, _ in self.filehandles.values():
            f.flush()

    def close(self):
        for f, _ in self.filehandles.values():
            try:
                f.close()
            except OSError:
                pass
        self.filehandles.clear()


class MonitorMaster(Monitor):
    """Fan events out to all enabled backends; only JAX process 0 writes
    (reference gates on rank 0, ``monitor.py:29``).  ``flush``/``close``
    fan out too, and the master composes as a context manager."""

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        import jax
        self.enabled = monitor_config.enabled
        self.backends = []
        if jax.process_index() == 0:
            if monitor_config.tensorboard.enabled:
                self.backends.append(TensorBoardMonitor(monitor_config.tensorboard))
            if monitor_config.wandb.enabled:
                self.backends.append(WandbMonitor(monitor_config.wandb))
            if monitor_config.csv_monitor.enabled:
                self.backends.append(csvMonitor(monitor_config.csv_monitor))

    def write_events(self, event_list):
        for backend in self.backends:
            backend.write_events(event_list)

    def flush(self):
        for backend in self.backends:
            backend.flush()

    def close(self):
        for backend in self.backends:
            backend.close()
