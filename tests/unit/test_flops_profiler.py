"""Per-module flops/latency tree (reference ``flops_profiler/profiler.py:239``
``print_model_profile`` / ``:375`` aggregated profile)."""

import numpy as np

import jax

from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
from deepspeed_tpu.profiling.flops_profiler.profiler import (
    ModuleProfile, _scope_to_path, aggregate_by_depth, format_profile_tree,
    model_profile_tree)


def tiny_model():
    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=32, dtype="float32",
                            use_flash_attention=False, remat=False,
                            scan_layers=False)
    return Transformer(cfg)


def tiny_batch():
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(0, 64, (2, 16)).astype(np.int32)}


def test_scope_to_path_strips_transform_and_method_frames():
    assert _scope_to_path(
        "jit(f)/Transformer/Transformer.hidden_states/layers_0/attn/"
        "dot_general") == ("layers_0", "attn", "dot_general")
    assert _scope_to_path(
        "jit(f)/Transformer/layers_1/attn/bhst,bthd->bshd/transpose") == \
        ("layers_1", "attn", "transpose")
    assert _scope_to_path("reduce_sum") == ()


def test_model_profile_tree_structure_params_flops():
    model = tiny_model()
    root, _ = model_profile_tree(model, jax.random.key(0), tiny_batch())
    # module tree mirrors the flax structure
    assert set(root.children) >= {"embed_tokens", "layers_0", "layers_1",
                                  "final_norm", "lm_head"}
    blk = root.children["layers_0"]
    assert set(blk.children) >= {"input_norm", "attn", "mlp"}
    # subtree-aggregated params: root = model total, block > its norms
    total = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(
        model.init(jax.random.key(0), tiny_batch())))
    assert root.params == total
    assert blk.params > blk.children["input_norm"].params
    # flops: attention + mlp dominate the block (CPU path uses flax's
    # per-module cost analysis)
    assert root.flops > 0
    assert blk.flops >= blk.children["attn"].flops > 0
    assert blk.children["mlp"].flops > 0


def test_format_and_aggregate_render():
    model = tiny_model()
    root, total_ps = model_profile_tree(model, jax.random.key(0),
                                        tiny_batch())
    txt = format_profile_tree(root, total_ps, depth=2)
    assert "Transformer(" in txt and "(layers_0): Block(" in txt
    assert "% Params" in txt and "MACs" in txt
    agg = aggregate_by_depth(root, max_depth=1)
    assert "depth 0:" in agg and "depth 1:" in agg


def test_engine_prints_profile_tree(tmp_path):
    import deepspeed_tpu
    report_file = tmp_path / "profile.txt"
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_model(),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "flops_profiler": {"enabled": True, "profile_step": 1,
                                   "output_file": str(report_file)}})
    rng = np.random.default_rng(0)
    b = {"input_ids": rng.integers(0, 64, (8, 16)).astype(np.int32)}
    for _ in range(2):
        loss = engine(b)
        engine.backward(loss)
        engine.step()
    out = report_file.read_text()
    assert "DeepSpeed Flops Profiler" in out
    assert "(layers_0): Block(" in out
    assert "Detailed Profile per GPU" in out


def test_module_profile_walk_depths():
    root = ModuleProfile("", "M")
    root.child("a").child("b")
    depths = {node.name: d for d, node in root.walk()}
    assert depths == {"": 0, "a": 1, "b": 2}
