"""Decoder-only transformer — the framework's flagship model family.

This is the TPU-native counterpart of the model surface the reference serves
through kernel injection (``module_inject/containers/{opt,llama,gptneox,...}``
+ ``model_implementations/transformers/ds_transformer.py:19``): one
configurable decoder covering the OPT/GPT/Llama architecture space, written
flax-first so that:

* attention routes through the Pallas flash-attention kernel on TPU
  (``ops/transformer/flash_attention.py``) with a jnp fallback for CPU tests;
* parameter names match the AutoTP sharding rules
  (``runtime/zero/partition.py DEFAULT_TP_RULES``) so tensor parallelism is
  a config flag, not a model rewrite;
* sequence-parallel sharding constraints are applied at block boundaries
  when an ``sp`` mesh axis is live;
* the whole stack is scan-over-layers for O(1) compile time at depth, with
  ``jax.checkpoint`` policies from the activation-checkpointing config.
"""

import os

from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.utils.logging import logger


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None       # GQA; None → MHA
    ffn_hidden_size: Optional[int] = None    # None → 4*hidden
    max_seq_len: int = 2048
    activation: str = "relu"                 # relu (OPT) | gelu tanh (GPT2) | gelu_exact (neox) | silu (llama gated)
    gated_mlp: bool = False                  # llama-style SwiGLU
    position_embedding: str = "learned"      # learned (OPT/GPT) | rope (llama/neox) | alibi (bloom)
    rope_theta: float = 10000.0
    rope_dim: Optional[int] = None           # partial rotary (neox rotary_pct / gptj rotary_dim)
    rope_interleaved: bool = False           # gptj rotate-every-two layout
    layernorm_epsilon: float = 1e-5
    rms_norm: bool = False                   # llama
    parallel_residual: bool = False          # x + attn(ln(x)) + mlp(ln'(x)) (neox/gptj)
    shared_attn_mlp_norm: bool = False       # gptj: one ln feeds both branches
    embedding_norm: bool = False             # bloom word_embeddings_layernorm
    attention_bias: Optional[bool] = None    # None → not rms_norm
    attention_out_bias: Optional[bool] = None  # gpt-neo: o_proj biased, qkv not
    mlp_bias: Optional[bool] = None          # None → not rms_norm
    # gpt-neo: per-layer "global"/"local" pattern + band width; local layers
    # attend to the trailing `window_size` positions only.  Requires
    # scan_layers=False (layers are no longer homogeneous).
    attention_layers: Optional[tuple] = None
    window_size: int = 256
    # None → 1/sqrt(head_dim); gpt-neo uses 1.0 (unscaled logits)
    attention_softmax_scale: Optional[float] = None
    # MoE trunk (reference Megatron-DeepSpeed MoE-GPT layout): every
    # `moe_every`-th block swaps its MLP for a `moe/layer.py` MoE with
    # `moe_num_experts` experts sharded over the `ep` mesh axis.  0 = dense.
    moe_num_experts: int = 0
    moe_every: int = 2
    # index of the FIRST MoE layer; -1 → `moe_every - 1` (the Megatron
    # default, where MoE layers sit at every-1, 2*every-1, ...).  Lets
    # checkpoints whose pattern starts elsewhere (e.g. layers 0,2,4 with
    # interval 2) map without remapping layer indices.
    moe_layer_offset: int = -1
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_eval_capacity_factor: float = 1.0
    moe_ep_size: int = 1
    moe_aux_coef: float = 0.01
    # Megatron-style MoE experts carry per-expert biases (dense_h_to_4h.bias
    # / dense_4h_to_h.bias) — needed for exact checkpoint parity
    moe_expert_bias: bool = False
    lm_head_bias: bool = False               # gptj
    # opt-350m: embeddings live in a smaller space with project_in /
    # project_out linears around the trunk (HF word_embed_proj_dim)
    embed_proj_dim: Optional[int] = None
    # opt-350m is the post-LN OPT: norms AFTER the residual adds, and no
    # final norm (HF do_layer_norm_before=False)
    pre_layer_norm: bool = True
    dropout: float = 0.0
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    use_flash_attention: bool = True
    fused_qkv: bool = False                  # single fused QKV gemm (MHA only)
    # >1: sequence-chunked cross-entropy — the [B,S,V] logits tensor never
    # materializes (per-chunk head matmul + CE under jax.checkpoint); cuts
    # the loss section's HBM traffic at large vocabularies
    loss_seq_chunks: int = 0
    sparse_attention: Optional[object] = None  # SparsityConfig → block-sparse
    # int8 KV cache (beyond the reference's fp16 cache): payload int8 +
    # per-(position, kv-head) scales; decode is HBM-bound on the KV stream
    # at large batch, so halving its bytes buys real decode throughput
    kv_cache_quant: bool = False
    # run the decode kernel's score/PV matmuls int8×int8 on the MXU
    # (requires kv_cache_quant): removes the in-kernel int8→bf16 slab
    # casts at the cost of additionally quantizing q and the probability
    # rows (~0.5% extra attention error).  Measured NEUTRAL-to-slower on
    # v5e at OPT-1.3B shapes (the quantize work offsets the cast
    # savings) — opt-in for shapes where the KV stream dominates harder
    decode_int8_matmuls: bool = False
    # "ulysses" | "ring" routes training attention through explicit
    # sequence-parallel collectives over the live sp mesh axis; None leaves
    # seq sharding to GSPMD constraint propagation
    sequence_parallel_impl: Optional[str] = None
    remat: bool = True
    remat_policy: str = "nothing_saveable"
    scan_layers: bool = True

    def __post_init__(self):
        if self.moe_num_experts > 0 and self.scan_layers:
            raise ValueError("MoE trunk requires scan_layers=False (mixed "
                             "dense/MoE blocks are heterogeneous; expert "
                             "params shard over ep, not a layer axis)")
        if self.moe_num_experts > 0:
            if self.moe_layer_offset < -1:
                raise ValueError(
                    f"moe_layer_offset={self.moe_layer_offset}: only -1 "
                    f"(the moe_every-1 default) or a layer index >= 0 is "
                    f"meaningful")
            off = resolve_moe_offset(self)
            if off >= self.num_layers:
                raise ValueError(
                    f"first MoE layer {off} (moe_layer_offset/moe_every-1) "
                    f"is past num_layers={self.num_layers} — the model "
                    f"would silently build all-dense despite "
                    f"moe_num_experts={self.moe_num_experts}")
        if self.decode_int8_matmuls and not self.kv_cache_quant:
            raise ValueError("decode_int8_matmuls requires "
                             "kv_cache_quant=True (the MXU path consumes "
                             "int8 KV payloads)")
        if self.attention_layers is not None:
            if len(self.attention_layers) != self.num_layers:
                raise ValueError(
                    f"attention_layers has {len(self.attention_layers)} "
                    f"entries for {self.num_layers} layers")
            if self.scan_layers:
                raise ValueError("attention_layers (per-layer local/global "
                                 "patterns) requires scan_layers=False")
        if self.fused_qkv and self.kv_heads != self.num_heads:
            logger.warning(
                "fused_qkv requested but num_kv_heads != num_heads (GQA) — "
                "falling back to separate q/k/v projections; the param tree "
                "will carry q_proj/k_proj/v_proj, not qkv_proj")

    @property
    def kv_heads(self):
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def ffn_size(self):
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def attn_bias_enabled(self):
        return self.attention_bias if self.attention_bias is not None \
            else not self.rms_norm

    @property
    def attn_out_bias_enabled(self):
        return self.attention_out_bias if self.attention_out_bias is not None \
            else self.attn_bias_enabled

    def window_for_layer(self, layer_idx):
        """Band width for this layer, or None for full (global) attention."""
        if self.attention_layers is None or layer_idx is None:
            return None
        return self.window_size \
            if self.attention_layers[layer_idx] == "local" else None

    @property
    def mlp_bias_enabled(self):
        return self.mlp_bias if self.mlp_bias is not None else not self.rms_norm

    @property
    def jnp_dtype(self):
        return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                "float16": jnp.float16}[self.dtype]

    def num_params(self):
        """Analytic parameter count (embeddings + blocks + final norm)."""
        h, v, l = self.hidden_size, self.vocab_size, self.num_layers
        f = self.ffn_size
        kvh = self.kv_heads * self.head_dim
        attn = h * h + h * kvh * 2 + h * h  # q, k, v, o kernels
        mlp = h * f * (3 if self.gated_mlp else 2)
        norm_size = h if self.rms_norm else 2 * h
        norms_per_layer = 1 if (self.parallel_residual
                                and self.shared_attn_mlp_norm) else 2
        per_layer = attn + mlp + norms_per_layer * norm_size
        emb = v * h + (self.max_seq_len * h
                       if self.position_embedding == "learned" else 0)
        head = 0 if self.tie_word_embeddings else v * h
        return emb + l * per_layer + norm_size + head


def resolve_remat_policy(name):
    """Map a policy name to a jax.checkpoint policy.

    Beyond the stock ``jax.checkpoint_policies`` names, ``dots_and_attn_saveable``
    saves weight-stationary dot outputs AND the flash-attention residuals
    (tagged ``flash_out``/``flash_lse`` in the kernel's vjp) — the backward
    pass then reuses the O(S) attention residuals instead of re-running the
    forward kernel, the right default trade on HBM-rich chips."""
    if name in ("dots_and_attn_saveable", "attn_residuals_saveable"):
        cp = jax.checkpoint_policies
        return cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable,
            cp.save_only_these_names("flash_out", "flash_lse"))
    if name == "flash_only_saveable":
        # long-context middle ground: save ONLY the flash-attention
        # residuals (out + lse, O(S) per layer) so the backward never
        # re-runs the attention kernel, while every projection/MLP dot
        # (O(S·M) each — the HBM hogs at long seq) is rematerialized
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse")
    return getattr(jax.checkpoint_policies, name, None)


def _norm(config, name):
    if config.rms_norm:
        return nn.RMSNorm(epsilon=config.layernorm_epsilon, name=name,
                          param_dtype=jnp.float32)
    return nn.LayerNorm(epsilon=config.layernorm_epsilon, name=name,
                        param_dtype=jnp.float32)


def _rope(q, k, positions, head_dim, theta, rope_dim=None, interleaved=False):
    """Rotary position embeddings.  Default: neox/llama half-split layout;
    ``interleaved`` selects the gptj rotate-every-two layout; ``rope_dim``
    rotates only the first ``rope_dim`` features (neox ``rotary_pct`` /
    gptj ``rotary_dim``)."""
    d = rope_dim or head_dim
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]

    def rot(x):
        rx, pass_through = x[..., :d], x[..., d:]
        if interleaved:
            x1, x2 = rx[..., 0::2], rx[..., 1::2]
            r1 = x1 * cos - x2 * sin
            r2 = x2 * cos + x1 * sin
            out = jnp.stack([r1, r2], axis=-1).reshape(rx.shape)
        else:
            x1, x2 = rx[..., :half], rx[..., half:]
            out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                                  axis=-1)
        if pass_through.shape[-1]:
            out = jnp.concatenate([out, pass_through], axis=-1)
        return out.astype(x.dtype)

    return rot(q), rot(k)


def alibi_slopes(n_heads):
    """ALiBi per-head slopes (bloom layout)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if np.log2(n_heads).is_integer():
        slopes = pow2_slopes(n_heads)
    else:
        p = 2 ** int(np.floor(np.log2(n_heads)))
        slopes = pow2_slopes(p) + pow2_slopes(2 * p)[0::2][: n_heads - p]
    return jnp.asarray(slopes, dtype=jnp.float32)


def alibi_bias(n_heads, kv_len):
    """[H, T] key-positional ALiBi bias.  The relative form
    ``slope·(t - s)`` differs from this per query row only by a constant,
    which softmax cancels — so the key-absolute form is exact for causal
    attention (what bloom itself implements)."""
    return alibi_slopes(n_heads)[:, None] * jnp.arange(kv_len)[None, :]


def reference_attention(q, k, v, causal=True, mask=None, bias=None,
                        window=None):
    """jnp attention used as the CPU fallback and the golden reference for
    the Pallas kernel tests.  q,k,v: [B, S, H, D] / [B, S, KVH, D];
    ``bias``: optional [H, T] additive logit bias (ALiBi); ``window``:
    optional band width (gpt-neo local attention — attend to the trailing
    ``window`` positions only)."""
    B, S, H, D = q.shape
    KVH = k.shape[2]
    if KVH != H:
        rep = H // KVH
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias[None, :, None, :].astype(jnp.float32)
    if causal:
        rows = jnp.arange(S)[:, None]
        cols = jnp.arange(k.shape[1])[None, :]
        causal_mask = cols <= rows
        if window is not None:
            causal_mask = causal_mask & (cols > rows - window)
        logits = jnp.where(causal_mask[None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :].astype(bool), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def _prefill_attention(q, k, v, config, window=None):
    """Causal self-attention for a from-zero generation prefill: ONLY the
    flash kernel or the dense causal reference — never ``_attention``'s
    sequence-parallel shard_map or block-sparse branches.  Generation
    inputs are unsharded (an sp>1 topology would shard_map over them and
    crash or mis-attend), and decode attends dense over the same cache,
    so a sparse prefill would silently diverge from its own decode."""
    if window is None and config.use_flash_attention and q.shape[1] > 1:
        from deepspeed_tpu.ops.transformer.flash_attention import (
            flash_attention, pallas_supported)
        if pallas_supported():
            return flash_attention(q, k, v, causal=True)
    return reference_attention(q, k, v, causal=True, window=window)


def _attention(q, k, v, config, mask=None, bias=None, window=None):
    if window is not None:
        # banded local attention (gpt-neo): dense path with a band mask —
        # the flash/sparse kernels are bypassed (HF computes it dense too)
        return reference_attention(q, k, v, causal=True, mask=mask, bias=bias,
                                   window=window)
    if config.sparse_attention is not None and q.shape[1] > 1 and bias is None:
        from deepspeed_tpu.ops.sparse_attention.block_sparse import (
            block_sparse_attention, cached_layout)
        sc = config.sparse_attention
        if mask is not None and mask.ndim != 2:
            logger.warning(
                "sparse_attention only folds 2-D key-padding masks; got a "
                f"{mask.ndim}-D mask — falling back to dense attention")
        else:
            layout = cached_layout(sc, q.shape[1], causal=True)
            if k.shape[2] != q.shape[2]:  # GQA: expand kv heads for the kernel
                k = jnp.repeat(k, q.shape[2] // k.shape[2], axis=2)
                v = jnp.repeat(v, q.shape[2] // v.shape[2], axis=2)
            return block_sparse_attention(q, k, v, layout, sc.block,
                                          causal=True, key_padding_mask=mask)
    if config.sequence_parallel_impl and q.shape[1] > 1 and mask is None \
            and bias is None:
        from deepspeed_tpu.parallel.topology import get_topology
        topo = get_topology()
        if topo is not None and topo.get_sequence_parallel_world_size() > 1:
            from deepspeed_tpu.parallel.sequence import shard_map_attention
            if k.shape[2] != q.shape[2]:  # GQA: expand for the sp kernels
                k = jnp.repeat(k, q.shape[2] // k.shape[2], axis=2)
                v = jnp.repeat(v, q.shape[2] // v.shape[2], axis=2)
            batch_axes = tuple(a for a in topo.get_data_parallel_axes()
                               if topo.mesh.shape[a] > 1) or None
            head_axes = "tp" if topo.mesh.shape.get("tp", 1) > 1 else None
            fn = shard_map_attention(topo.mesh,
                                     impl=config.sequence_parallel_impl,
                                     axis="sp", causal=True,
                                     batch_axes=batch_axes,
                                     head_axes=head_axes)
            return fn(q, k, v)
    if config.use_flash_attention and q.shape[1] > 1 and mask is None \
            and bias is None:
        from deepspeed_tpu.ops.transformer.flash_attention import (
            flash_attention, pallas_supported)
        if pallas_supported():
            return flash_attention(q, k, v, causal=True)
    return reference_attention(q, k, v, causal=True, mask=mask, bias=bias)


_CACHE_DATA_KEYS = ("k", "v", "k_scale", "v_scale")


def _cache_data(cache):
    """The data arrays of a cache dict (payloads + optional quant scales),
    without the per-layer/per-row bookkeeping markers."""
    return {kk: cache[kk] for kk in _CACHE_DATA_KEYS if kk in cache}


def _paged_write(cache, k_new, v_new, ks_new, vs_new, positions, per_row):
    """Scatter this step's K/V rows into a PAGED cache pool.

    Pool layout (``init_paged_cache``): ``[L, num_pages, page_size,
    KVH*D]``; ``cache["pages"]`` is the per-row page table ``[B,
    n_pages]`` mapping virtual page index ``pos // page_size`` to a
    physical page.  Each virtual write position resolves to ``(pages[b,
    pos // page], pos % page)`` — one batched scatter per buffer, no
    per-page Python loop, so the program shape is independent of where
    the host placed the pages.  Unmapped virtual pages alias the
    reserved TRASH page 0: retired/free lanes keep scattering masked
    garbage there instead of into reclaimed pages (the paged analog of
    the dense path's "dead lanes write into their own lane" safety
    argument)."""
    li = cache["layer"]
    pages = cache["pages"]                      # [B, n_pages] int32
    page = cache["k"].shape[-2]
    B_, S_ = k_new.shape[0], k_new.shape[1]
    if per_row and S_ == 1:
        pos = positions[:, 0]                   # [B] per-row decode
        pidx = (pos // page).astype(jnp.int32)
        off = (pos % page).astype(jnp.int32)
        phys = pages[jnp.arange(B_), pidx]      # [B]

        def w(buf, new):
            return buf.at[li, phys, off].set(new[:, 0].astype(buf.dtype))
    elif per_row:
        # per-row MULTI-token block (speculative verify): each row writes
        # S_ contiguous positions from ITS OWN start, resolved through
        # its table row in one batched scatter.  Dead lanes' table rows
        # are redirected to the trash page by the caller, so their
        # (possibly lane-overflowing, gather-clamped) virtual positions
        # can only ever land on trash.
        pos = positions                         # [B, S]
        pidx = (pos // page).astype(jnp.int32)
        off = (pos % page).astype(jnp.int32)
        phys = jnp.take_along_axis(pages, pidx, axis=1)      # [B, S]

        def w(buf, new):
            return buf.at[li, phys, off].set(new.astype(buf.dtype))
    else:
        # row-uniform multi-token block (chunked prefill / shared-pos
        # decode): positions start..start+S-1 may span page boundaries
        pos = positions[0, 0] + jnp.arange(S_)  # [S]
        pidx = (pos // page).astype(jnp.int32)
        off = jnp.broadcast_to((pos % page).astype(jnp.int32), (B_, S_))
        phys = pages[:, pidx]                   # [B, S]

        def w(buf, new):
            return buf.at[li, phys, off].set(new.astype(buf.dtype))

    out = {"k": w(cache["k"], k_new), "v": w(cache["v"], v_new)}
    if ks_new is not None:
        out["k_scale"] = w(cache["k_scale"], ks_new)
        out["v_scale"] = w(cache["v_scale"], vs_new)
    return out


def _paged_gather(cache):
    """Materialize THIS layer's virtual [B, n_pages*page_size, ...] view
    of the paged pool via the page table — a transient 1/L the size of
    the monolithic per-layer cache slice the dense paths already
    materialize.  Virtual positions on unmapped (trash) pages carry
    garbage; every attention path masks KV positions beyond each query's
    own position, and the host never maps a live write/read position to
    the trash page, so the garbage is never attended."""
    li, pages = cache["layer"], cache["pages"]
    B, n = pages.shape
    page = cache["k"].shape[-2]

    def g(buf):
        v = buf[li, pages]                      # [B, n, page, F]
        return v.reshape(B, n * page, v.shape[-1])

    out = {"k": g(cache["k"]), "v": g(cache["v"])}
    if "k_scale" in cache:
        out["k_scale"] = g(cache["k_scale"])
        out["v_scale"] = g(cache["v_scale"])
    return out


def cached_attention(q, k_cache, v_cache, q_positions, bias=None,
                     window=None, layer=None, k_scale=None, v_scale=None,
                     int8_matmuls=False):
    """Decode attention against a KV cache.

    q: [B, S, H, D]; caches: [B, S_max, KVH*D] (S-major, heads flattened —
    the decode kernel's full-lane-width DMA layout; the cache write is the
    raw projection output) — or, with ``layer`` given, the FULL
    layer-stacked [L, B, S_max, KVH*D] cache (the Pallas kernel indexes the
    layer itself; no per-layer slice is materialized).  q_positions: [B, S]
    absolute positions.  KV entries at positions > q_pos are masked — this
    covers both causality and the unwritten cache tail.  TPU-native analog
    of the reference ``softmax_context`` KV-cache op
    (``csrc/transformer/inference/csrc/pt_binding.cpp``).

    PER-ROW CONTIGUITY (the ``1 < S <= 512`` Pallas chunk branch): the
    chunk kernel receives only each row's FIRST position
    (``starts = q_positions[:, 0]``) and derives the rest as
    ``starts[b] + iota(S)`` — so when that branch is taken, every row's
    positions must be contiguous and ascending
    (``q_positions[b, i] == q_positions[b, 0] + i``), which is exactly
    what ``prefill_chunked`` / multi-token decode feed it.  Gapped or
    reordered positions would silently diverge from the dense fallback's
    per-position mask (regression-tested against the dense path in
    tests/unit/test_decode_attention.py); such callers must route to the
    dense path (pass a ``bias``/``window``, or S > 512).
    """
    B, S, H, D = q.shape
    S_max, KVH = k_cache.shape[-2], k_cache.shape[-1] // D
    # NOTE: on TPU, f32 matmuls run as multi-pass bf16 on the MXU (jax
    # default precision), so single-token decode and batched prefill round
    # differently — logits agree to ~1e-2, not 1e-6.  Hardware numerics,
    # not a cache bug (the CPU mesh reproduces exact parity).
    # kernel selection goes through the ONE capability-probed dispatch
    # table (ops/transformer/registry.py) — this function only ever sees
    # monolithic caches (the paged pool dispatches in write_and_attend)
    from deepspeed_tpu.ops.transformer.registry import select_kernel
    mode = select_kernel(s=S, paged=False, has_bias=bias is not None,
                         has_window=window is not None)
    if mode == "pallas_decode":
        # single-token decode: the Pallas online-softmax kernel streams the
        # cache blockwise instead of materializing [B,H,1,S_max] fp32
        # logits; sliding windows (mistral-style) mask inside the kernel
        from deepspeed_tpu.ops.transformer.decode_attention import (
            decode_attention)
        lengths = (q_positions[:, 0] + 1).astype(jnp.int32)
        return decode_attention(q[:, 0], k_cache, v_cache,
                                lengths, layer=layer,
                                k_scale=k_scale,
                                v_scale=v_scale,
                                window=window,
                                int8_matmuls=int8_matmuls)[:, None]
    if mode == "pallas_chunked_prefill":
        # multi-token block vs cache (chunked prefill / incremental
        # multi-token feed): the chunk kernel keeps score tiles at
        # [S, block_k] and never dequantizes the whole cache — the dense
        # fallback below materializes [B, H, S, S_max] fp32 scores (and,
        # quantized, a full-precision cache copy) per layer.  S is capped
        # at MAX_CHUNK_S (512): the kernel's q block and f32 accumulator
        # scale with S x H x D and would blow VMEM on longer blocks —
        # those keep the dense HBM fallback.
        from deepspeed_tpu.ops.transformer.decode_attention import (
            chunk_prefill_attention)
        starts = q_positions[:, 0].astype(jnp.int32)
        return chunk_prefill_attention(q, k_cache, v_cache, starts,
                                       layer=layer, k_scale=k_scale,
                                       v_scale=v_scale)
    if layer is not None:
        # dense fallback needs the layer slice after all
        sl = lambda c: jax.lax.dynamic_index_in_dim(c, layer, 0,
                                                    keepdims=False)
        k_cache, v_cache = sl(k_cache), sl(v_cache)
        if k_scale is not None:
            k_scale, v_scale = sl(k_scale), sl(v_scale)
    if k_scale is not None:
        # int8 payloads: dequantize for the dense path.  This re-expands
        # the WHOLE cache to full precision every step — the quantized
        # cache only pays off through the Pallas decode kernel (single
        # token, no alibi bias / sliding window)
        if S == 1:
            # multi-token prefill (S > 1) always takes this path and the
            # one-off dequant there is expected — only a *decode* step
            # landing here (alibi bias or no Pallas support) repeats the
            # full-cache dequant every token and actually hurts
            from deepspeed_tpu.utils.logging import warning_once
            warning_once(
                "kv_cache_quant decode fell back to dense attention "
                "(alibi bias or no Pallas support) — the full cache is "
                "dequantized per step, so the int8 cache SLOWS decode "
                "here instead of speeding it up")
        deq = lambda c, s: (c.reshape(B, S_max, KVH, D).astype(jnp.float32)
                            * s[..., None]).astype(q.dtype)
        k_cache = deq(k_cache, k_scale)
        v_cache = deq(v_cache, v_scale)
        k_cache = k_cache.reshape(B, S_max, KVH * D)
        v_cache = v_cache.reshape(B, S_max, KVH * D)
    # [B, S_max, KVH*D] → head-major [B, KVH, S_max, D] for the einsum
    k_cache = k_cache.reshape(B, S_max, KVH, D).transpose(0, 2, 1, 3)
    v_cache = v_cache.reshape(B, S_max, KVH, D).transpose(0, 2, 1, 3)
    if KVH != H:
        rep = H // KVH
        k_cache = jnp.repeat(k_cache, rep, axis=1)
        v_cache = jnp.repeat(v_cache, rep, axis=1)
    scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("bshd,bhtd->bhst", q, k_cache).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias[None, :, None, :].astype(jnp.float32)
    kv_pos = jnp.arange(S_max)
    ok = q_positions[:, None, :, None] >= kv_pos[None, None, None, :]
    if window is not None:
        ok = ok & (kv_pos[None, None, None, :]
                   > q_positions[:, None, :, None] - window)
    logits = jnp.where(ok, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bhtd->bshd", probs, v_cache)


class Attention(nn.Module):
    config: TransformerConfig
    layer_idx: Optional[int] = None

    @nn.compact
    def __call__(self, x, positions, mask=None, cache=None, prefill=False):
        cfg = self.config
        D, H, KVH = cfg.head_dim, cfg.num_heads, cfg.kv_heads
        window = cfg.window_for_layer(self.layer_idx)
        dense = partial(nn.DenseGeneral, use_bias=cfg.attn_bias_enabled,
                        dtype=cfg.jnp_dtype, param_dtype=jnp.float32)
        if cfg.fused_qkv and KVH == H:
            # one [h, 3·H·D] gemm instead of three [h, H·D] gemms — better
            # MXU utilization at small hidden sizes (checkpoint conversion
            # policies emit separate projections, so this is opt-in)
            qkv = dense(features=(3, H, D), name="qkv_proj")(x)
            q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        else:
            # fused_qkv with GQA falls back (warned once at config time)
            q = dense(features=(H, D), name="q_proj")(x)
            k = dense(features=(KVH, D), name="k_proj")(x)
            v = dense(features=(KVH, D), name="v_proj")(x)
        if cfg.position_embedding == "rope":
            q, k = _rope(q, k, positions, D, cfg.rope_theta,
                         rope_dim=cfg.rope_dim,
                         interleaved=cfg.rope_interleaved)
        if cfg.attention_softmax_scale is not None:
            # every attention path divides by sqrt(D); fold any other scale
            # (gpt-neo: 1.0, i.e. unscaled logits) into q up front so the
            # flash/decode kernels need no changes
            q = q * jnp.asarray(cfg.attention_softmax_scale * np.sqrt(D),
                                q.dtype)
        if cache is None:
            kv_len = x.shape[1]
        elif "pages" in cache:               # paged: the virtual length
            kv_len = cache["pages"].shape[1] * cache["k"].shape[-2]
        else:
            kv_len = cache["k"].shape[-2]
        bias = alibi_bias(H, kv_len) \
            if cfg.position_embedding == "alibi" else None
        if cache is not None:
            if cfg.sparse_attention is not None:
                # KV-cache decode attends densely over the cache; a
                # sparse-trained model sees a (slightly) different pattern
                # at generation time.  Surface it instead of silently
                # diverging.
                logger.warning(
                    "sparse_attention model decoding with dense KV-cache "
                    "attention — train/decode attention patterns differ")
            # write this step's k/v at the current position, attend over
            # cache; cache layout is [.., S_max, KVH*D] (S-major, heads
            # flattened — the decode kernel's full-lane-width DMA layout;
            # the write is the raw projection output, no transpose).
            # ALL cache layouts (monolithic / layer-stacked / paged pool)
            # and program classes (decode, chunked prefill, speculative
            # verify) go through the ONE kernel-registry dispatch point —
            # write form, kernel selection (capability-probed), the fused
            # aliased decode write, and the reference/gather fallback all
            # live there (ops/transformer/registry.py).
            from deepspeed_tpu.ops.transformer.registry import (
                write_and_attend)
            out, new_cache = write_and_attend(
                cfg, q, k, v, positions, cache, bias=bias, window=window,
                prefill=prefill)
        else:
            out = _attention(q, k, v, cfg, mask=mask, bias=bias,
                             window=window)
            new_cache = None
        proj = dense(features=cfg.hidden_size, axis=(-2, -1),
                     use_bias=cfg.attn_out_bias_enabled, name="o_proj")(
            out.reshape(*out.shape[:2], H, D))
        return proj, new_cache


class MLP(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = partial(nn.Dense, use_bias=cfg.mlp_bias_enabled,
                        dtype=cfg.jnp_dtype, param_dtype=jnp.float32)
        act = {"relu": nn.relu, "gelu": nn.gelu,
               "gelu_exact": partial(nn.gelu, approximate=False),
               "silu": nn.silu,
               # clip text encoder: x * sigmoid(1.702 x)
               "quick_gelu": lambda x: x * nn.sigmoid(1.702 * x)}[cfg.activation]
        if cfg.gated_mlp:
            gate = dense(cfg.ffn_size, name="gate_proj")(x)
            up = dense(cfg.ffn_size, name="up_proj")(x)
            h = act(gate) * up
        else:
            h = act(dense(cfg.ffn_size, name="up_proj")(x))
        return dense(cfg.hidden_size, name="down_proj")(h)


def resolve_moe_offset(cfg):
    """The index of the first MoE layer; the -1 sentinel means
    ``moe_every - 1`` (the Megatron default pattern)."""
    off = cfg.moe_layer_offset
    return cfg.moe_every - 1 if off < 0 else off


def _is_moe_layer(cfg, layer_idx):
    if cfg.moe_num_experts <= 0 or layer_idx is None:
        return False
    off = resolve_moe_offset(cfg)
    return layer_idx >= off and (layer_idx - off) % cfg.moe_every == 0


def _block_mlp(cfg, layer_idx, h, train=True):
    """Dense MLP or MoE for one block; returns (out, aux_loss).  A plain
    function (submodules attach to the calling compact method) so flax's
    module summary never re-invokes it as a standalone module method.
    ``train`` selects the gate's capacity/noise regime (reference
    ``TopKGate`` train vs eval capacity)."""
    if not _is_moe_layer(cfg, layer_idx):
        return MLP(cfg, name="mlp")(h), 0.0
    from deepspeed_tpu.moe.layer import MoE
    out, aux, _ = MoE(hidden_size=cfg.hidden_size,
                      num_experts=cfg.moe_num_experts,
                      ep_size=cfg.moe_ep_size, k=cfg.moe_top_k,
                      capacity_factor=cfg.moe_capacity_factor,
                      eval_capacity_factor=cfg.moe_eval_capacity_factor,
                      ffn_hidden_size=cfg.ffn_size,
                      expert_bias=cfg.moe_expert_bias,
                      dtype=cfg.jnp_dtype, name="moe_mlp")(h, train=train)
    return out.astype(cfg.jnp_dtype), aux


class Block(nn.Module):
    config: TransformerConfig
    layer_idx: Optional[int] = None


    @nn.compact
    def __call__(self, x, positions, mask=None, cache=None, train=True,
                 prefill=False):
        # ``prefill``: STATIC bool — this call is a from-zero multi-token
        # prefill, so attention can take the flash path over the fresh
        # q/k/v (see Attention).  Threaded as a positional static arg
        # because jax.checkpoint turns `positions` into a tracer, hiding
        # the fact from any staticness test inside.
        cfg = self.config
        if not cfg.pre_layer_norm:
            # post-LN (opt-350m): norm follows each residual add
            attn, new_cache = Attention(cfg, layer_idx=self.layer_idx,
                                        name="attn")(x, positions, mask,
                                                     cache, prefill=prefill)
            x = _norm(cfg, "input_norm")(x + attn).astype(cfg.jnp_dtype)
            mlp_out, aux = _block_mlp(cfg, self.layer_idx, x, train=train)
            x = _norm(cfg, "post_attn_norm")(x + mlp_out).astype(cfg.jnp_dtype)
            return x, new_cache, aux
        normed = _norm(cfg, "input_norm")(x).astype(cfg.jnp_dtype)
        attn, new_cache = Attention(cfg, layer_idx=self.layer_idx,
                                    name="attn")(normed, positions, mask,
                                                 cache, prefill=prefill)
        if cfg.parallel_residual:
            mlp_in = normed if cfg.shared_attn_mlp_norm else \
                _norm(cfg, "post_attn_norm")(x).astype(cfg.jnp_dtype)
            mlp_out, aux = _block_mlp(cfg, self.layer_idx, mlp_in,
                                      train=train)
            x = x + attn + mlp_out
        else:
            x = x + attn
            mlp_out, aux = _block_mlp(
                cfg, self.layer_idx,
                _norm(cfg, "post_attn_norm")(x).astype(cfg.jnp_dtype),
                train=train)
            x = x + mlp_out
        return x, new_cache, aux


class ScanBlock(Block):
    """Block with the (carry, output) signature nn.scan requires.  The
    carry is ``(activation, stacked_cache)``: the FULL ``[L, ...]`` KV
    cache rides the carry with a per-iteration layer counter, so decode
    writes ONE token slice per step in place — the previous ys-based
    design re-materialized the entire cache every decode step (a
    ~full-HBM-cache write per generated token)."""

    @nn.compact
    def __call__(self, carry, positions, mask=None, prefill=False):
        x, cache = carry
        x, new_cache, aux = Block.__call__(self, x, positions, mask, cache,
                                           True, prefill)
        if new_cache is not None:
            new_cache = dict(new_cache, layer=new_cache["layer"] + 1)
        return (x, new_cache), aux


class Transformer(nn.Module):
    """Decoder-only LM.  ``__call__(batch)`` returns the causal-LM loss when
    ``batch`` has ``labels`` (or shifts ``input_ids``), else logits."""
    config: TransformerConfig

    def setup(self):
        cfg = self.config
        embed_dim = cfg.embed_proj_dim or cfg.hidden_size
        self.embed_tokens = nn.Embed(cfg.vocab_size, embed_dim,
                                     param_dtype=jnp.float32, name="embed_tokens")
        if cfg.embed_proj_dim is not None:
            self.project_in = nn.Dense(cfg.hidden_size, use_bias=False,
                                       dtype=cfg.jnp_dtype,
                                       param_dtype=jnp.float32,
                                       name="project_in")
            self.project_out = nn.Dense(cfg.embed_proj_dim, use_bias=False,
                                        dtype=cfg.jnp_dtype,
                                        param_dtype=jnp.float32,
                                        name="project_out")
        if cfg.position_embedding == "learned":
            self.embed_positions = nn.Embed(cfg.max_seq_len, cfg.hidden_size,
                                            param_dtype=jnp.float32,
                                            name="embed_positions")
        if cfg.embedding_norm:
            self.embed_norm = _norm(cfg, "embed_norm")
        block = ScanBlock if cfg.scan_layers else Block
        if cfg.remat:
            policy = resolve_remat_policy(cfg.remat_policy)
            # `train` and `prefill` gate Python control flow (MoE gate
            # regime / flash-vs-cached attention) and must stay static
            # bools through jax.checkpoint, so they ride positionally:
            # non-scan Block(self, x, positions, mask, cache, train,
            # prefill) -> (5, 6); ScanBlock(self, carry, positions, mask,
            # prefill) -> (4,).  (kwargs are not covered by
            # static_argnums.)
            static = (4,) if cfg.scan_layers else (5, 6)
            block = nn.remat(block, policy=policy, static_argnums=static)
        if cfg.scan_layers:
            self.blocks = nn.scan(
                block,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layers")
        else:
            self.block_list = [block(cfg, layer_idx=i, name=f"layers_{i}")
                               for i in range(cfg.num_layers)]
        if cfg.pre_layer_norm:
            self.final_norm = _norm(cfg, "final_norm")
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(cfg.vocab_size, use_bias=cfg.lm_head_bias,
                                    dtype=cfg.jnp_dtype, param_dtype=jnp.float32,
                                    name="lm_head")

    def hidden_states(self, input_ids, mask=None, cache=None, start_pos=0,
                      with_aux=False, train=True):
        cfg = self.config
        B, S = input_ids.shape
        # start_pos: scalar, or [B] per-row offsets (padded-prompt decode —
        # each row continues from its own prompt length).  The RANK of
        # start_pos statically selects the cache-write path: per-row
        # offsets need a scatter, the shared-position fast path keeps the
        # proven dynamic_update_slice (see Attention).
        start = jnp.asarray(start_pos)
        per_row_pos = start.ndim >= 1
        if start.ndim == 1:
            start = start[:, None]
        positions = start + jnp.broadcast_to(jnp.arange(S), (B, S))
        x = self.embed_tokens(input_ids).astype(cfg.jnp_dtype)
        if cfg.embed_proj_dim is not None:
            x = self.project_in(x)
        if cfg.position_embedding == "learned":
            x = x + self.embed_positions(positions).astype(cfg.jnp_dtype)
        if cfg.embedding_norm:
            x = self.embed_norm(x).astype(cfg.jnp_dtype)
        marker = {"per_row": jnp.zeros((), jnp.int32)} if per_row_pos else {}
        if cache is not None and "pages" in cache:
            # paged pool: the per-row page table threads every layer's
            # cache dict unchanged (pages are constant across layers)
            marker["pages"] = cache["pages"]
            if "paged_kernel_off" in cache:
                # serving.paged_kernel=False: the registry routes paged
                # attention back to the gather path.  STATIC pytree
                # structure (like per_row) — flipping the knob is a
                # different program, never a retrace surprise
                marker["paged_kernel_off"] = cache["paged_kernel_off"]
        # from-zero multi-token prefill, decided where the start is
        # still STATICALLY visible (generation passes a literal 0;
        # inside the remat-wrapped block `positions` is a tracer):
        # attention then takes the flash path over the fresh q/k/v
        # instead of the dense cached fallback (see Attention)
        prefill = (cache is not None and S > 1
                   and isinstance(start_pos, (int, np.integer))
                   and int(start_pos) == 0)
        if cfg.scan_layers:
            carry_cache = None if cache is None else \
                {**_cache_data(cache),
                 "layer": jnp.asarray(0, jnp.int32), **marker}
            (x, out_cache), aux_layers = self.blocks((x, carry_cache),
                                                     positions, mask,
                                                     prefill)
            aux = jnp.sum(aux_layers)
            new_cache = None if cache is None else _cache_data(out_cache)
        else:
            aux = 0.0
            # the full stacked cache threads through the loop; each layer
            # writes only its token slice (see Attention stacked-carry path)
            cur = None if cache is None else _cache_data(cache)
            for i, blk in enumerate(self.block_list):
                layer_cache = None if cur is None else \
                    {**cur, "layer": jnp.asarray(i, jnp.int32), **marker}
                # train/prefill positional: static_argnums only covers
                # positionals
                x, nc, a = blk(x, positions, mask, layer_cache, train,
                               prefill)
                if cur is not None:
                    cur = _cache_data(nc)
                aux = aux + a
            new_cache = cur
        h = self.final_norm(x).astype(cfg.jnp_dtype) \
            if cfg.pre_layer_norm else x
        if with_aux:
            return h, new_cache, aux
        return (h, new_cache) if cache is not None else h

    def _head(self, x):
        if self.config.embed_proj_dim is not None:
            x = self.project_out(x)
        if self.config.tie_word_embeddings:
            emb = self.embed_tokens.embedding.astype(self.config.jnp_dtype)
            return x @ emb.T
        return self.lm_head(x)

    def _head_pure(self, ref):
        """Pure head closure over concrete weight arrays — safe to call
        inside ``jax.checkpoint``/``lax.map`` (a bound ``nn.Dense`` is not:
        flax modules cannot be invoked under raw jax transforms).  ``ref``
        is any [..., S, h] activation; a zero-width slice through lm_head /
        project_out forces their params to exist at init time with no
        compute."""
        cfg = self.config
        proj = None
        head_ref = ref[..., :0, :]
        if cfg.embed_proj_dim is not None:
            # zero-width pass both forces project_out's params to exist and
            # gives lm_head its (projected-width) init reference
            head_ref = self.project_out(head_ref)
            proj = jnp.asarray(
                self.project_out.variables["params"]["kernel"], cfg.jnp_dtype)
        # keep the projection as a separate matmul: folding proj @ W would
        # materialize a [hidden, vocab] weight and ~2x the head FLOPs
        chain = (lambda x: x) if proj is None else (lambda x: x @ proj)
        if cfg.tie_word_embeddings:
            W = self.embed_tokens.embedding.astype(cfg.jnp_dtype).T
            return lambda x: chain(x) @ W
        self.lm_head(head_ref)
        p = self.lm_head.variables["params"]
        W = jnp.asarray(p["kernel"], cfg.jnp_dtype)
        if "bias" in p:
            b = jnp.asarray(p["bias"], cfg.jnp_dtype)
            return lambda x: chain(x) @ W + b
        return lambda x: chain(x) @ W

    def logits(self, input_ids, mask=None):
        return self._head(self.hidden_states(input_ids, mask, train=False))

    def decode(self, input_ids, cache, start_pos, logits_at=None):
        """KV-cached decode/prefill step: returns (logits, new_cache).
        ``input_ids``: [B, S_step]; positions are ``start_pos + arange``.

        ``logits_at`` ([B] int32, optional): project ONLY these per-row
        positions through the vocab head, returning [B, 1, V].  Generation
        prefill needs just each row's last real position — the full
        [B, S, V] prefill logits are a multi-GB temporary at long prompts
        (bs16 x 3968 x 50k vocab = 6.4 GB bf16) that OOMs a 16 GB chip."""
        h, new_cache = self.hidden_states(input_ids, cache=cache,
                                          start_pos=start_pos, train=False)
        if logits_at is not None:
            h = jnp.take_along_axis(
                h, logits_at.astype(jnp.int32)[:, None, None], axis=1)
        return self._head(h), new_cache

    def prefill_chunked(self, input_ids, cache, chunk_size, logits_at=None):
        """Memory-bounded prefill: the prompt runs through the trunk in
        ``chunk_size``-token blocks via an ``nn.scan`` over chunks (params
        broadcast, cache carried), each chunk attending to the cache
        through the Pallas chunk kernel — per-layer transients are
        O(B·chunk) instead of O(B·prompt), which is what lets a 4k-prompt
        or bs128 prefill fit next to the KV cache (reference analog: the
        workspace-resident incremental prefill of ``inference_context.h``).

        The prompt is right-padded to a chunk multiple; padded positions
        write garbage K/V beyond the live region, which is safe: every
        attention path masks positions beyond each query's own position,
        and decode overwrites position ``prompt_len + t`` before reading
        it.  Returns ``(logits, cache)`` like :meth:`decode` —
        ``logits_at`` ([B] int32) selects the per-row positions projected
        through the vocab head ([B, 1, V]); default is the last prompt
        position.
        """
        cfg = self.config
        B, P = input_ids.shape
        C = int(chunk_size)
        n = -(-P // C)
        ids = jnp.pad(input_ids, ((0, 0), (0, n * C - P)))
        chunks = ids.reshape(B, n, C).swapaxes(0, 1)          # [n, B, C]
        starts = (jnp.arange(n) * C).astype(jnp.int32)
        if logits_at is None:
            logits_at = jnp.full((B,), P - 1, jnp.int32)
        logits_at = logits_at.astype(jnp.int32)

        # each chunk selects its rows' requested hidden vectors and merges
        # them into a [B, 1, hidden] carry — stacking every chunk's full
        # hidden states as scan outputs would reintroduce the O(B x P x h)
        # transient this method exists to avoid
        def _chunk_body(mdl, carry, xs):
            cache, h_sel = carry
            start, chunk_ids = xs
            h, new_cache = mdl.hidden_states(chunk_ids, cache=cache,
                                             start_pos=start, train=False)
            local = jnp.clip(logits_at - start, 0, C - 1)
            h_c = jnp.take_along_axis(h, local[:, None, None], axis=1)
            in_chunk = ((logits_at >= start)
                        & (logits_at < start + C))[:, None, None]
            return (_cache_data(new_cache),
                    jnp.where(in_chunk, h_c, h_sel)), ()

        scanner = nn.scan(_chunk_body, variable_broadcast="params",
                          split_rngs={"params": False, "dropout": False},
                          in_axes=0, out_axes=0)
        h0 = jnp.zeros((B, 1, cfg.hidden_size), cfg.jnp_dtype)
        (new_cache, h_last), _ = scanner(self, (_cache_data(cache), h0),
                                         (starts, chunks))
        return self._head(h_last), new_cache

    def init_cache(self, batch_size, max_len, dtype=None):
        """Zero KV cache: [L, B, max_len, KVH*D] per k/v (layer-stacked for
        the scanned trunk; S-major with flattened heads so decode cache
        writes are the raw projection output and the decode kernel's KV
        DMAs are contiguous full-lane-width slabs).  With
        ``kv_cache_quant`` the payloads are int8 plus per-(position,
        kv-head) float scales [L, B, max_len, KVH]."""
        cfg = self.config
        dtype = dtype or cfg.jnp_dtype
        shape = (cfg.num_layers, batch_size, max_len,
                 cfg.kv_heads * cfg.head_dim)
        if cfg.kv_cache_quant:
            sshape = shape[:-1] + (cfg.kv_heads,)
            return {"k": jnp.zeros(shape, jnp.int8),
                    "v": jnp.zeros(shape, jnp.int8),
                    "k_scale": jnp.zeros(sshape, jnp.float32),
                    "v_scale": jnp.zeros(sshape, jnp.float32)}
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def init_paged_cache(self, num_pages, page_size, dtype=None):
        """Zero PAGED KV pool: ``[L, num_pages, page_size, KVH*D]`` per
        k/v (+ per-(position, kv-head) scales with ``kv_cache_quant``).
        Physical pages are position-order-free: a consumer threads a
        per-row page table (``cache["pages"]``: virtual page ``pos //
        page_size`` → physical page) through ``decode``, and the
        attention paths see the gathered virtual view.  Page 0 is
        conventionally the serving engine's reserved trash page (never
        allocated; unmapped table entries point at it)."""
        cfg = self.config
        dtype = dtype or cfg.jnp_dtype
        shape = (cfg.num_layers, int(num_pages), int(page_size),
                 cfg.kv_heads * cfg.head_dim)
        if cfg.kv_cache_quant:
            sshape = shape[:-1] + (cfg.kv_heads,)
            return {"k": jnp.zeros(shape, jnp.int8),
                    "v": jnp.zeros(shape, jnp.int8),
                    "k_scale": jnp.zeros(sshape, jnp.float32),
                    "v_scale": jnp.zeros(sshape, jnp.float32)}
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def __call__(self, batch):
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
            mask = batch.get("attention_mask")
        else:
            input_ids, labels, mask = batch, None, None
        if labels is None:
            labels = derive_causal_labels(input_ids, mask)
        cfg = self.config
        C = cfg.loss_seq_chunks
        if C > 1 and input_ids.shape[1] % C != 0:
            logger.warning(
                f"loss_seq_chunks={C} does not divide seq_len="
                f"{input_ids.shape[1]} — falling back to full-logits loss "
                f"(materializes the [B,S,V] tensor)")
            C = 0
        h, _, aux = self.hidden_states(input_ids, mask, with_aux=True)
        if C > 1:
            loss = chunked_cross_entropy_loss(h, labels, self._head_pure(h), C)
        else:
            loss = cross_entropy_loss(self._head(h), labels)
        if cfg.moe_num_experts > 0:
            loss = loss + cfg.moe_aux_coef * aux
        return loss


def derive_causal_labels(input_ids, attention_mask=None, ignore_index=-100):
    """Next-token labels from inputs; padded positions (mask==0) are
    excluded so pad ids are never trained as targets."""
    labels = jnp.pad(input_ids[..., 1:], [(0, 0)] * (input_ids.ndim - 1) + [(0, 1)],
                     constant_values=ignore_index)
    if attention_mask is not None:
        next_mask = jnp.pad(attention_mask[..., 1:],
                            [(0, 0)] * (attention_mask.ndim - 1) + [(0, 1)],
                            constant_values=0)
        labels = jnp.where(next_mask.astype(bool), labels, ignore_index)
    return labels


def chunked_cross_entropy_loss(h, labels, head_fn, n_chunks,
                               ignore_index=-100):
    """Sequence-chunked causal-LM loss: the head matmul + CE run per chunk
    under ``jax.checkpoint`` so only one chunk's [B, S/C, V] logits is ever
    live (fwd or bwd) — the backward recomputes each chunk's logits instead
    of storing the full [B, S, V] fp32 tensor.  Matches
    ``cross_entropy_loss`` exactly (sum-of-nll / count composition)."""
    B, S, _ = h.shape
    if S % n_chunks:
        raise ValueError(f"seq_len {S} not divisible by n_chunks {n_chunks}")
    csz = S // n_chunks

    @jax.checkpoint
    def one(args):
        hb, lb = args
        logits = head_fn(hb).astype(jnp.float32)
        valid = lb != ignore_index
        safe = jnp.where(valid, lb, 0)
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        return jnp.sum((logz - gold) * valid), jnp.sum(valid)

    if os.environ.get("DSTPU_LOSS_CHUNK_UNROLL", "0") == "1":
        # unrolled variant: chunks slice h directly (no chunk-major copy of
        # the full activation, no dynamic-update-slice in the backward) and
        # XLA can interleave chunk i's CE (VPU) with chunk i+1's head
        # matmul (MXU)
        parts = [one((jax.lax.dynamic_slice_in_dim(h, i * csz, csz, axis=1),
                      jax.lax.dynamic_slice_in_dim(labels, i * csz, csz,
                                                   axis=1)))
                 for i in range(n_chunks)]
        sums = jnp.stack([p[0] for p in parts])
        counts = jnp.stack([p[1] for p in parts])
    else:
        # chunk-major copy once, then a compact while loop over chunks
        hc = h.reshape(B, n_chunks, csz, h.shape[-1]).transpose(1, 0, 2, 3)
        lc = labels.reshape(B, n_chunks, csz).transpose(1, 0, 2)
        sums, counts = jax.lax.map(one, (hc, lc))
    return jnp.sum(sums) / jnp.maximum(jnp.sum(counts), 1)


def cross_entropy_loss(logits, labels, ignore_index=-100, z_loss=0.0):
    """Causal-LM loss with ignore-index masking, computed in fp32."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * valid
    loss = nll.sum() / jnp.maximum(valid.sum(), 1)
    if z_loss > 0.0:
        loss = loss + z_loss * jnp.mean((logz * valid) ** 2)
    return loss
