"""TL006 positive fixture: jit-signature instability (retrace drift)."""
import jax
import jax.numpy as jnp

from deepspeed_tpu.tools.lint.hotpath import hot_path


def step(params, lr, step_no):
    return params


step_jit = jax.jit(step)
out = step_jit(jnp.ones(4), 1e-3, 7)            # TL006 x2: scalars traced
out2 = step_jit(jnp.ones(4), lr=0.5, step_no=jnp.asarray(7))  # TL006: kw scalar


def run(x, cfg):
    return x


run_jit = jax.jit(run, static_argnames=("cfg",))


def make_cfg():
    return object()


out3 = run_jit(jnp.ones(2), cfg=make_cfg())     # TL006: identity-hashed static
out4 = run_jit(jnp.ones(2), cfg=lambda: 1)      # TL006: lambda static


def pick(k, x):
    return x


pick_jit = jax.jit(pick, static_argnums=(0,))
out5 = pick_jit(make_cfg(), jnp.ones(2))        # TL006: positional unstable static


@hot_path("fixture.decode")
def decode(batch, cache):
    if batch.shape[0] > 8:                      # TL006: shape branch on hot path
        return cache
    while batch.ndim > 2:                       # TL006: shape branch on hot path
        batch = batch[0]
    if len(batch) > 4:                          # TL006: len() of a parameter
        return cache
    return batch
