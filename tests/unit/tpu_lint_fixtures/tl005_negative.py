"""TL005 negative fixture: lookups hoisted to setup, or off the hot path."""
from deepspeed_tpu.tools.lint.hotpath import hot_path


def make_train_step(config):
    lr = config["lr"]                        # setup time — fine

    @hot_path("fixture.train_step")
    def train_step(params, batch):
        return params, lr                    # closed-over value

    return train_step


def build(config):
    return config.get("optimizer")           # cold path — fine
