"""BERT encoder family — flax implementation over the fused encoder layer.

Reference parity: the reference's inference test matrix is BERT-heavy
(``tests/unit/inference/test_inference.py``; injection policy
``module_inject/replace_policy.py`` HFBertLayerPolicy; the training kernels
behind ``DeepSpeedTransformerLayer`` were built for BERT).  The encoder
stack here IS ``DeepSpeedTransformerLayer`` (post-LN path) — the same
layer-op users of the reference wrap, driven through a full model with
embeddings, pooler, and the masked-LM head.
"""

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: str = "float32"
    num_labels: Optional[int] = None   # set → sequence classification head

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def jnp_dtype(self):
        return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                "float16": jnp.float16}[self.dtype]

    def layer_config(self):
        return DeepSpeedTransformerConfig(
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            heads=self.num_heads,
            layer_norm_eps=self.layer_norm_eps,
            attn_dropout_ratio=0.0,
            hidden_dropout_ratio=0.0,
            pre_layer_norm=False,        # BERT is post-LN
            fp16=self.dtype == "float16",
            compute_dtype=self.jnp_dtype)


class BertEmbeddings(nn.Module):
    config: BertConfig

    def setup(self):
        cfg = self.config
        # setup-style so the MLM head can reach word_embeddings for tying
        self.word_embeddings = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                                        param_dtype=jnp.float32)
        self.position_embeddings = nn.Embed(cfg.max_position_embeddings,
                                            cfg.hidden_size,
                                            param_dtype=jnp.float32)
        self.token_type_embeddings = nn.Embed(cfg.type_vocab_size,
                                              cfg.hidden_size,
                                              param_dtype=jnp.float32)
        self.layer_norm = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                       param_dtype=jnp.float32)

    def __call__(self, input_ids, token_type_ids=None):
        cfg = self.config
        S = input_ids.shape[1]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(jnp.arange(S)[None])
             + self.token_type_embeddings(token_type_ids))
        return self.layer_norm(x).astype(cfg.jnp_dtype)


class BertModel(nn.Module):
    """Embeddings + N fused encoder layers + pooler (HF BertModel shape)."""
    config: BertConfig
    add_pooler: bool = True

    def setup(self):
        cfg = self.config
        self.embeddings = BertEmbeddings(cfg)
        lc = cfg.layer_config()
        self.layers = [DeepSpeedTransformerLayer(lc, name=f"layers_{i}")
                       for i in range(cfg.num_layers)]
        if self.add_pooler:
            self.pooler = nn.Dense(cfg.hidden_size, name="pooler",
                                   param_dtype=jnp.float32,
                                   dtype=cfg.jnp_dtype)

    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        x = self.embeddings(input_ids, token_type_ids)
        for layer in self.layers:
            x = layer(x, attention_mask=attention_mask)
        pooled = jnp.tanh(self.pooler(x[:, 0])) if self.add_pooler else None
        return x, pooled


class BertEncoder(nn.Module):
    """Headless contract (HF ``BertModel``): returns the final hidden states
    from a dict call — the module headless checkpoints convert onto."""
    config: BertConfig
    add_pooler: bool = False

    def setup(self):
        self.bert = BertModel(self.config, add_pooler=self.add_pooler)

    def __call__(self, batch, attention_mask=None, token_type_ids=None):
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            attention_mask = batch.get("attention_mask", attention_mask)
            token_type_ids = batch.get("token_type_ids", token_type_ids)
        else:
            input_ids = batch
        h, _ = self.bert(input_ids, attention_mask, token_type_ids)
        return h


class BertForMaskedLM(nn.Module):
    """HF ``BertForMaskedLM`` contract: logits over the vocab per position.
    The decoder weight ties to the word embeddings (HF default)."""
    config: BertConfig

    def setup(self):
        cfg = self.config
        self.bert = BertModel(cfg, add_pooler=False)
        self.transform_dense = nn.Dense(cfg.hidden_size, name="transform_dense",
                                        param_dtype=jnp.float32,
                                        dtype=cfg.jnp_dtype)
        self.transform_ln = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                         name="transform_ln",
                                         param_dtype=jnp.float32)
        self.decoder_bias = self.param("decoder_bias", nn.initializers.zeros,
                                       (cfg.vocab_size,), jnp.float32)

    def __call__(self, batch, attention_mask=None, token_type_ids=None):
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            attention_mask = batch.get("attention_mask", attention_mask)
            token_type_ids = batch.get("token_type_ids", token_type_ids)
        else:
            input_ids = batch
        cfg = self.config
        h, _ = self.bert(input_ids, attention_mask, token_type_ids)
        h = nn.gelu(self.transform_dense(h), approximate=False)
        h = self.transform_ln(h).astype(cfg.jnp_dtype)
        # tied decoder: logits = h @ word_embeddings^T + bias
        we = self.bert.embeddings.word_embeddings.embedding
        logits = h @ we.T.astype(h.dtype) + self.decoder_bias.astype(h.dtype)
        return logits


class BertForSequenceClassification(nn.Module):
    """HF ``BertForSequenceClassification`` contract (pooled CLS → labels)."""
    config: BertConfig

    def setup(self):
        cfg = self.config
        self.bert = BertModel(cfg, add_pooler=True)
        self.classifier = nn.Dense(cfg.num_labels or 2, name="classifier",
                                   param_dtype=jnp.float32,
                                   dtype=cfg.jnp_dtype)

    def __call__(self, batch, attention_mask=None, token_type_ids=None):
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            attention_mask = batch.get("attention_mask", attention_mask)
            token_type_ids = batch.get("token_type_ids", token_type_ids)
        else:
            input_ids = batch
        _, pooled = self.bert(input_ids, attention_mask, token_type_ids)
        return self.classifier(pooled)
