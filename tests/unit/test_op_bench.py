"""Smoke tests for the op micro-benchmark CLI (analog of reference
``tests/perf/adam_test.py`` — correctness of the harness, not speed)."""

from deepspeed_tpu.benchmarks import op_bench


def test_bench_adam_smoke():
    r = op_bench.bench_adam(numel=2048, iters=1)
    assert r["op"] == "fused_adamw" and r["ms"] > 0


def test_bench_flash_smoke():
    r = op_bench.bench_flash_attention(b=1, s=256, h=2, d=64, iters=1)
    assert r["TFLOP/s"] > 0
    r = op_bench.bench_flash_attention(b=1, s=256, h=2, d=64, iters=1,
                                       bwd=True)
    assert r["op"].endswith("bwd")


def test_bench_quant_smoke():
    r = op_bench.bench_quantizer(numel=64 * 2048, iters=1)
    assert r["ms"] > 0
