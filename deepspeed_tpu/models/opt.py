"""OPT model family presets — the reference's headline workload
(DeepSpeed-Chat SFT benchmarks all run OPT: ``blogs/deepspeed-chat/README.md:38-66``).

Architecture facts per the OPT paper / HF configs: learned positions,
ReLU MLP, pre-LN, tied embeddings for the LM head in the small models.
"""

from deepspeed_tpu.models.transformer import Transformer, TransformerConfig

OPT_CONFIGS = {
    "opt-125m": dict(hidden_size=768, num_layers=12, num_heads=12,
                     ffn_hidden_size=3072),
    "opt-350m": dict(hidden_size=1024, num_layers=24, num_heads=16,
                     ffn_hidden_size=4096),
    "opt-1.3b": dict(hidden_size=2048, num_layers=24, num_heads=32,
                     ffn_hidden_size=8192),
    "opt-2.7b": dict(hidden_size=2560, num_layers=32, num_heads=32,
                     ffn_hidden_size=10240),
    "opt-6.7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                     ffn_hidden_size=16384),
    "opt-13b": dict(hidden_size=5120, num_layers=40, num_heads=40,
                    ffn_hidden_size=20480),
    "opt-30b": dict(hidden_size=7168, num_layers=48, num_heads=56,
                    ffn_hidden_size=28672),
    "opt-66b": dict(hidden_size=9216, num_layers=64, num_heads=72,
                    ffn_hidden_size=36864),
}


def opt_config(name, **overrides):
    if name not in OPT_CONFIGS:
        raise ValueError(f"unknown OPT model {name}; known: {sorted(OPT_CONFIGS)}")
    base = dict(vocab_size=50272, max_seq_len=2048, activation="relu",
                position_embedding="learned", rms_norm=False,
                tie_word_embeddings=True)
    base.update(OPT_CONFIGS[name])
    base.update(overrides)
    return TransformerConfig(**base)


def opt_model(name, **overrides):
    return Transformer(opt_config(name, **overrides))


# Llama-style presets exercise the rope/RMSNorm/SwiGLU/GQA paths
# (reference covers llama via module_inject/containers/llama.py).
LLAMA_CONFIGS = {
    "llama-tiny": dict(hidden_size=256, num_layers=4, num_heads=8,
                       num_kv_heads=4, ffn_hidden_size=688, vocab_size=32000),
    "llama-7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                     ffn_hidden_size=11008, vocab_size=32000),
    "llama-13b": dict(hidden_size=5120, num_layers=40, num_heads=40,
                      ffn_hidden_size=13824, vocab_size=32000),
}


def llama_config(name, **overrides):
    base = dict(max_seq_len=2048, activation="silu", gated_mlp=True,
                position_embedding="rope", rms_norm=True,
                tie_word_embeddings=False)
    base.update(LLAMA_CONFIGS[name])
    base.update(overrides)
    return TransformerConfig(**base)


def llama_model(name, **overrides):
    return Transformer(llama_config(name, **overrides))
