"""Hot-path registry for tpu-lint.

``@hot_path("name")`` marks a function as a TPU hot path: a train step,
decode loop, prefill chunk, or anything else that runs once per training or
serving step.  The decorator is an IDENTITY at runtime (zero overhead — it
runs once at definition time and returns the function unchanged); its value
is to the static analyzer, which treats every marked function AND everything
lexically nested in or (heuristically) called from one as hot when applying
host-transfer and caching rules (TL001, TL005).

Kept in its own module with no linter imports so the runtime engines can
import it for free.
"""

# (name, module, qualname) of every hot path registered this process —
# consumed by the jaxpr harness and by `python -m deepspeed_tpu.tools.lint
# --hot-paths` for debugging.
REGISTERED = []


def hot_path(name):
    """Mark the decorated function as a TPU hot path named ``name``."""
    def mark(fn):
        REGISTERED.append((name, fn.__module__, fn.__qualname__))
        return fn
    return mark
