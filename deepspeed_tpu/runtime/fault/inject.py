"""Deterministic fault injection — kill the run at every seam, on purpose.

Recovery code that has never seen a failure does not work; this registry
lets tests (and chaos drills on real slices) trigger a precise failure at a
named point in the checkpoint / training / serving pipeline:

====================================  ====================================
point                                 seam
====================================  ====================================
``ckpt.save_io``                      start of a checkpoint save (the
                                      transient-IOError seam the retry
                                      policy covers)
``ckpt.arrays_write``                 after array shards are written,
                                      before metadata — a kill here leaves
                                      a data-partial staging dir
``ckpt.before_manifest``              staging dir fully written, manifest
                                      not yet emitted
``ckpt.corrupt_shard``                after the manifest: the ``corrupt``
                                      action flips bytes in one array
                                      shard (bit-rot simulation)
``ckpt.before_commit_rename``         manifest durable, atomic rename not
                                      yet performed
``ckpt.before_latest_swap``           tag committed, ``latest`` pointer
                                      not yet swapped
``train.step_begin``                  top of every supervised train step
                                      (``sigterm``-at-step-K, ``hang``)
``infer.executable_load``             AOT executable load/compile in the
                                      inference engine
``serving.pre_admit``                 before the serving engine's fused
                                      admit dispatch (slot reserved, lane
                                      prefilled, state not yet written)
``serving.pre_decode_dispatch``       before each serving decode-block
                                      dispatch
``serving.mid_drain``                 every iteration of the graceful
                                      preemption drain loop (kills here
                                      land BEFORE the snapshot publish)
``serving.sigterm_at_iter``           top of every serving scheduler
                                      iteration (``sigterm``-at-iter-K:
                                      the graceful-preemption proof)
``serving.pre_step_lock``             in ``step()``, after the owner
                                      check, before the engine lock —
                                      a ``yield`` here perturbs the
                                      scheduler-vs-handler acquisition
                                      order
``serving.pre_submit_lock``           in ``submit()`` before the lock
``serving.pre_cancel_lock``           in ``cancel()`` before the lock
``serving.pre_subscribe_lock``        in ``token_events()`` before the
                                      lock
``serving.mirror_drain``              per event popped in the host
                                      mirror's drain loop (lock held —
                                      a ``yield`` here stretches the
                                      retirement window other threads
                                      contend against)
====================================  ====================================

Arm points programmatically (:func:`configure_injection`) or via the
``DSTPU_FAULT_INJECT`` env var — specs separated by ``;``, fields by
``,``::

    DSTPU_FAULT_INJECT="point=ckpt.before_latest_swap,action=exit,at=1"

Spec fields: ``point`` (required), ``action`` (``exit`` | ``raise`` |
``sigterm`` | ``hang`` | ``corrupt``; default ``raise``), ``at`` (fire on
the Nth hit of the point, 1-based; default 1), ``times`` (how many
consecutive hits fire, default 1; ``0`` = every hit from ``at`` on),
``seconds`` (hang duration, default 3600), ``exit_code`` (default 17).

Actions:

* ``exit`` — ``os._exit``: the process dies with no cleanup, no atexit, no
  finally blocks.  The honest simulation of SIGKILL / machine preemption.
* ``raise`` — raise :class:`InjectedFault` (an ``IOError``): the transient
  failure the retry/backoff policy must absorb.
* ``sigterm`` — deliver SIGTERM to self: the graceful-preemption path the
  elastic agent handles.
* ``hang`` — sleep at the seam: what a stuck collective looks like to the
  heartbeat watchdog.
* ``corrupt`` — flip bytes in the largest file under the ``path`` the seam
  provides (array shard): manifest verification must catch it.
* ``yield`` — sleep a RANDOMIZED ``[0, seconds]`` interval drawn from a
  deterministic per-spec RNG (``seed`` field): the interleaving stress
  harness (``tools/lint/interleave_check.py``) arms this at the serving
  lock seams to force different thread schedules per seed while staying
  reproducible.

``fire()`` is a dict-lookup no-op when nothing is armed — it is safe on
hot-ish paths like the supervisor step loop.
"""

import os
import random
import signal
import threading
import time

from deepspeed_tpu.utils.logging import logger

ENV_VAR = "DSTPU_FAULT_INJECT"

INJECTION_POINTS = (
    "ckpt.save_io",
    "ckpt.arrays_write",
    "ckpt.before_manifest",
    "ckpt.corrupt_shard",
    "ckpt.before_commit_rename",
    "ckpt.before_latest_swap",
    "train.step_begin",
    "infer.executable_load",
    "serving.pre_admit",
    "serving.pre_decode_dispatch",
    "serving.mid_drain",
    "serving.sigterm_at_iter",
    "serving.pre_step_lock",
    "serving.pre_submit_lock",
    "serving.pre_cancel_lock",
    "serving.pre_subscribe_lock",
    "serving.mirror_drain",
)


class InjectedFault(IOError):
    """The transient failure raised by the ``raise`` action."""


class _Spec:
    __slots__ = ("point", "action", "at", "times", "seconds", "exit_code",
                 "seed", "rng", "hits", "fired")

    def __init__(self, point, action="raise", at=1, times=1, seconds=3600.0,
                 exit_code=17, seed=0):
        if point not in INJECTION_POINTS:
            raise ValueError(f"unknown injection point {point!r}; one of "
                             f"{INJECTION_POINTS}")
        if action not in ("exit", "raise", "sigterm", "hang", "corrupt",
                          "yield"):
            raise ValueError(f"unknown injection action {action!r}")
        self.point = point
        self.action = action
        self.at = int(at)
        self.times = int(times)
        self.seconds = float(seconds)
        self.exit_code = int(exit_code)
        self.seed = int(seed)
        # yield draws: one RNG PER FIRING THREAD (keyed by thread name,
        # seeded from spec seed + name) — a shared stream would hand
        # draws to threads in OS-scheduling order, breaking the
        # reproduce-from-the-same-seed contract on multi-threaded seams
        self.rng = {}
        self.hits = 0
        self.fired = 0

    def yield_rng(self):
        name = threading.current_thread().name
        rng = self.rng.get(name)
        if rng is None:
            rng = self.rng.setdefault(name,
                                      random.Random(f"{self.seed}:{name}"))
        return rng


_armed = {}          # point -> list[_Spec]
_env_loaded = False


def injection_points():
    return INJECTION_POINTS


def configure_injection(specs):
    """Arm injection specs.  ``specs``: an env-var-style string, a dict, or
    a list of dicts.  Returns the armed spec objects (tests inspect
    ``.hits`` / ``.fired``)."""
    if isinstance(specs, str):
        specs = [_parse_one(s) for s in specs.split(";") if s.strip()]
    elif isinstance(specs, dict):
        specs = [specs]
    armed = []
    for spec in specs:
        s = _Spec(**spec)
        _armed.setdefault(s.point, []).append(s)
        armed.append(s)
    if armed:
        logger.warning("[fault] injection ARMED: "
                       + "; ".join(f"{s.point}:{s.action}@{s.at}"
                                   for s in armed))
    return armed


def _parse_one(text):
    out = {}
    for field in text.split(","):
        field = field.strip()
        if not field:
            continue
        k, _, v = field.partition("=")
        out[k.strip()] = v.strip()
    return out


def reset_injection():
    """Disarm everything (test teardown)."""
    _armed.clear()


def _load_env():
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    spec = os.environ.get(ENV_VAR)
    if spec:
        configure_injection(spec)


def active():
    _load_env()
    return bool(_armed)


_fire_lock = threading.Lock()

# fire observers: ``cb(point, action, hit_no)`` invoked for every spec
# that FIRES (before its action runs — exit/raise/hang must not lose the
# record).  The serving flight recorder registers one so chaos-drill
# post-mortems show exactly which seam fired before the fallout.
# Observers must be cheap and non-raising; exceptions are swallowed.
_observers = []


def add_fire_observer(cb):
    """Register ``cb(point, action, hit_no)``; returns ``cb`` (handy for
    symmetric :func:`remove_fire_observer` calls)."""
    _observers.append(cb)
    return cb


def remove_fire_observer(cb):
    try:
        _observers.remove(cb)
    except ValueError:
        pass


def _notify(point, spec):
    for cb in list(_observers):
        try:
            cb(point, spec.action, spec.hits)
        except Exception:                # noqa: BLE001 — observer only
            pass


def fire(point, path=None):
    """Hit an injection point.  No-op unless a spec is armed for it.
    Spec bookkeeping (``hits``/``fired``) is locked: the serving lock
    seams fire from several threads concurrently, and an unsynchronized
    check-then-act would let an ``at``/``times``-limited spec fire twice
    (or lose hits).  The action itself runs OUTSIDE the lock — it may
    sleep, raise or never return."""
    _load_env()
    specs = _armed.get(point)
    if not specs:
        return
    to_run = []
    with _fire_lock:
        for spec in specs:
            spec.hits += 1
            if spec.hits < spec.at:
                continue
            if spec.times and spec.fired >= spec.times:
                continue
            spec.fired += 1
            to_run.append(spec)
    for spec in to_run:
        _notify(point, spec)
        _execute(spec, path)


def _execute(spec, path):
    if spec.action == "yield":
        # fires on EVERY hit of a hot seam — no per-fire log spam, and
        # the sleep is a deterministic per-thread draw so a failing
        # interleaving reproduces from the same seed
        time.sleep(spec.yield_rng().random() * spec.seconds)
        return
    logger.warning(f"[fault] injection FIRING: {spec.point} -> "
                   f"{spec.action} (hit {spec.hits})")
    if spec.action == "exit":
        # os._exit: no atexit, no finally, no flush — a crash, not an exit
        os._exit(spec.exit_code)
    if spec.action == "raise":
        raise InjectedFault(
            f"injected transient fault at {spec.point} (hit {spec.hits})")
    if spec.action == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if spec.action == "hang":
        time.sleep(spec.seconds)
        return
    if spec.action == "corrupt":
        _corrupt_largest_file(path)
        return


def _corrupt_largest_file(path):
    """Flip bytes in the middle of the largest regular file under ``path``
    (a directory or a single file) — the bit-rot manifest verification
    exists to catch.  File size is unchanged, so only checksums notice."""
    if path is None:
        raise ValueError("corrupt action needs the seam to provide a path")
    target, size = None, -1
    if os.path.isfile(path):
        target, size = path, os.path.getsize(path)
    else:
        for dirpath, _d, filenames in os.walk(path):
            for name in filenames:
                if name == "MANIFEST.json":
                    continue
                p = os.path.join(dirpath, name)
                s = os.path.getsize(p)
                if s > size:
                    target, size = p, s
    if target is None or size <= 0:
        raise ValueError(f"corrupt action: no file to corrupt under {path}")
    with open(target, "r+b") as f:
        f.seek(size // 2)
        chunk = f.read(min(64, size - size // 2))
        f.seek(size // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))
    logger.warning(f"[fault] corrupted {min(64, size)} bytes of {target}")
