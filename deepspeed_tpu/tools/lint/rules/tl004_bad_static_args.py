"""TL004 — unhashable or array-valued static args.

``static_argnums``/``static_argnames`` hash their values into the
compilation-cache key.  A list/dict/set there raises at call time; an array
(or anything freshly constructed per call) silently RECOMPILES on every
step — the classic "why is every step 30 s" bug.  The rule flags:

* jit-wrapped functions whose static parameters default to mutable literals,
* call sites of a jitted name passing list/dict/set/array expressions in a
  static position.
"""

import ast

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule
from deepspeed_tpu.tools.lint.rules.tl002_missing_donation import (
    JIT_NAMES, jit_decorator_kwargs)

_ARRAY_CTORS = {"jnp.array", "jnp.asarray", "np.array", "np.asarray",
                "jnp.zeros", "jnp.ones", "jnp.arange", "np.zeros", "np.ones",
                "jax.numpy.array", "jax.numpy.asarray"}


def _static_spec(keywords):
    """(argnums, argnames) literal values from jit kwargs, or None."""
    nums, names = None, None
    for kw in keywords or []:
        if kw.arg == "static_argnums":
            nums = _int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            names = _str_tuple(kw.value)
    if nums is None and names is None:
        return None
    return nums or (), names or ()


def _int_tuple(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _str_tuple(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _bad_value(node):
    """Why this expression must not be a static arg, or None."""
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return "unhashable (list/dict/set)"
    if isinstance(node, ast.Call) and dotted_name(node.func) in _ARRAY_CTORS:
        return "an array (hashes by identity -> recompiles every call)"
    return None


@rule("TL004", "unhashable or array-valued static args")
def check(module):
    # (1) defaults of static params on @jit-decorated functions
    for fn in module.functions:
        keywords = jit_decorator_kwargs(fn.node)
        spec = _static_spec(keywords)
        if spec is None:
            continue
        nums, names = spec
        a = fn.node.args
        defaults = list(a.defaults)
        # align defaults with trailing positional params; indices count the
        # FULL signature (including self/cls) — that is what jax's
        # static_argnums refers to
        pos = [p.arg for p in (*a.posonlyargs, *a.args)]
        for i, d in enumerate(defaults):
            pname = pos[len(pos) - len(defaults) + i]
            idx = pos.index(pname)
            if (idx in nums or pname in names):
                why = _bad_value(d)
                if why:
                    yield Finding(
                        "TL004", module.path, d.lineno, d.col_offset,
                        f"static arg '{pname}' of jitted '{fn.name}' "
                        f"defaults to {why}")
    # (2) call sites of names bound to jit(..., static_argnums=...)
    static_of = {}          # bound name -> (nums, names)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if isinstance(v, ast.Call) and dotted_name(v.func) in JIT_NAMES:
            spec = _static_spec(v.keywords)
            if spec is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        static_of[tgt.id] = spec
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        spec = None
        if isinstance(callee, ast.Name) and callee.id in static_of:
            spec = static_of[callee.id]
        elif isinstance(callee, ast.Call) and \
                dotted_name(callee.func) in JIT_NAMES:
            spec = _static_spec(callee.keywords)   # jax.jit(f, ...)(args)
        if spec is None:
            continue
        nums, names = spec
        for i, arg in enumerate(node.args):
            if i in nums:
                why = _bad_value(arg)
                if why:
                    yield Finding(
                        "TL004", module.path, arg.lineno, arg.col_offset,
                        f"static arg {i} is {why}")
        for kw in node.keywords:
            if kw.arg in names:
                why = _bad_value(kw.value)
                if why:
                    yield Finding(
                        "TL004", module.path, kw.value.lineno,
                        kw.value.col_offset,
                        f"static arg '{kw.arg}' is {why}")
