"""File-level shard surgery for foreign (per-rank) checkpoint sets.

Reference parity: ``deepspeed/checkpoint/reshape_utils.py`` +
``reshape_meg_2d.py`` + ``reshape_3d_utils.py`` — merging and re-splitting
Megatron-style tensor-parallel shard files when the target TP degree differs
from the source.

Our own checkpoints never need this (Orbax stores are logically global), but
importing a TP-sharded external checkpoint — or exporting one for a
torch-based consumer — does.  TP placement follows the same column/row rules
the live framework uses (``runtime/zero/partition.py DEFAULT_TP_RULES``):
column-parallel weights split on the output dim, row-parallel on the input
dim.
"""

import re

import numpy as np


def partition_data(data, num_partitions):
    """Split a list into contiguous near-equal chunks (reference
    ``reshape_utils.py partition_data``)."""
    parts = []
    n = len(data)
    base, rem = divmod(n, num_partitions)
    start = 0
    for i in range(num_partitions):
        size = base + (1 if i < rem else 0)
        parts.append(data[start:start + size])
        start += size
    return parts


def merge_tp_shards(shards, dim):
    """Concatenate per-TP-rank arrays back into the full tensor."""
    if len(shards) == 1:
        return np.asarray(shards[0])
    return np.concatenate([np.asarray(s) for s in shards], axis=dim)


def split_tp_shards(array, degree, dim):
    """Split a full tensor into `degree` equal TP shards along `dim`."""
    array = np.asarray(array)
    if array.shape[dim] % degree != 0:
        raise ValueError(f"dim {dim} of shape {array.shape} not divisible "
                         f"by tp degree {degree}")
    return [np.ascontiguousarray(s) for s in np.split(array, degree, axis=dim)]


def reshape_tp(shards, target_degree, dim):
    """source-degree shards → target-degree shards along the same dim."""
    full = merge_tp_shards(shards, dim)
    return split_tp_shards(full, target_degree, dim)


# --------------------------------------------------------------------- #
# TP-dim classification by parameter name — DELEGATES to the live sharding
# rules (``runtime/zero/partition.py DEFAULT_TP_RULES``) so offline surgery
# and runtime placement agree by construction.
# --------------------------------------------------------------------- #
def infer_tp_dim(param_name, ndim, rules=None):
    """Which dim a parameter splits on for TP, or None if replicated.

    MUST agree with the runtime placement (``tp_spec_for`` in
    ``runtime/zero/partition.py``) — checkpoint surgery along any other axis
    silently corrupts resharded weights: column-parallel → output dim (last
    dim of a 2-D ``Dense`` kernel; the HEAD dim, ndim-2, of a ≥3-D
    ``DenseGeneral`` kernel), row-parallel → first (input) dim,
    embeddings → vocab dim 0.
    """
    if ndim < 2:
        return None
    from deepspeed_tpu.runtime.zero.partition import (is_expert_stacked,
                                                      tp_dim_for, tp_rule_kind)
    kind = tp_rule_kind(param_name.lower(), rules)
    if kind is None:
        return None
    dim = tp_dim_for(kind, ndim,
                     expert_stacked=is_expert_stacked(param_name, ndim))
    return dim if dim is not None and dim >= 0 else None


def reshape_flat_state_dict(flat, source_degree, target_degree, rules=None):
    """Reshape a {name: [shard_0..shard_{src-1}]} dict of TP shard lists into
    target-degree shard lists, keyed by the same names.  ``rules`` overrides
    the default name-classification rules (same format as
    ``DEFAULT_TP_RULES``) for foreign naming schemes."""
    out = {}
    for name, shards in flat.items():
        if len(shards) != source_degree:
            raise ValueError(f"{name}: expected {source_degree} shards, got "
                             f"{len(shards)}")
        ndim = np.asarray(shards[0]).ndim
        dim = infer_tp_dim(name, ndim, rules=rules)
        if dim is None:
            # Unclassified ⇒ must genuinely be replicated; a sharded param
            # that slipped past the name rules would otherwise lose data.
            for i, s in enumerate(shards[1:], start=1):
                if not np.array_equal(np.asarray(s), np.asarray(shards[0])):
                    raise ValueError(
                        f"{name}: shards 0 and {i} differ but no TP rule "
                        f"classifies this parameter; pass rules= with a "
                        f"pattern for it")
            out[name] = [np.asarray(shards[0])] * target_degree
        else:
            out[name] = reshape_tp(shards, target_degree, dim)
    return out
