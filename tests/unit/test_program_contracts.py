"""The program-contract lockfile gate (``tools/lint/contract.py`` +
``PROGRAMS.lock``).

Tier-1 regenerates every contract — primitive multiset, donation-alias
count, collective counts, byte-level comm budgets, abstract signatures —
from the REAL hot-path programs and the ``parallel/`` sharding plans, and
diffs them against the committed lockfile: a lost donation, a new host
callback, a surprise collective, a byte-volume regression, or a drifted
signature fails here with a readable per-program diff instead of
surfacing as an HBM cliff rounds later.

The mesh-scaling tables ({1,2,4,8} bytes/chip per plan) are consistency-
checked here for free; their full regen-and-diff compiles 12 extra plan
points (~2 min) and runs as the ``slow``-marked test at the bottom and as
``ds_lint --comm`` — this container's tier-1 wall-clock budget cannot
absorb the compiles."""

import json
import os
import re
import pathlib
import subprocess
import sys

import pytest

from deepspeed_tpu.tools.lint import comm_contract, contract, mem_contract

HERE = pathlib.Path(__file__).resolve().parent
REPO = HERE.parents[1]
LOCK = REPO / contract.LOCKFILE_NAME

# hot-path registry names covered by a locked program contract
_COVERED = {
    "runtime.train_step": "runtime.train_step",
    "runtime.apply_update": "runtime.apply_update",
    "inference.decode": "inference.decode",
    "inference.prefill_chunk": "inference.prefill_chunk",
    "serving.decode_step": "serving.decode_step",
    "serving.admit": "serving.admit",
    "serving.decode_step_paged": "serving.decode_step_paged",
    "serving.prefill_chunk_paged": "serving.prefill_chunk_paged",
    "serving.admit_paged": "serving.admit_paged",
    "serving.spec_propose": "serving.spec_propose",
    "serving.spec_verify": "serving.spec_verify",
    "serving.spec_verify_paged": "serving.spec_verify_paged",
    "serving.spec_draft_prefill": "serving.spec_draft_prefill",
    "serving.spec_draft_admit": "serving.spec_draft_admit",
    "hybrid.rollout_generate": "hybrid.rollout",
}
# host-side orchestrators / sub-programs of a locked contract: no single
# stable jitted program of their own.  A NEW @hot_path lands in neither
# set and fails test_lockfile_covers_registered_hot_paths until its
# contract exists (or it is consciously exempted here).
_ORCHESTRATORS = {
    "runtime.train_batch",      # host loop around runtime.train_step
    "runtime.step",             # 3-call path orchestrator
    "runtime.forward",          # 3-call path orchestrator
    "runtime.fwd_bwd",          # sub-program of the fused/3-call step
    "runtime.fwd_bwd_acc",      # gas>1 variant of fwd_bwd
    "inference.generate",       # host wrapper around inference.decode
    "hybrid.rollout_cast",      # once-per-optimizer-step view builder
    # the HTTP front end's scheduler-owner loop drives the engine's
    # locked serving programs and must never mint one of its own — the
    # e2e zero-new-executables test (test_serving_frontend.py) proves it
    "serving.http_frontend_loop",
}


def _registered_hot_path_names():
    """Static sweep: every ``@hot_path("name")`` in the package source."""
    names = set()
    pkg = REPO / "deepspeed_tpu"
    for path in pkg.rglob("*.py"):
        for m in re.finditer(r'@hot_path\(\s*"([^"]+)"', path.read_text()):
            names.add(m.group(1))
    return names


@pytest.fixture(scope="module")
def lock():
    assert LOCK.exists(), \
        f"{LOCK} missing — generate with bin/ds_lint --contracts --update"
    return json.loads(LOCK.read_text())


def test_lockfile_covers_registered_hot_paths(lock):
    """Every @hot_path in the package is either contract-locked or a
    documented host orchestrator — a new hot path must add its contract
    (ds_lint --contracts --update) or a conscious exemption above."""
    registered = _registered_hot_path_names()
    registered.discard("name")           # the docstring example in hotpath.py
    unknown = registered - set(_COVERED) - _ORCHESTRATORS
    assert not unknown, \
        f"@hot_path entry point(s) with no contract in {LOCK.name}: " \
        f"{sorted(unknown)}"
    programs = lock["programs"]
    missing = {v for v in _COVERED.values()} - set(programs)
    assert not missing, f"contracts missing from {LOCK.name}: {missing}"
    # the paged serving programs are explicitly part of the acceptance bar
    for name in ("serving.decode_step_paged", "serving.prefill_chunk_paged",
                 "serving.admit_paged"):
        assert name in programs


def test_lockfile_programs_have_sound_contracts(lock):
    """Locked invariants that must hold regardless of drift: no host
    callbacks anywhere, and donated programs actually alias."""
    for name, c in lock["programs"].items():
        assert c["host_callbacks"] == 0, name
        if c["donation"]["declared"]:
            floor = c["donation"]["min_aliased"] or 1
            assert c["donation"]["aliased"] >= floor, (name, c["donation"])


@pytest.mark.parametrize("builder_name", contract.program_names())
def test_program_contract_matches_lockfile(lock, builder_name):
    """The gate: regenerate this program's contract and diff it against
    the committed lockfile — any mismatch fails with the per-program
    field diff."""
    name, fresh = contract.build_program_contract(builder_name)
    locked = lock["programs"].get(name)
    assert locked is not None, \
        f"{name} not in {LOCK.name} — run ds_lint --contracts --update"
    diff = contract.diff_program(name, locked, fresh)
    assert not diff, "contract break (regenerate-and-diff):\n" + \
        "\n".join(diff)


@pytest.mark.parametrize("plan_name",
                         [b.__name__ for b in __import__(
                             "deepspeed_tpu.parallel.plans",
                             fromlist=["PLAN_BUILDERS"]).PLAN_BUILDERS])
def test_collective_schedule_matches_lockfile(lock, plan_name):
    """The static collective-schedule gate: the sharding plan's compiled
    HLO must carry exactly the locked collective counts (and satisfy the
    plan's semantic invariants) — MULTICHIP dry-run totals are locked,
    not re-measured."""
    name, fresh = contract.build_plan_contract(plan_name)
    problems = contract.validate_plan_contract(fresh)
    assert not problems, f"{name}: {problems}"
    locked = lock["collective_schedules"].get(name)
    assert locked is not None, \
        f"{name} not in {LOCK.name} — run ds_lint --contracts --update"
    diff = contract.diff_program(name, locked, fresh)
    assert not diff, "collective-schedule break:\n" + "\n".join(diff)


# ------------------------------------------------------------------ #
# The gate actually fails, readably, on synthetic contract breaks
# ------------------------------------------------------------------ #
def _synthetic_donating_ep(donate=True):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.tools.lint.entry_points import EntryPoint

    def update(params, cache):
        return jax.tree.map(lambda c: c + 1.0, cache)

    fn = jax.jit(update, donate_argnums=(1,)) if donate else jax.jit(update)
    args = ({"w": jnp.ones((4, 4))}, {"k": jnp.zeros((2, 8))})
    return EntryPoint("synthetic.update", fn, args, expect_donation=donate)


def test_dropped_donation_fails_with_readable_diff():
    """Acceptance: a synthetic contract break (the exact PR 5 bug class —
    a donation silently dropped) fails the diff with a per-program,
    per-field message."""
    locked = contract.contract_of_entry_point(_synthetic_donating_ep(True))
    fresh = contract.contract_of_entry_point(_synthetic_donating_ep(False))
    assert locked["donation"]["aliased"] >= 1
    assert fresh["donation"]["aliased"] == 0
    diff = contract.diff_program("synthetic.update", locked, fresh)
    text = "\n".join(diff)
    assert diff and diff[0] == "synthetic.update:"
    assert "donation" in text and "LOST donation" in text


def test_surprise_collective_and_primitive_drift_diff():
    """Tampered lockfile entries produce readable field-level diffs."""
    locked = {"kind": "collective_schedule", "mesh": {"tp": 2},
              "collectives": {"all-gather": 35, "all-reduce": 39},
              "expect": ["all-gather"], "reduction": True}
    fresh = dict(locked, collectives={"all-gather": 37, "all-reduce": 39,
                                      "all-to-all": 2})
    diff = contract.diff_program("parallel.fake", locked, fresh)
    text = "\n".join(diff)
    assert "collectives.all-gather: 35 -> 37" in text
    assert "collectives.all-to-all: 0 -> 2" in text

    # plan semantics (expect / reduction) are part of the schedule contract
    weakened = dict(locked, expect=[], reduction=False)
    text = "\n".join(contract.diff_program("parallel.fake", locked, weakened))
    assert "expect: ['all-gather'] -> []" in text
    assert "reduction: True -> False" in text

    p_locked = {"kind": "program", "primitives": {"scan": 1, "add": 3},
                "primitives_sha256": "aaaa", "host_callbacks": 0,
                "collectives": {}, "donation": {"declared": True,
                                                "aliased": 2,
                                                "min_aliased": 0},
                "in_avals": ["f32[2]"], "out_avals": ["f32[2]"]}
    p_fresh = dict(p_locked, primitives={"scan": 1, "add": 3,
                                         "pure_callback": 1},
                   primitives_sha256="bbbb", host_callbacks=1)
    diff = contract.diff_program("inference.fake", p_locked, p_fresh)
    text = "\n".join(diff)
    assert "primitives.pure_callback: 0 -> 1" in text
    assert "host_callbacks: 0 -> 1" in text


def test_diff_lockfiles_reports_added_and_removed():
    a = {"programs": {"x": {"kind": "program"}}, "collective_schedules": {}}
    b = {"programs": {"y": {"kind": "program"}}, "collective_schedules": {}}
    text = "\n".join(contract.diff_lockfiles(a, b))
    assert "x: locked but no longer extracted" in text
    assert "y: not in PROGRAMS.lock" in text


def test_schedule_diff_prints_old_and_new_side_by_side():
    """A schedule change prints BOTH whole schedules, not only field
    paths — a reviewer reads 'what was the schedule, what is it now' in
    two lines (counts + bytes when budgeted)."""
    locked = {"kind": "collective_schedule", "mesh": {"tp": 2}, "world": 8,
              "collectives": {"all-gather": 40, "all-reduce": 70},
              "comm": {"all-gather": {"count": 40,
                                      "bytes_per_step": 2155872},
                       "all-reduce": {"count": 70,
                                      "bytes_per_step": 1048576}},
              "expect": [], "reduction": True}
    fresh = dict(locked,
                 collectives={"all-gather": 42, "all-reduce": 70},
                 comm={"all-gather": {"count": 42,
                                      "bytes_per_step": 70254592},
                       "all-reduce": {"count": 70,
                                      "bytes_per_step": 1048576}})
    diff = contract.diff_program("parallel.fake", locked, fresh)
    text = "\n".join(diff)
    assert "collectives.all-gather: 40 -> 42" in text
    # the byte story is the reviewable half of the regression
    assert "all-gather bytes: 2.1MB -> 67.0MB per step" in text
    side = [ln for ln in diff if "schedule:" in ln or ln.strip()
            .startswith("->")]
    assert len(side) == 2, diff
    assert "all-gather x40 (2.1MB)" in side[0]
    assert "all-gather x42 (67.0MB)" in side[1]


# ------------------------------------------------------------------ #
# Comm budgets + mesh-scaling tables (the byte-level contract layer)
# ------------------------------------------------------------------ #
def test_lockfile_carries_comm_budgets(lock):
    """Every locked program carries a comm budget; today's single-chip
    programs must budget ZERO bytes (a collective appearing in one is a
    contract break, not a surprise), and every sharding-plan schedule
    budgets every counted collective with nonzero bytes and matching
    instance counts."""
    for name, c in lock["programs"].items():
        assert "comm" in c, f"{name}: no comm budget locked"
        assert c["comm"] == {}, \
            f"{name}: single-chip program budgets {c['comm']}"
    for name, c in lock["collective_schedules"].items():
        assert c["world"] == 8, name
        counts = c["collectives"]
        budget = c["comm"]
        assert set(budget) == set(counts), (name, budget, counts)
        for op, n in counts.items():
            assert budget[op]["count"] == n, (name, op)
            assert budget[op]["bytes_per_step"] > 0, (name, op)


def test_lockfile_scaling_tables_are_sound(lock):
    """The locked {1,2,4,8} tables' internal invariants, checked with no
    compiles: all four plans present with all four mesh points, one chip
    moves zero bytes, the top row equals the locked schedule's budget
    (same compile), and every growing collective carries a declared
    reason (the prover's growth gate on the committed artifact)."""
    scaling = lock["mesh_scaling"]
    assert set(scaling) == set(lock["collective_schedules"])
    for name, sc in scaling.items():
        worlds = [row["world"] for row in sc["points"]]
        assert worlds == [1, 2, 4, 8], (name, worlds)
        assert sc["points"][0]["bytes_per_chip_total"] == 0, \
            f"{name}: phantom collective traffic on a mesh of one"
        top = sc["points"][-1]
        sched = lock["collective_schedules"][name]
        assert top["collectives"] == sched["comm"], \
            f"{name}: scaling table top row disagrees with the locked " \
            f"schedule budget"
        assert top["mesh"] == sched["mesh"], name
        problems = comm_contract.validate_scaling_contract(name, sc)
        assert not problems, "\n".join(problems)
        # the growth flags themselves are locked: every flagged op is
        # declared, and nothing is declared "just in case" for ops that
        # never appear in the table
        seen_ops = set()
        for row in sc["points"]:
            seen_ops |= set(row["bytes_per_chip"])
        for op in sc["allowed_growth"]:
            assert op in seen_ops, \
                f"{name}: allowed_growth for {op!r} which never appears"


def test_growth_prover_flags_synthetic_replication():
    """Unit acceptance for the scaling prover: a per-chip trajectory that
    GROWS (the replicated-tensor smell) is flagged with a readable
    transition trail and fails validation unless declared."""
    table = [
        comm_contract.scaling_entry(1, {"tp": 1}, {}),
        comm_contract.scaling_entry(
            2, {"tp": 2},
            {"all-gather": {"count": 4, "bytes_per_step": 4 * 2048}}),
        comm_contract.scaling_entry(
            4, {"tp": 4},
            {"all-gather": {"count": 4, "bytes_per_step": 4 * 16384}}),
    ]
    flags = comm_contract.growth_flags(table)
    assert "all-gather" in flags
    assert "2->4" in flags["all-gather"][0]
    contract_ = {"kind": "mesh_scaling", "points": table,
                 "grows_with_mesh": flags, "allowed_growth": {}}
    problems = comm_contract.validate_scaling_contract("fixture.bad",
                                                       contract_)
    assert problems and "GROWS with mesh size" in problems[0]
    assert "replicated-tensor smell" in problems[0]
    # a declared reason clears it
    contract_["allowed_growth"] = {"all-gather": "weak-scaling batch"}
    assert not comm_contract.validate_scaling_contract("fixture.ok",
                                                       contract_)
    # flat-or-falling trajectories stay clean
    table[2]["bytes_per_chip"]["all-gather"] = 4096
    assert not comm_contract.growth_flags(table)


def test_scaling_diff_renders_bytes_per_chip():
    """A scaling-table drift diffs readably, per mesh point, in bytes."""
    a = {"points": [comm_contract.scaling_entry(
        2, {"tp": 2}, {"all-gather": {"count": 1,
                                      "bytes_per_step": 2 * 1024}})],
        "grows_with_mesh": {}, "allowed_growth": {}}
    b = {"points": [comm_contract.scaling_entry(
        2, {"tp": 2}, {"all-gather": {"count": 1,
                                      "bytes_per_step": 2 * 1048576}})],
        "grows_with_mesh": {}, "allowed_growth": {}}
    diff = comm_contract.diff_scaling("parallel.fake", a, b)
    text = "\n".join(diff)
    assert "mesh 2 all-gather: 1.0KB -> 1.0MB per chip" in text
    # a drift confined to a declared growth REASON renders the actual
    # strings, not two identical key lists
    c = dict(a, allowed_growth={"all-gather": "old reason"})
    d = dict(a, allowed_growth={"all-gather": "new reason"})
    text = "\n".join(comm_contract.diff_scaling("parallel.fake", c, d))
    assert "allowed_growth[all-gather]: 'old reason' -> 'new reason'" \
        in text
    # an instance-count drift whose bytes (and hence the truncated
    # per-chip number) are unchanged still diffs — the locked per-point
    # schedule entries are compared, not only bytes_per_chip
    e = {"points": [comm_contract.scaling_entry(
        2, {"tp": 2}, {"all-gather": {"count": 2,
                                      "bytes_per_step": 2 * 1024}})],
        "grows_with_mesh": {}, "allowed_growth": {}}
    text = "\n".join(comm_contract.diff_scaling("parallel.fake", a, e))
    assert "mesh 2 all-gather schedule: 1x/2.0KB -> 2x/2.0KB" in text


def test_hlo_comm_parser_formats():
    """The HLO parser handles every replica-group/operand format XLA
    emits: explicit and iota groups, tuple-shaped variadic all-to-all,
    async -start (the -done halves never double-count), permute pair
    lists, and group-free instructions spanning the world."""
    txt = """
%ag = f32[4,8]{1,0} all-gather(f32[2,8]{1,0} %c), replica_groups={{0,1},{2,3},{4,5},{6,7}}, dimensions={0}, metadata={op_name="x{y}"}
%ar = f32[4,16]{1,0} all-reduce-start(f32[4,16]{1,0} %d), replica_groups=[4,2]<=[8], to_apply=%region
%ard = f32[4,16]{1,0} all-reduce-done(f32[4,16]{1,0} %ar)
%a2a = (f32[2,8]{1,0}, f32[2,8]{1,0}) all-to-all(f32[2,8]{1,0} %p, f32[2,8]{1,0} %q), replica_groups={{0,1},{2,3}}
%cp = f32[4,16]{1,0} collective-permute(f32[4,16]{1,0} %e), source_target_pairs={{0,2},{2,4},{4,6},{6,0}}
%bf = bf16[8]{0} all-reduce(bf16[8]{0} %g), replica_groups={}
%pm = pred[4,16]{1,0} all-reduce(pred[4,16]{1,0} %m), replica_groups=[4,2]<=[8]
"""
    comm = comm_contract.parse_hlo_comm(txt, 8)
    assert comm["all-gather"] == {"count": 1, "bytes_per_step": 512}
    # pred is the one digit-free dtype token: 64 bool bytes x 2 x 4
    assert comm["all-reduce"] == {"count": 3,
                                  "bytes_per_step": 2048 + 128 + 512}
    assert comm["all-to-all"] == {"count": 1, "bytes_per_step": 512}
    assert comm["collective-permute"] == {"count": 1,
                                          "bytes_per_step": 1024}


# ------------------------------------------------------------------ #
# Memory/FLOP contracts (PROGRAMS.lock format 3, tools/lint/
# mem_contract.py) — artifact invariants + the synthetic-break proof
# run fast (no hot-path compiles); the per-program regen-and-diff is
# slow-marked (16 compiles) like the mesh-scaling sweep
# ------------------------------------------------------------------ #
def test_lockfile_format3_carries_memory_and_cost(lock):
    """Every locked program AND plan carries a memory_analysis byte
    footprint and a cost_analysis budget, internally consistent (the
    no-compile half of the acceptance bar)."""
    assert lock["_meta"]["format"] >= 3
    for section in ("programs", "collective_schedules"):
        for name, c in lock[section].items():
            mem, cost = c.get("memory"), c.get("cost")
            assert mem and cost, f"{name}: no memory/cost contract"
            for field in mem_contract.MEM_FIELDS + ("total_bytes",):
                assert isinstance(mem.get(field), int), (name, field)
            assert mem["total_bytes"] == (
                mem["argument_size_in_bytes"]
                + mem["output_size_in_bytes"]
                + mem["temp_size_in_bytes"]
                - mem["alias_size_in_bytes"]), name
            assert cost["flops"] > 0, name
            assert cost["bytes_accessed"] > 0, name
            assert not mem_contract.validate_memory_contract(name, c), \
                mem_contract.validate_memory_contract(name, c)
    # donated programs buy real bytes: every program whose donation
    # aliases buffers aliases >0 bytes in the memory contract
    for name, c in lock["programs"].items():
        if c["donation"]["declared"] and c["donation"]["aliased"]:
            assert c["memory"]["alias_size_in_bytes"] > 0, name


def test_memory_diff_tolerance_band():
    """Within-tolerance drift is silent (compiler noise across patch
    releases must not flip the gate); beyond it, the byte story
    renders."""
    base = {"memory": {"argument_size_in_bytes": 1 << 20,
                       "output_size_in_bytes": 1 << 20,
                       "temp_size_in_bytes": 100 * 1024,
                       "alias_size_in_bytes": 1 << 20,
                       "generated_code_size_in_bytes": 0,
                       "total_bytes": (1 << 20) + 100 * 1024},
            "cost": {"flops": 10 ** 9, "bytes_accessed": 10 ** 8}}
    within = json.loads(json.dumps(base))
    within["memory"]["temp_size_in_bytes"] += 1024      # ~1% < 2%
    assert mem_contract.diff_memory("p", base, within) == []
    beyond = json.loads(json.dumps(base))
    beyond["memory"]["temp_size_in_bytes"] = 612 * 1024
    lines = mem_contract.diff_memory("p", base, beyond)
    text = "\n".join(lines)
    assert "temp HBM: 100.0KB -> 612.0KB" in text
    assert "MEMORY GROWTH beyond tolerance" in text
    # cost drift diffs too
    slower = json.loads(json.dumps(base))
    slower["cost"]["flops"] = 2 * 10 ** 9
    assert any("flops" in ln for ln in
               mem_contract.diff_memory("p", base, slower))


def _synthetic_mem_ep(donate=True):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.tools.lint.entry_points import EntryPoint

    def update(params, cache):
        return jax.tree.map(lambda c: c + 1.0, cache)

    fn = jax.jit(update, donate_argnums=(1,)) if donate else jax.jit(update)
    args = ({"w": jnp.ones((4, 4))}, {"k": jnp.zeros((128, 1024))})
    return EntryPoint("synthetic.update", fn, args, expect_donation=donate)


def test_dropped_donation_memory_break_fails_readably():
    """The acceptance synthetic break: dropping a donation makes the
    aliased bytes vanish and the live total jump by the whole donated
    buffer — the diff renders the byte story, and the update-time
    growth ratchet REFUSES the regression unless declared."""
    locked = contract.contract_of_entry_point(_synthetic_mem_ep(True),
                                              with_memory=True)
    fresh = contract.contract_of_entry_point(_synthetic_mem_ep(False),
                                             with_memory=True)
    assert locked["memory"]["alias_size_in_bytes"] > 0
    assert fresh["memory"]["alias_size_in_bytes"] == 0
    assert fresh["memory"]["total_bytes"] \
        > locked["memory"]["total_bytes"]
    diff = contract.diff_program("synthetic.update", locked, fresh)
    text = "\n".join(diff)
    assert diff and diff[0] == "synthetic.update:"
    assert "donated-alias HBM" in text and "live HBM total" in text
    assert "MEMORY GROWTH beyond tolerance" in text
    problems = mem_contract.growth_problems("synthetic.update", locked,
                                            fresh)
    assert problems and "GROWS" in problems[0] \
        and "cannot land silently" in problems[0]
    # a declared reason clears the ratchet (but never the lock diff)
    assert not mem_contract.growth_problems(
        "synthetic.update", locked, fresh,
        declared={"synthetic.update": "intentional double-buffer"})
    # shrinkage diffs (regen to claim the win) but never trips growth
    assert not mem_contract.growth_problems("synthetic.update", fresh,
                                            locked)
    # the FAST gate regenerates without memory: the same locked
    # contract diffs clean against a fresh side with no memory section
    no_mem = contract.contract_of_entry_point(_synthetic_mem_ep(True))
    assert "memory" not in no_mem
    assert contract.diff_program("synthetic.update", locked, no_mem) \
        == []


def test_mem_gate_unknown_name_fails_not_green():
    """A misspelled program name must NEVER exit 0 having checked
    nothing — the filtered sweep reports unknown names as a failure
    (and, thanks to the static builder->program map, without paying a
    single engine build, which is what keeps this test fast)."""
    ok, lines = mem_contract.check_memory_against_lockfile(
        names={"serving.decode_stpe"})
    assert not ok
    text = "\n".join(lines)
    assert "unknown program name" in text
    assert "serving.decode_stpe" in text
    assert "serving.decode_step" in text          # the known list helps


def test_builder_program_map_is_complete():
    """Every registered builder appears in the static map (the
    cross-check against what each builder actually constructs runs in
    the slow regen test and in every --mem sweep)."""
    from deepspeed_tpu.tools.lint import entry_points
    assert set(entry_points.BUILDER_PROGRAMS) \
        == {b.__name__ for b in entry_points.BUILDERS}


@pytest.mark.slow
@pytest.mark.parametrize("builder_name", contract.program_names())
def test_program_memory_contract_matches_lockfile(lock, builder_name):
    """The full memory regen-and-diff of one program: compile it and
    hold its byte footprint + cost budget against the committed lock
    within tolerance.  ``slow``: one compile per program (the PR 14
    budget discipline — tier-1's wall clock cannot absorb 16 compiles);
    run via ``ds_lint --mem`` or ``-m slow``."""
    name, fresh = contract.build_program_contract(builder_name,
                                                  with_memory=True)
    from deepspeed_tpu.tools.lint import entry_points
    assert entry_points.BUILDER_PROGRAMS[builder_name] == name, \
        "builder->program map drifted — name-filtered --mem sweeps " \
        "would skip the wrong program"
    locked = lock["programs"].get(name)
    assert locked is not None, name
    diff = mem_contract.diff_memory(name, locked, fresh)
    assert not diff, f"memory-contract break for {name}:\n" + \
        "\n".join(diff)
    assert not mem_contract.growth_problems(name, locked, fresh)


@pytest.mark.slow
def test_ds_lint_mem_cli_exits_1_on_memory_break(tmp_path):
    """Acceptance: ``ds_lint --mem`` exits 1 from the CLI on a memory
    break, with the byte story on stdout.  A tampered lockfile (the
    locked temp bytes shrunk 8x, so the real program reads as an 8x
    regression) drives the real subprocess gate on one program."""
    tampered = json.loads(LOCK.read_text())
    m = tampered["programs"]["serving.decode_step"]["memory"]
    m["temp_size_in_bytes"] //= 8
    m["total_bytes"] = (m["argument_size_in_bytes"]
                        + m["output_size_in_bytes"]
                        + m["temp_size_in_bytes"]
                        - m["alias_size_in_bytes"])
    bad = tmp_path / "PROGRAMS.tampered.lock"
    bad.write_text(json.dumps(tampered))
    env = dict(os.environ, DSTPU_MEM_LOCKFILE=str(bad),
               JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.tools.lint", "--mem",
         "serving.decode_step"],
        capture_output=True, text=True, env=env, cwd=str(REPO),
        timeout=900)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "MEMORY-CONTRACT BREAK" in proc.stdout
    assert "temp HBM" in proc.stdout
    assert "GROWS" in proc.stdout
    # and the untampered lock answers 0 for the same program
    env.pop("DSTPU_MEM_LOCKFILE")
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.tools.lint", "--mem",
         "serving.decode_step"],
        capture_output=True, text=True, env=env, cwd=str(REPO),
        timeout=900)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


@pytest.mark.slow
@pytest.mark.parametrize("plan_name",
                         [b.__name__ for b in __import__(
                             "deepspeed_tpu.parallel.plans",
                             fromlist=["PLAN_BUILDERS"]).PLAN_BUILDERS])
def test_mesh_scaling_matches_lockfile(lock, plan_name):
    """The full regen-and-diff of one plan's scaling table: compile the
    scaled-down mesh points {1,2,4} (the 8-point is derived from the
    locked schedule, whose own fresh compile is proven by
    test_collective_schedule_matches_lockfile), then validate growth and
    diff per chip.  ``slow``: three engine compiles per plan; run via
    ``ds_lint --comm`` or ``-m slow``."""
    sched_name = f"parallel.{plan_name}"
    name, fresh = contract.build_plan_scaling_contract(
        plan_name, full_contract=lock["collective_schedules"][sched_name])
    problems = comm_contract.validate_scaling_contract(name, fresh)
    assert not problems, "\n".join(problems)
    locked = lock["mesh_scaling"].get(name)
    assert locked is not None, \
        f"{name} not in {LOCK.name} — run ds_lint --contracts --update"
    diff = comm_contract.diff_scaling(name, locked, fresh)
    assert not diff, "mesh-scaling break:\n" + "\n".join(diff)
