"""Atomic durable filesystem primitives for the checkpoint protocol.

Every mutation of checkpoint state goes through these three idioms:

* **atomic file publish** — write to ``<name>.tmp.<pid>`` in the same
  directory, fsync the file, ``os.replace`` onto the final name, fsync the
  directory.  A crash at any instruction leaves either the old file or the
  new file, never a truncated hybrid (the seed's in-place ``latest``
  truncate-then-write bricked resume when killed between the two).
* **atomic directory publish** — stage everything under ``<tag>.tmp``,
  fsync the payload, ``os.rename`` to ``<tag>``, fsync the parent.  POSIX
  rename is atomic on one filesystem; a crash leaves only a ``.tmp``
  orphan that recovery ignores and GC removes.
* **recursive fsync** — flush file data AND directory entries; a rename is
  only crash-durable once the parent directory entry is on disk.
"""

import os

from deepspeed_tpu.utils.logging import logger


def fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path):
    """fsync a directory entry (no-op on filesystems that refuse O_RDONLY
    dir fds — e.g. some FUSE mounts — where rename durability is the
    mount's problem, not ours)."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError as e:
        logger.debug(f"fsync_dir({path}) skipped: {e}")
        return
    try:
        os.fsync(fd)
    except OSError as e:
        logger.debug(f"fsync_dir({path}) failed: {e}")
    finally:
        os.close(fd)


def fsync_tree(root):
    """fsync every regular file under ``root``, then every directory
    bottom-up — the durability barrier before an atomic rename publish."""
    for dirpath, _dirnames, filenames in os.walk(root, topdown=False):
        for name in filenames:
            p = os.path.join(dirpath, name)
            if os.path.isfile(p) and not os.path.islink(p):
                fsync_file(p)
        fsync_dir(dirpath)


def atomic_write_bytes(path, data: bytes):
    """Publish ``data`` at ``path`` atomically and durably."""
    path = os.path.abspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))


def atomic_write_text(path, text: str):
    atomic_write_bytes(path, text.encode("utf-8"))


def atomic_publish_dir(tmp_dir, final_dir):
    """Promote a fully-written staging directory to its final name.  The
    payload is fsynced first, so after the rename lands the checkpoint is
    durable; if ``final_dir`` already exists (re-save of the same tag) it
    is moved aside and removed only after the new version is in place."""
    import shutil
    fsync_tree(tmp_dir)
    parent = os.path.dirname(os.path.abspath(final_dir))
    backup = None
    if os.path.isdir(final_dir):
        backup = f"{final_dir}.old.{os.getpid()}"
        os.rename(final_dir, backup)
    os.rename(tmp_dir, final_dir)
    fsync_dir(parent)
    if backup is not None:
        shutil.rmtree(backup, ignore_errors=True)
