"""``ds_lint --stats-docs`` — the serving metric surface must not drift
undocumented (``docs/observability.md``).

Statically (never importing the code under analysis — the linter
discipline every other gate here follows) collects:

* every ``stats`` counter key the serving engine touches
  (``inference/serving/engine.py``: the ``self.stats = {...}`` literal,
  ``stats.update({...})`` calls and ``stats["key"]`` /
  ``stats.get("key")`` accesses), and
* every ``/metrics`` series name the HTTP front end exports
  (``frontend/transport.py``: ``gauge("name", ...)`` first arguments
  prefixed ``dstpu_serving_``, plus full ``dstpu_*`` string literals),
  the histogram families ``monitor/trace.py`` declares in its
  ``HISTOGRAM_SERIES`` literal, and the device-memory families
  ``monitor/memwatch.py`` declares in its ``MEMORY_SERIES`` literal,

then asserts each appears as a backticked token in the observability
doc's tables.  Exit 1 lists what is missing; wired into tier-1 via
``tests/unit/test_tpu_lint.py`` so a new counter or series cannot land
without its documentation row.
"""

import ast
import os
import re
import sys

_PKG = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
ENGINE_PY = os.path.join(_PKG, "inference", "serving", "engine.py")
TRANSPORT_PY = os.path.join(_PKG, "inference", "serving", "frontend",
                            "transport.py")
TRACE_PY = os.path.join(_PKG, "monitor", "trace.py")
MEMWATCH_PY = os.path.join(_PKG, "monitor", "memwatch.py")
DOC_MD = os.path.join(os.path.dirname(_PKG), "docs", "observability.md")


def _parse(path):
    with open(path, encoding="utf-8") as f:
        return ast.parse(f.read(), filename=path)


def _is_stats_attr(node):
    """True for ``<anything>.stats`` attribute nodes (``self.stats``,
    ``srv.stats``)."""
    return isinstance(node, ast.Attribute) and node.attr == "stats"


def _dict_str_keys(node):
    if not isinstance(node, ast.Dict):
        return
    for k in node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            yield k.value


def collect_stats_keys(engine_path=ENGINE_PY):
    """Every string key the engine reads/writes on a ``stats`` dict."""
    keys = set()
    for node in ast.walk(_parse(engine_path)):
        # self.stats = {...} / self.stats.update({...})
        if isinstance(node, ast.Assign) \
                and any(_is_stats_attr(t) for t in node.targets):
            keys.update(_dict_str_keys(node.value))
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("update", "get", "setdefault") \
                and _is_stats_attr(node.func.value):
            for arg in node.args:
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    keys.add(arg.value)
                keys.update(_dict_str_keys(arg))
        # stats["key"] subscripts (reads and writes)
        if isinstance(node, ast.Subscript) and _is_stats_attr(node.value):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                keys.add(sl.value)
    return keys


def collect_metric_series(transport_path=TRANSPORT_PY,
                          trace_path=TRACE_PY,
                          memwatch_path=MEMWATCH_PY):
    """Every ``/metrics`` series name: ``gauge("x", ...)`` calls (the
    ``dstpu_serving_`` prefix is applied by the helper), whole
    ``dstpu_*`` string literals, and the ``HISTOGRAM_SERIES`` /
    ``MEMORY_SERIES`` tuples the trace and memwatch modules declare as
    pure literals."""
    series = set()
    for node in ast.walk(_parse(transport_path)):
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else \
                fn.attr if isinstance(fn, ast.Attribute) else None
            if name == "gauge" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                series.add(f"dstpu_serving_{node.args[0].value}")
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            v = node.value
            # whole series names only — skip prefix fragments from
            # f-strings (they end with the joining underscore)
            if v.startswith("dstpu_") and not v.endswith("_") \
                    and re.fullmatch(r"[a-z0-9_]+", v):
                series.add(v)
    for path, literal in ((trace_path, "HISTOGRAM_SERIES"),
                          (memwatch_path, "MEMORY_SERIES")):
        for node in _parse(path).body:
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == literal
                            for t in node.targets):
                series.update(ast.literal_eval(node.value))
    return series


def doc_tokens(doc_path=DOC_MD):
    """Backticked tokens in the observability doc (the metric tables)."""
    try:
        with open(doc_path, encoding="utf-8") as f:
            text = f.read()
    except FileNotFoundError:
        return set()
    return set(re.findall(r"`([^`\n]+)`", text))


def main(doc_path=DOC_MD, engine_path=ENGINE_PY,
         transport_path=TRANSPORT_PY, trace_path=TRACE_PY):
    stats = collect_stats_keys(engine_path)
    series = collect_metric_series(transport_path, trace_path)
    tokens = doc_tokens(doc_path)
    missing_stats = sorted(k for k in stats if k not in tokens)
    missing_series = sorted(s for s in series if s not in tokens)
    if not stats or not series:
        print("tpu-lint[stats-docs]: error: collected "
              f"{len(stats)} stats keys / {len(series)} series — the "
              "collector lost its sources (engine/transport/trace "
              "moved?)", file=sys.stderr)
        return 2
    if missing_stats or missing_series:
        for k in missing_stats:
            print(f"stats-docs: stats[{k!r}] is exported by the serving "
                  f"engine but undocumented in {os.path.relpath(doc_path)}")
        for s in missing_series:
            print(f"stats-docs: /metrics series {s!r} is exported but "
                  f"undocumented in {os.path.relpath(doc_path)}")
        print(f"tpu-lint[stats-docs]: {len(missing_stats)} stats key(s) "
              f"+ {len(missing_series)} series missing from the docs "
              f"table — add rows to docs/observability.md")
        return 1
    print(f"tpu-lint[stats-docs]: OK — {len(stats)} stats keys and "
          f"{len(series)} /metrics series all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
