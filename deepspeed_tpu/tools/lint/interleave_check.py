"""Interleaving stress harness — the runtime prover paired with the
TL008/TL009 lock-discipline rules (the same "rule + prover" pairing
TL006 ships with its retrace counter).

The static rules prove every DECLARED access site is lock-correct; this
harness proves the contract actually holds under adversarial thread
schedules: it drives concurrent ``submit`` / ``cancel`` / ``status`` /
``token_events`` / metrics-snapshot traffic against a stepping scheduler
thread while RANDOMIZED yields (the fault registry's ``yield`` action,
deterministic per seed) are injected at the named lock seams —
``serving.pre_step_lock``, ``serving.pre_submit_lock``,
``serving.pre_cancel_lock``, ``serving.pre_subscribe_lock`` and the
lock-held ``serving.mirror_drain`` — so every run explores a different
acquisition interleaving, reproducibly.

Run with ``DSTPU_CONCURRENCY_CHECKS=1`` (the default here), every
guarded-field access additionally asserts the engine lock is held
(``serving/concurrency.py``); a single unlocked touch anywhere in the
interleaving surfaces as a :class:`ConcurrencyViolation` and fails the
harness.  The invariants asserted per seed:

* **bitwise serving** — every never-cancelled request's COMPLETED output
  is bitwise-identical to a sequential reference run of the same
  workload (admission order and slot churn may differ; outputs may not);
* **exactly one terminal status** per request (cancel racing the mirror
  drain's retirement must resolve to COMPLETED xor CANCELLED — never
  both, never a KeyError);
* **lossless streams** — a mid-flight ``token_events`` subscription
  drains to exactly the request's final generated tokens plus one typed
  ``end`` event;
* **zero guarded-field assertion trips** and no thread died.

Tier-1 via ``tests/unit/test_serving_concurrency.py``; also the runtime
half of ``ds_lint --concurrency``.  ``main()`` is the CLI entry point.
"""

import os
import threading
import time

import numpy as np

YIELD_SEAMS = ("serving.pre_step_lock", "serving.pre_submit_lock",
               "serving.pre_cancel_lock", "serving.pre_subscribe_lock",
               "serving.mirror_drain")

TERMINAL = ("COMPLETED", "SHED_DEADLINE", "CANCELLED", "ABORTED")


def _tiny_served_engine(seed=0):
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import (Transformer,
                                                  TransformerConfig)
    cfg = TransformerConfig(vocab_size=97, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=64,
                            use_flash_attention=False, dtype="float32")
    model = Transformer(cfg)
    ids = jnp.asarray(np.random.default_rng(seed).integers(0, 97, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 8,
                       "serving": {"enabled": True, "num_slots": 2,
                                   "max_cache_len": 48, "prefill_chunk": 8,
                                   "prefill_token_budget": 16,
                                   "decode_block": 2,
                                   # fairness ON so the metrics thread
                                   # iterates live window state — the
                                   # /metrics-vs-compaction race surface
                                   "fairness_tokens_per_s": 1e6,
                                   "fairness_window_s": 10.0}})
    eng.set_params(params)
    return eng


def _workload(rng, n_keep, n_victims):
    reqs = []
    for i in range(n_keep + n_victims):
        plen = int(rng.integers(8, 20))
        reqs.append({
            "idx": i,
            "prompt": rng.integers(1, 97, (plen,)).astype(np.int32),
            "max_new": int(rng.integers(3, 9)),
            "eos": -1 if i % 2 else 96,
            "client": f"tenant-{i % 2}",
            "victim": i >= n_keep,
        })
    return reqs


def _reference_outputs(eng, reqs):
    """Sequential single-threaded serve of the keep requests — the
    bitwise baseline the concurrent run must reproduce."""
    srv = eng.serve()
    rids = {r["idx"]: srv.submit(r["prompt"], max_new_tokens=r["max_new"],
                                 eos_token_id=r["eos"],
                                 client_id=r["client"])
            for r in reqs if not r["victim"]}
    srv.drain()
    ref = {idx: srv.result(rid).output for idx, rid in rids.items()}
    srv.close()
    return ref


def _run_one_seed(eng, reqs, ref, seed, yield_s):
    from deepspeed_tpu.runtime.fault import inject
    problems = []
    errors = []                          # (thread, repr) — any means FAIL
    rid_of = {}                          # idx -> rid
    harness_lock = threading.Lock()
    rid_ready = threading.Event()
    stop = threading.Event()

    inject.reset_injection()
    inject.configure_injection([
        {"point": p, "action": "yield", "at": 1, "times": 0,
         "seconds": yield_s, "seed": seed + i}
        for i, p in enumerate(YIELD_SEAMS)])
    srv = eng.serve()
    rng = np.random.default_rng(1000 + seed)

    def guard(name, fn):
        def run():
            try:
                fn()
            except Exception as e:       # noqa: BLE001 — the verdict
                errors.append((name, f"{type(e).__name__}: {e}"))
                stop.set()
        return threading.Thread(target=run, name=f"ilv-{name}",
                                daemon=True)

    def scheduler():
        srv.bind_owner()
        while not stop.is_set():
            if srv.work_pending():
                srv.step()
            else:
                srv.wake.wait(timeout=0.005)
                srv.wake.clear()

    def submitter(share):
        local = np.random.default_rng(2000 + seed + share)
        for r in reqs[share::2]:
            time.sleep(float(local.random()) * yield_s)
            rid = srv.submit(r["prompt"], max_new_tokens=r["max_new"],
                             eos_token_id=r["eos"], client_id=r["client"])
            with harness_lock:
                rid_of[r["idx"]] = rid
                if len(rid_of) == len(reqs):
                    rid_ready.set()

    def canceller():
        local = np.random.default_rng(3000 + seed)
        rid_ready.wait(timeout=60)
        victims = [r["idx"] for r in reqs if r["victim"]]
        for idx in victims:
            time.sleep(float(local.random()) * 4 * yield_s)
            with harness_lock:
                rid = rid_of.get(idx)
            if rid is not None:
                srv.cancel(rid)          # False when already terminal

    streams = {}                         # idx -> (tokens, end_event)

    def subscriber():
        rid_ready.wait(timeout=60)
        keeps = [r["idx"] for r in reqs if not r["victim"]][:4]
        for idx in keeps:
            with harness_lock:
                rid = rid_of[idx]
            stream = srv.token_events(rid)
            toks, end = stream.tokens(timeout=60)
            streams[idx] = (toks, end)

    def metrics():
        while not stop.is_set():
            srv.health_snapshot()
            # the /metrics surface: stats + fairness windows snapshotted
            # under the engine lock while the scheduler mutates them
            with srv._lock:
                dict(srv.stats)
                if srv._fairness is not None:
                    srv._fairness.window_usage()
            time.sleep(yield_s / 2)

    threads = [guard("scheduler", scheduler), guard("submit-0",
               lambda: submitter(0)), guard("submit-1",
               lambda: submitter(1)), guard("cancel", canceller),
               guard("subscribe", subscriber), guard("metrics", metrics)]
    for t in threads:
        t.start()
    # wait until every request reached a terminal status (or a thread
    # died); the scheduler thread keeps stepping the whole time
    deadline = time.monotonic() + 300
    try:
        while time.monotonic() < deadline and not stop.is_set():
            with harness_lock:
                rids = dict(rid_of)
            if len(rids) == len(reqs) and all(
                    srv.status(rid) in TERMINAL for rid in rids.values()):
                break
            time.sleep(0.005)
        else:
            if not stop.is_set():
                problems.append(f"seed {seed}: requests still live at "
                                f"the 300s harness deadline")
    finally:
        stop.set()
        srv.wake.set()
        for t in threads:
            t.join(timeout=60)
        inject.reset_injection()

    if errors:
        problems.extend(f"seed {seed}: thread {n} died: {e}"
                        for n, e in errors)
    completed = cancelled = 0
    for r in reqs:
        idx = r["idx"]
        rid = rid_of.get(idx)
        if rid is None:
            problems.append(f"seed {seed}: request {idx} never submitted")
            continue
        status = srv.status(rid)
        res = srv.result(rid)
        if status not in TERMINAL or res is None:
            problems.append(f"seed {seed}: request {idx} (rid {rid}) "
                            f"not terminal: {status}")
            continue
        if r["victim"]:
            # cancel raced the mirror drain's retirement: either side may
            # win, but EXACTLY one terminal status must result
            if status not in ("CANCELLED", "COMPLETED"):
                problems.append(f"seed {seed}: victim {idx} ended "
                                f"{status} ({res.detail})")
            cancelled += status == "CANCELLED"
        else:
            if status != "COMPLETED":
                problems.append(f"seed {seed}: keep request {idx} ended "
                                f"{status} ({res.detail})")
            elif not np.array_equal(res.output, ref[idx]):
                problems.append(
                    f"seed {seed}: request {idx} output diverges from "
                    f"the sequential reference (bitwise-serving "
                    f"invariant broken)")
            completed += 1
    for idx, (toks, end) in streams.items():
        rid = rid_of.get(idx)
        res = srv.result(rid) if rid is not None else None
        if end is None or end.get("event") != "end":
            problems.append(f"seed {seed}: stream {idx} never ended")
        elif res is not None and res.output is not None:
            # the FULL generated sequence, not a prefix: the output is
            # eos-padded to max_new, so the real sequence ends at the
            # first eos (inclusive) — a stream that lost tail tokens
            # before its end event must fail here
            P = len(reqs[idx]["prompt"])
            eos = reqs[idx]["eos"]
            want = [int(t) for t in res.output[P:]]
            if eos >= 0 and eos in want:
                want = want[:want.index(eos) + 1]
            if toks != want or end["status"] != "COMPLETED":
                problems.append(f"seed {seed}: stream {idx} diverges "
                                f"from the final output "
                                f"({len(toks)} streamed vs "
                                f"{len(want)} generated)")
    report = {
        "completed": completed,
        "cancelled": cancelled,
        "lock_wait_s": dict(srv._lock.wait_s),
        "lock_acquires": dict(srv._lock.acquires),
    }
    srv.close()
    return report, problems


def run_interleave_check(seeds=(0, 1), n_keep=6, n_victims=3,
                         yield_s=0.002, checks=True):
    """Run the stress scenario once per seed; returns
    ``{"ok", "problems", "per_seed"}``.  ``checks=True`` arms
    ``DSTPU_CONCURRENCY_CHECKS`` for the engines built here (restoring
    the caller's environment afterwards)."""
    from deepspeed_tpu.inference.serving.concurrency import ENV_VAR
    prev = os.environ.get(ENV_VAR)
    if checks:
        os.environ[ENV_VAR] = "1"
    try:
        eng = _tiny_served_engine()
        rng = np.random.default_rng(7)
        reqs = _workload(rng, n_keep, n_victims)
        ref = _reference_outputs(eng, reqs)
        per_seed, problems = {}, []
        for seed in seeds:
            report, probs = _run_one_seed(eng, reqs, ref, seed, yield_s)
            per_seed[seed] = report
            problems.extend(probs)
        return {"ok": not problems, "problems": problems,
                "per_seed": per_seed}
    finally:
        if prev is None:
            os.environ.pop(ENV_VAR, None)
        else:
            os.environ[ENV_VAR] = prev


def main():
    result = run_interleave_check()
    for seed, report in result["per_seed"].items():
        waits = ", ".join(f"{k}={v:.4f}s"
                          for k, v in report["lock_wait_s"].items())
        print(f"[interleave] seed {seed}: {report['completed']} "
              f"completed, {report['cancelled']} cancelled, "
              f"lock waits {waits}")
    for p in result["problems"]:
        print(f"[interleave] PROBLEM: {p}")
    verdict = ("OK — bitwise outputs, single terminal statuses, zero "
               "guarded-field assertion trips" if result["ok"]
               else "INTERLEAVING FAILURE — see problems above")
    print(f"[interleave] {verdict}")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
