"""Flops profiler.

The reference monkey-patches ``torch.nn.functional`` to count flops at
runtime (``profiling/flops_profiler/profiler.py:23,441-``).  On TPU the
compiler already knows: XLA's cost analysis on the compiled executable gives
exact flop/byte counts for the *optimized* program — more accurate than
op-by-op Python counting, and free.  The profiler reads
``compiled.cost_analysis()`` plus wall-clock timing to report
flops / MACs / params / achieved TFLOPS and MFU.

Per-module tree (reference ``print_model_profile``, ``profiler.py:239``):

* flops / MACs / params per module come from flax's module summary
  (exact per-call counts via ``jax.jit`` cost analysis on each submodule);
* measured per-module DEVICE latency comes from one profiled run — XLA-op
  durations in the ``jax.profiler`` trace joined against the compiled
  HLO's ``op_name`` metadata, which carries the flax module scope path
  (the TPU analog of the reference's per-module hook timers).
"""

import glob
import os
import re
import tempfile
import time

import numpy as np

import jax

from deepspeed_tpu.utils.logging import log_dist, logger

# Peak bf16 TFLOP/s per chip for MFU estimates (public figures).
PEAK_TFLOPS = {
    "tpu v4": 275.0,
    "tpu v5 lite": 197.0,   # v5e
    "tpu v5e": 197.0,
    "tpu v5": 459.0,        # v5p
    "tpu v6 lite": 918.0,   # trillium
    "cpu": 0.1,
}

# Peak HBM GB/s per chip for bandwidth-utilization estimates (public figures).
PEAK_HBM_GBPS = {
    "tpu v4": 1228.0,
    "tpu v5 lite": 819.0,   # v5e
    "tpu v5e": 819.0,
    "tpu v5": 2765.0,       # v5p
    "tpu v6 lite": 1640.0,  # trillium
    "cpu": 50.0,
}


def _device_peak(table, default):
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for key, val in table.items():
        if kind.startswith(key):
            return val
    return table.get(d.platform, default)


def device_peak_tflops():
    return _device_peak(PEAK_TFLOPS, 100.0)


def device_peak_hbm_gbps():
    return _device_peak(PEAK_HBM_GBPS, 819.0)


def device_hbm_bytes():
    """Device memory budget in bytes, via the accelerator's canonical
    ``memory_snapshot`` reader: the backend's reported ``bytes_limit``
    when available, else the datasheet capacity for the device kind
    (``accelerator.tpu_accelerator.DATASHEET_HBM_BYTES``; 0 =
    unknown/unbounded, callers should skip budget checks)."""
    from deepspeed_tpu.accelerator.real_accelerator import get_accelerator
    return int(get_accelerator().memory_snapshot()["bytes_limit"])


def cost_analysis_of(fn, *args, **kwargs):
    """Compile ``fn`` and return XLA's cost analysis dict (flops, bytes)
    — the compiled-program extraction itself is the shared cost model
    (``autotuning.cost_model.xla_cost_analysis``), the same code the
    memory/FLOP contract layer and the bench roofline blocks read."""
    from deepspeed_tpu.autotuning.cost_model import xla_cost_analysis
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    return xla_cost_analysis(compiled)


class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler:23``): profile one
    training step at ``profile_step`` and report totals."""

    def __init__(self, engine=None, model=None):
        self.engine = engine
        self.started = False
        self.flops = 0.0
        self.macs = 0.0
        self.params = 0
        self.step_time = 0.0

    def start_profile(self, ignore_list=None):
        self.started = True
        self._t0 = time.perf_counter()

    def stop_profile(self):
        if self.started:
            self.step_time = time.perf_counter() - self._t0
            self.started = False

    def profile_fn(self, fn, *args, **kwargs):
        """Profile an arbitrary jittable function: returns dict of metrics."""
        costs = cost_analysis_of(fn, *args, **kwargs)
        flops = float(costs.get("flops", 0.0))
        # timed execution
        f = jax.jit(fn)
        out = f(*args, **kwargs)          # warmup/compile
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        n = 3
        for _ in range(n):
            out = f(*args, **kwargs)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / n
        achieved = flops / dt / 1e12 if dt > 0 else 0.0
        peak = device_peak_tflops() * jax.device_count()
        return {
            "flops": flops,
            "latency_s": dt,
            "tflops": achieved,
            "mfu": achieved / peak if peak else 0.0,
            "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
        }

    def get_total_flops(self, as_string=False):
        return _num_to_string(self.flops) + "FLOPS" if as_string else self.flops

    def get_total_params(self, as_string=False):
        return _num_to_string(self.params) if as_string else self.params

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=3,
                            detailed=True, output_file=None, batch=None):
        """Reference-format profile report (``profiler.py:239``): totals,
        per-depth aggregates, and the detailed per-module tree (flops/MACs
        exact from the module summary; latency measured from a profiled
        run where a device trace is available)."""
        if self.engine is not None and self.engine.params is not None:
            self.params = sum(int(np.prod(l.shape))
                              for l in jax.tree.leaves(self.engine.params))
        lines = [
            "-------------------------- DeepSpeed Flops Profiler --------------------------",
            f"params per gpu: {_num_to_string(self.params)}",
            f"profile step: {profile_step}",
            f"step latency: {self.step_time*1e3:.2f} ms",
        ]
        tree = None
        module = getattr(self.engine, "module", None) if self.engine else None
        import flax.linen as nn
        if detailed and isinstance(module, nn.Module) and batch is not None:
            try:
                tree, total_ps = model_profile_tree(
                    module, jax.random.key(0), batch,
                    variables=getattr(self.engine, "params", None))
                lines.append(
                    "----------------------------- Aggregated Profile per GPU"
                    " -----------------------------")
                lines.append(aggregate_by_depth(
                    tree, max_depth=module_depth if module_depth > 0 else 3,
                    top=max(int(top_modules), 1)))
                lines.append(
                    "------------------------------ Detailed Profile per GPU"
                    " ------------------------------")
                lines.append(format_profile_tree(
                    tree, total_ps, depth=module_depth))
            except Exception as e:
                lines.append(f"(per-module tree unavailable: {e})")
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report)
        log_dist(report, ranks=[0])
        return report


def _num_to_string(num, precision=2):
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(num) >= div:
            return f"{num/div:.{precision}f} {unit}"
    return str(num)


# --------------------------------------------------------------------- #
# Per-module profile tree (reference profiler.py:239 print_model_profile)
# --------------------------------------------------------------------- #
class ModuleProfile:
    """One node of the per-module tree: subtree-aggregated params / fwd
    flops / bwd (vjp) flops, measured device latency, and children."""

    def __init__(self, name, module_type=""):
        self.name = name
        self.module_type = module_type
        self.params = 0
        self.flops = 0.0          # forward flops (2x MACs)
        self.vjp_flops = 0.0      # fwd+bwd flops of the vjp
        self.latency_ps = 0       # measured device time attributed here
        self.children = {}

    @property
    def macs(self):
        return self.flops / 2.0

    def child(self, name, module_type=""):
        if name not in self.children:
            self.children[name] = ModuleProfile(name, module_type)
        return self.children[name]

    def walk(self, depth=0):
        yield depth, self
        for c in self.children.values():
            yield from c.walk(depth + 1)


def _scope_to_path(op_name):
    """HLO metadata op_name → module path tuple.

    ``jit(fn)/Model/Model.hidden_states/layers_0/attn/dot_general`` →
    ``("layers_0", "attn", ...)``: transform frames (``jit(...)`` etc.),
    method frames (``Class.method``), and einsum-label frames are dropped;
    a trailing primitive name simply stops the tree walk at the owning
    module."""
    parts = [p for p in op_name.split("/")
             if "(" not in p and "." not in p
             and re.match(r"^[A-Za-z_]\w*$", p)]
    # drop the leading model-class frame
    return tuple(parts[1:])


def _hlo_op_scopes(compiled_text):
    """Map HLO instruction name → op_name metadata scope."""
    return dict(re.findall(
        r"%?([\w.\-]+) = [^\n]*metadata=\{[^}]*op_name=\"([^\"]+)\"",
        compiled_text))


def _trace_op_stats(trace_fn):
    """Run ``trace_fn()`` under the jax profiler; return
    {hlo_op: [dur_ps, flops]} summed over the device plane's XLA-op events.
    Returns {} when no device plane with op events is found (e.g. CPU test
    meshes)."""
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    with tempfile.TemporaryDirectory() as d:
        try:
            with jax.profiler.trace(d):
                trace_fn()
            from tensorflow.tsl.profiler.protobuf import xplane_pb2
        except Exception as e:               # pragma: no cover - no tf proto
            logger.warning(f"flops profiler: trace unavailable ({e}); "
                           "per-module latency will be missing")
            return {}
        out = {}
        for path in glob.glob(d + "/**/*.xplane.pb", recursive=True):
            xs = xplane_pb2.XSpace()
            with open(path, "rb") as f:
                xs.ParseFromString(f.read())
            for plane in xs.planes:
                if "/device:" not in plane.name:
                    continue
                ev_meta = plane.event_metadata
                stats_meta = plane.stat_metadata
                for line in plane.lines:
                    if line.name != "XLA Ops":
                        continue
                    for ev in line.events:
                        md = ev_meta[ev.metadata_id]
                        # "%fusion.3 = ..." → "fusion.3"
                        nm = md.name.split(" = ")[0].lstrip("%")
                        flops = 0
                        for st in list(ev.stats) + list(md.stats):
                            if stats_meta[st.metadata_id].name == "flops":
                                flops = int(st.int64_value or st.uint64_value
                                            or 0)
                        rec = out.setdefault(nm, [0, 0])
                        rec[0] += ev.duration_ps
                        # per-occurrence: ops inside rolled loops execute
                        # (and cost) once per iteration
                        rec[1] += flops
        return out


def model_profile_tree(module, rngs, *args, measure_latency=True,
                       variables=None, **kwargs):
    """Build the per-module profile tree for a flax module.

    Structure + params come from flax's module summary.  flops + latency:

    * on accelerators, from ONE profiled run of the compiled program —
      per-XLA-op durations and flop counts joined to module scopes via the
      HLO ``op_name`` metadata (exact for the *optimized* program);
    * on CPU (test meshes, no device trace), flops fall back to flax's
      per-module cost analysis and latency stays unattributed.

    Returns ``(root, total_latency_ps)``.  Ops the join can't see (fully
    fused across module boundaries) stay at the nearest attributed
    ancestor.
    """
    from flax.linen import summary as _summary
    on_cpu = jax.default_backend() == "cpu"
    table_fn = _summary._get_module_table(
        module, depth=None, show_repeated=True,
        compute_flops=on_cpu, compute_vjp_flops=on_cpu)
    rows = table_fn(rngs, *args, **kwargs)

    root = ModuleProfile("", type(module).__name__)
    for row in rows:
        node = root
        for part in row.path:
            node = node.child(part)
        node.module_type = type(row.module_copy).__name__
        if on_cpu:
            node.flops = float(row.flops) if row.flops and row.flops > 0 \
                else 0.0
            node.vjp_flops = float(row.vjp_flops) \
                if row.vjp_flops and row.vjp_flops > 0 else 0.0
        node.params = sum(
            int(np.prod(np.shape(v)))
            for v in jax.tree.leaves(row.module_variables.get("params", {})))

    def _aggregate_params(node):
        # rows carry each module's OWN variables; the tree reports subtree
        # totals like the reference
        node.params += sum(_aggregate_params(c)
                           for c in node.children.values())
        return node.params

    _aggregate_params(root)

    total_ps = 0
    if measure_latency:
        if variables is None:
            # callers profiling a LIVE engine must pass its params instead:
            # a fresh init would duplicate every parameter on a chip that
            # may already be near HBM capacity
            variables = module.init(rngs, *args, **kwargs)
        fn = jax.jit(lambda v, *a: module.apply(v, *a, **kwargs))
        # one compile serves warmup, the profiled run, AND the HLO text
        # (jit dispatch would compile a second executable)
        compiled = fn.lower(variables, *args).compile()
        scopes = _hlo_op_scopes(compiled.as_text())
        from deepspeed_tpu.utils.sync import dependent_sync_scalar
        dependent_sync_scalar(compiled(variables, *args))   # warmup

        def run():
            dependent_sync_scalar(compiled(variables, *args))

        stats = _trace_op_stats(run)
        for op, (ps, flops) in stats.items():
            total_ps += ps
            scope = scopes.get(op)
            path = _scope_to_path(scope) if scope else ()
            node = root
            node.latency_ps += ps
            if not on_cpu:
                node.flops += flops
            for part in path:
                nxt = node.children.get(part)
                if nxt is None:
                    break
                node = nxt
                node.latency_ps += ps
                if not on_cpu:
                    node.flops += flops
    return root, total_ps


def format_profile_tree(root, total_latency_ps=0, depth=-1, indent=2):
    """Reference-style detailed tree (``profiler.py:239``): every module
    annotated with subtree params, MACs, and measured latency share."""
    tot_flops = root.flops or 1.0
    tot_params = root.params or 1
    tot_lat = root.latency_ps or total_latency_ps or 1
    lines = []

    def fmt(node, d, prefix):
        ann = (f"{_num_to_string(node.params)} = "
               f"{100.0 * node.params / tot_params:.2f}% Params, "
               f"{_num_to_string(node.macs)}MACs = "
               f"{100.0 * node.flops / tot_flops:.2f}% MACs")
        if node.latency_ps:
            ann += (f", {node.latency_ps / 1e6:.3f} ms = "
                    f"{100.0 * node.latency_ps / tot_lat:.2f}% latency")
        name = f"({node.name}): " if node.name else ""
        lines.append(" " * (d * indent) + f"{name}{node.module_type}({ann})")
        if depth < 0 or d < depth:
            for c in node.children.values():
                fmt(c, d + 1, prefix)

    fmt(root, 0, "")
    return "\n".join(lines)


def aggregate_by_depth(root, max_depth=3, top=3):
    """Reference "aggregated profile": top modules per depth by params /
    MACs / latency (``profiler.py:375``)."""
    by_depth = {}
    for d, node in root.walk():
        by_depth.setdefault(d, []).append(node)
    out = []
    for d in sorted(by_depth)[:max_depth + 1]:
        nodes = by_depth[d]
        top_p = sorted(nodes, key=lambda n: -n.params)[:top]
        top_f = sorted(nodes, key=lambda n: -n.flops)[:top]
        top_l = sorted(nodes, key=lambda n: -n.latency_ps)[:top]
        out.append(f"depth {d}:")
        out.append("    params      - " + str(
            {n.name or n.module_type: _num_to_string(n.params) for n in top_p}))
        out.append("    MACs        - " + str(
            {n.name or n.module_type: _num_to_string(n.macs) for n in top_f}))
        if any(n.latency_ps for n in nodes):
            out.append("    fwd latency - " + str(
                {n.name or n.module_type: f"{n.latency_ps/1e6:.3f} ms"
                 for n in top_l}))
    return "\n".join(out)


def get_model_profile(model_fn, args=(), kwargs=None, print_profile=True,
                      detailed=True, warm_up=1, as_string=True):
    """Standalone API parity (reference ``profiler.py get_model_profile``)."""
    prof = FlopsProfiler()
    metrics = prof.profile_fn(model_fn, *args, **(kwargs or {}))
    flops, macs = metrics["flops"], metrics["flops"] / 2
    params = 0
    if print_profile:
        log_dist(f"flops={_num_to_string(flops)} macs={_num_to_string(macs)} "
                 f"tflops={metrics['tflops']:.2f} mfu={metrics['mfu']*100:.1f}%",
                 ranks=[0])
    if as_string:
        return _num_to_string(flops), _num_to_string(macs), str(params)
    return flops, macs, params
