"""PipelineEngine — pipeline-parallel training engine.

Parity with reference ``runtime/pipe/engine.py:42`` (``PipelineEngine``):
``train_batch``/``eval_batch`` consume gradient-accumulation microbatches and
run them through pipeline stages; ``forward``/``backward`` are disallowed
exactly like the reference (``pipe/engine.py:1107-1118``).

TPU realization: the instruction schedule + p2p machinery is replaced by the
differentiable SPMD pipeline (``parallel/pipeline.py``).  The model arrives
as a ``PipelineModule`` (sequence of LayerSpecs).  Layers are initialized
shape-propagated, then split into:

* ``pre``  — leading layers whose param structure differs from the majority
  (e.g. embeddings) — run under plain GSPMD before the pipelined region;
* ``body`` — the uniform run of identical-structure layers (e.g. transformer
  blocks), stacked ``[P, L/P, ...]`` and sharded over the ``pp`` mesh axis;
* ``post`` — trailing non-uniform layers (final norm, LM head) — run under
  GSPMD after the region.

This is the idiomatic TPU pipeline decomposition: embeddings/heads are
sharded over dp/tp like any other op, while the repeated trunk pipelines.
ZeRO/TP sharding composes: the plan shards body-leaf inner dims over
dp/tp *in addition to* the leading pp dim.
"""

from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel import topology as topo_mod
from deepspeed_tpu.parallel.pipeline import spmd_pipeline, stack_stage_params
from deepspeed_tpu.runtime.engine import DeepSpeedEngine, _opt_state_shardings
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.runtime.zero.partition import build_sharding_plan, ZeroShardingPlan
from deepspeed_tpu.utils.logging import log_dist


class PipelineEngine(DeepSpeedEngine):

    def __init__(self, model: PipelineModule = None, **kwargs):
        assert isinstance(model, PipelineModule), \
            "PipelineEngine requires a PipelineModule"
        self.pipe_module = model
        # honor PipelineModule(num_stages=...) when the config doesn't set
        # pipeline.stages (reference: module carries the stage count)
        cfg = kwargs.get("config")
        if model.num_stages and isinstance(cfg, dict):
            cfg = dict(cfg)
            pipe_blk = dict(cfg.get("pipeline", {}))
            pipe_blk.setdefault("stages", model.num_stages)
            cfg["pipeline"] = pipe_blk
            kwargs["config"] = cfg
        if model.partition_method not in ("parameters", "uniform"):
            from deepspeed_tpu.utils.logging import warning_once
            warning_once(
                f"partition_method={model.partition_method!r}: the SPMD "
                "pipeline stacks a uniform trunk (equal layers per stage); "
                "type-regex balancing is advisory only")
        super().__init__(model=model, **kwargs)
        self.num_stages = self.topology.pp
        if self.num_stages < 1:
            raise ValueError("pipeline requires pp >= 1 in the mesh")
        self.micro_batches = self.gradient_accumulation_steps()
        C = int(self._config.pipeline.max_in_flight_microbatches or 0)
        if C and self.micro_batches % C != 0:
            raise ValueError(
                f"pipeline.max_in_flight_microbatches={C} must divide "
                f"micro_batches={self.micro_batches}")
        self.max_in_flight = C
        sched = self._config.pipeline.schedule
        if sched not in ("fill_drain", "1f1b"):
            raise ValueError(f"pipeline.schedule must be 'fill_drain' or "
                             f"'1f1b', got {sched!r}")
        if sched == "1f1b" and C:
            raise ValueError(
                "pipeline.schedule='1f1b' already bounds the stash to O(P); "
                "it is mutually exclusive with max_in_flight_microbatches")
        self.pipe_schedule = sched

    # the reference forbids forward/backward/step on the pipeline engine —
    # train_batch is the unit of work (pipe/engine.py:1107-1118)
    def forward(self, *a, **k):
        raise RuntimeError("PipelineEngine does not support forward(); "
                           "use train_batch / eval_batch")

    __call__ = forward

    def backward(self, *a, **k):
        raise RuntimeError("PipelineEngine does not support backward(); "
                           "use train_batch")

    def step(self, *a, **k):
        raise RuntimeError("PipelineEngine does not support step(); "
                           "use train_batch")

    # ------------------------------------------------------------------ #
    def _setup_model_fns(self, model, model_parameters):
        self._is_flax = False
        self._init_fn = None
        self._raw_apply = None   # pipeline path doesn't use the base apply

    def _layer_params_and_apply(self, layer, rng, x_abs, abstract=False):
        """Init one layer against the incoming abstract activation.

        Every returned apply has the uniform signature
        ``apply(params, x, train=True)``; the flag is forwarded only to
        modules whose ``__call__`` declares it (MoE gates switch their
        capacity/noise regime on it, like the dense Transformer).
        ``abstract=True`` shape-evaluates the init instead of running it —
        the checkpoint-restore path needs only structure/shapes and must
        not materialize a throwaway random copy of the model."""
        import inspect
        import flax.linen as nn
        if isinstance(layer, nn.Module):
            if abstract:
                params = jax.eval_shape(
                    lambda r: layer.init(r, _zeros_like_abs(x_abs)), rng)
            else:
                params = layer.init(rng, _zeros_like_abs(x_abs))
            takes_train = "train" in inspect.signature(
                type(layer).__call__).parameters
            if takes_train:
                apply = lambda p, x, train=True: layer.apply(p, x, train=train)
            else:
                apply = lambda p, x, train=True: layer.apply(p, x)
            y_abs = jax.eval_shape(lambda p, x: apply(p, x), params, x_abs)
            return params, apply, y_abs
        # paramless callable
        y_abs = jax.eval_shape(layer, x_abs)
        return None, (lambda p, x, train=True: layer(x)), y_abs

    def _build_pipeline(self, example_micro, abstract=False):
        """Initialize all layers, split pre/body/post, stack body.

        ``TiedLayerSpec`` layers sharing a key share parameters (reference
        ``pipe/module.py:76,406-427``): the second occurrence initializes
        nothing and applies its ``forward_fn`` (or the module's apply) to
        the FIRST occurrence's params — the single GSPMD copy makes the
        reference's tied-grad allreduce unnecessary.  Tied layers must sit
        outside the stacked body (pre/post), which embedding/head tying
        always satisfies."""
        from deepspeed_tpu.runtime.pipe.module import TiedLayerSpec
        layers = self.pipe_module.build_layers()
        specs = self.pipe_module.layer_specs
        rng = jax.random.key(self._config.seed)
        x_abs = jax.eval_shape(lambda b: _first_tensor(b), example_micro)
        inits, applies, structs, reuse_of = [], [], [], []
        tied_first = {}
        for i, (spec, layer) in enumerate(zip(specs, layers)):
            tied_key = spec.key if isinstance(spec, TiedLayerSpec) else None
            if tied_key is not None and tied_key in tied_first:
                src = tied_first[tied_key]
                raw = spec.forward_fn or \
                    (lambda p, x, _l=layer: _l.apply(p, x))
                fwd = lambda p, x, train=True, _raw=raw: _raw(p, x)
                x_abs = jax.eval_shape(lambda p, x: fwd(p, x),
                                       inits[src], x_abs)
                inits.append(None)
                applies.append(fwd)
                structs.append(None)
                reuse_of.append(src)
                continue
            if tied_key is not None:
                tied_first[tied_key] = i
            rng, sub = jax.random.split(rng)
            params, apply, x_abs = self._layer_params_and_apply(
                layer, sub, x_abs, abstract=abstract)
            inits.append(params)
            applies.append(apply)
            structs.append(jax.tree.structure(params)
                           if params is not None else None)
            reuse_of.append(None)
        # majority structure = the pipeline body; the run must be contiguous
        # (stacked SPMD stages execute one uniform layer function)
        from collections import Counter
        counted = Counter(s for s in structs if s is not None)
        body_struct, body_count = counted.most_common(1)[0]
        idxs = [i for i, s in enumerate(structs)
                if s is not None and s == body_struct]
        first, last = idxs[0], idxs[-1]
        if last - first + 1 != body_count:
            gaps = [i for i in range(first, last + 1) if structs[i] != body_struct]
            raise ValueError(
                f"pipeline body (majority layer structure) is not contiguous: "
                f"layers {gaps} interrupt the run {first}..{last}. The SPMD "
                f"pipeline stacks a uniform trunk; move non-uniform layers "
                f"before/after the repeated blocks")
        body_types = {type(layers[i]).__name__ for i in range(first, last + 1)}
        if len(body_types) > 1:
            raise ValueError(
                f"pipeline body layers must be one module type, got {body_types}")
        if body_count % self.topology.pp != 0:
            raise ValueError(
                f"{body_count} pipeline body layers not divisible by "
                f"pp={self.topology.pp} stages")
        tied_sources = {r for r in reuse_of if r is not None}
        if any(reuse_of[i] is not None for i in range(first, last + 1)) or \
                any(first <= s <= last for s in tied_sources):
            raise ValueError(
                "TiedLayerSpec sharing with the pipeline body is "
                "unsupported (neither occurrence may fall in the stacked "
                "trunk); tie embedding/head layers (pre/post) only")

        def outer_entry(i):
            return {"apply": applies[i], "params": inits[i],
                    "layer_idx": i, "reuse_of": reuse_of[i]}

        self._pre = [outer_entry(i) for i in range(first)]
        self._post = [outer_entry(i) for i in range(last + 1, len(layers))]
        self._body_apply = applies[first]
        body_params = [inits[i] for i in range(first, last + 1)]
        if abstract:
            self._body_stacked = jax.eval_shape(
                lambda ps: stack_stage_params(ps, self.topology.pp),
                body_params)
        else:
            self._body_stacked = stack_stage_params(body_params,
                                                    self.topology.pp)
        log_dist(f"pipeline split: {first} pre / {body_count} body "
                 f"({self.topology.pp} stages × {body_count // self.topology.pp}) "
                 f"/ {len(layers) - last - 1} post layers", ranks=[0])

    def _assemble_params(self):
        return {
            "pre": [e["params"] for e in self._pre if e["params"] is not None],
            "body": self._body_stacked,
            "post": [e["params"] for e in self._post if e["params"] is not None],
        }

    def _build_pipe_plan(self, abstract):
        """Sharding plan: body gets pp on dim 0, zero/tp on inner dims
        computed per-stage then shifted right by the two stacked dims."""
        mesh = self.mesh
        zero_cfg = self._config.zero_config

        body_inner = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape[2:], a.dtype),
            abstract["body"])
        inner_plan = build_sharding_plan(body_inner, self.topology, zero_cfg)

        def lift(spec_tree):
            return jax.tree.map(lambda s: P(topo_mod.PP_AXIS, None, *s),
                                spec_tree, is_leaf=lambda x: isinstance(x, P))

        outer_plan = build_sharding_plan(
            {"pre": abstract["pre"], "post": abstract["post"]},
            self.topology, zero_cfg)

        param_specs = {"pre": outer_plan.param_specs["pre"],
                       "body": lift(inner_plan.param_specs),
                       "post": outer_plan.param_specs["post"]}
        grad_specs = {"pre": outer_plan.grad_specs["pre"],
                      "body": lift(inner_plan.grad_specs),
                      "post": outer_plan.grad_specs["post"]}
        opt_specs = {"pre": outer_plan.opt_specs["pre"],
                     "body": lift(inner_plan.opt_specs),
                     "post": outer_plan.opt_specs["post"]}
        return ZeroShardingPlan(param_specs, grad_specs, opt_specs, mesh)

    def _build_plan(self, abstract_params):
        """Base-engine hook override: the fresh-engine checkpoint-restore
        paths (``load_checkpoint`` → ``_init_params_from`` /
        ``_metadata_restore_targets``) build the plan from loaded shapes —
        a pipe-structured tree must get the pipe plan (pp-lifted body
        specs), not the flat one."""
        if (isinstance(abstract_params, dict)
                and set(abstract_params) == {"pre", "body", "post"}):
            self._plan = self._build_pipe_plan(abstract_params)
            self._abstract_params = abstract_params
        else:
            super()._build_plan(abstract_params)

    def _lazy_init_pipe(self, batch):
        built = getattr(self, "_body_apply", None) is not None
        if self._params is not None and built:
            return
        micro = jax.tree.map(lambda x: x[0], batch)
        loaded = self._params
        # with params already restored, only structure/shapes are needed —
        # don't materialize a throwaway random init of the whole model
        self._build_pipeline(micro, abstract=loaded is not None)
        raw = self._assemble_params()
        abstract = jax.eval_shape(lambda t: t, raw)
        if loaded is not None:
            # params were restored by load_checkpoint into a fresh engine
            # (which already built the pipe plan + optimizer state from the
            # loaded shapes via _build_plan above); only the module
            # structure — the pre/body/post split and layer applies — was
            # missing.  Keep the loaded params; the just-initialized layer
            # values are discarded.
            if jax.tree.structure(loaded) != jax.tree.structure(abstract):
                raise ValueError(
                    "checkpoint params do not match the pipeline module "
                    "structure (different layer split or layer count)")
            mismatch = [f"{a.shape} vs {b.shape}" for a, b in zip(
                jax.tree.leaves(loaded), jax.tree.leaves(abstract))
                if tuple(a.shape) != tuple(b.shape)]
            if mismatch:
                raise ValueError(
                    f"checkpoint param shapes do not match the pipeline "
                    f"module: {mismatch[:3]}")
            return
        self._plan = self._build_pipe_plan(abstract)
        self._abstract_params = abstract
        put = jax.jit(lambda t: jax.tree.map(
            lambda p: p.astype(jnp.float32)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, t),
            out_shardings=self._plan.param_shardings)
        self._params = put(raw)
        n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(self._params))
        log_dist(f"pipeline params initialized: {n/1e6:.2f}M "
                 f"across {self.topology.pp} stages", ranks=[0])
        self._init_opt_state()

    # ------------------------------------------------------------------ #
    def _pipe_loss(self, params, batch, rng, num_micro=None, train=True):
        """The full pipelined loss: pre → spmd_pipeline → post → loss_fn.

        ``batch``: pytree with leading [M, mb, ...]; convention (inputs,
        labels) tuple or dict with 'labels'.  Activations may be pytrees
        (MoE trunks carry ``(hidden, aux)``).  Tied layers resolve their
        shared params from the first occurrence (``seen``).
        """
        inputs, labels = _split_batch(batch)
        M = num_micro if num_micro is not None else self.micro_batches
        cast = lambda t: jax.tree.map(
            lambda p: p.astype(self.compute_dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, t)
        pre_ps = iter(cast(params["pre"]))
        post_ps = iter(cast(params["post"]))
        seen = {}

        def run_outer(entries, ps, x):
            for e in entries:
                if e["reuse_of"] is not None:
                    p = seen[e["reuse_of"]]
                elif e["params"] is not None:
                    p = next(ps)
                    seen[e["layer_idx"]] = p
                else:
                    p = None
                apply = e["apply"]
                x = jax.vmap(lambda xm: apply(p, xm, train=train))(x)
            return x

        x = run_outer(self._pre, pre_ps, inputs)

        body = cast(params["body"])
        layer_apply = self._body_apply

        def stage_fn(stage_params, xm):
            # one stage = scan over its L/P layers
            def one(h, p):
                return layer_apply(p, h, train=train), None
            out, _ = jax.lax.scan(one, xm, stage_params)
            return out

        ys = spmd_pipeline(stage_fn, body, x, M, self.mesh)
        out = run_outer(self._post, post_ps, ys)

        loss_fn = self.pipe_module.loss_fn or _default_loss
        losses = jax.vmap(loss_fn)(out, labels)
        return jnp.mean(losses.astype(jnp.float32))

    def _pipe_loss_and_grads_1f1b(self, params, batch, scale, train=True):
        """Interleaved 1F1B step: hand-rolled per-tick vjp inside the
        ``spmd_pipeline_1f1b`` region (reference ``TrainSchedule``,
        ``schedule.py:189``).  Boundary layers run INSIDE the region like
        the reference's stage placement — the pre chain (embeddings) on
        stage 0, the post chain + per-microbatch loss on the last stage —
        so each microbatch's backward starts the tick its forward finishes
        and the only M-sized buffers are the raw token ids/labels.
        Returns ``(scaled mean loss, grads)`` with the same semantics as
        differentiating ``mean(loss) * scale``."""
        from deepspeed_tpu.parallel.pipeline import spmd_pipeline_1f1b
        inputs, labels = _split_batch(batch)
        M = self.micro_batches
        cast = lambda t: jax.tree.map(
            lambda p: p.astype(self.compute_dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, t)

        def run_chain(entries, ps, x, seen):
            for e in entries:
                if e["reuse_of"] is not None:
                    p = seen[e["reuse_of"]]
                elif e["params"] is not None:
                    p = next(ps)
                    seen[e["layer_idx"]] = p
                else:
                    p = None
                x = e["apply"](p, x, train=train)
            return x

        pre_cast, pre_vjp = jax.vjp(cast, params["pre"])
        body_cast, body_vjp = jax.vjp(cast, params["body"])
        post_cast, post_vjp = jax.vjp(cast, params["post"])

        def first_fn(first_p, in_m):
            return run_chain(self._pre, iter(first_p), in_m, {})

        layer_apply = self._body_apply

        def stage_fn(stage_params, xm):
            def one(h, p):
                return layer_apply(p, h, train=train), None
            out, _ = jax.lax.scan(one, xm, stage_params)
            return out

        loss_fn = self.pipe_module.loss_fn or _default_loss
        # post layers may reuse (tied) pre-layer params: thread ONLY the
        # tied subtrees through the last-stage vjp (an untied model must
        # not pay a second embedding-grad accumulator + pp psum for a
        # gradient that is identically zero)
        pre_param_idx = [e["layer_idx"] for e in self._pre
                         if e["params"] is not None]
        # only PRE-sourced ties need threading; a tie between two post
        # layers resolves naturally inside run_chain's `seen`
        pre_set = set(pre_param_idx)
        tied_idx = sorted({e["reuse_of"] for e in self._post
                           if e["reuse_of"] in pre_set})
        tied_pos = [pre_param_idx.index(i) for i in tied_idx]
        tied_cast = [pre_cast[p] for p in tied_pos]

        def last_fn(last_p, y, label):
            post_params, tied_params = last_p
            seen = dict(zip(tied_idx, tied_params))
            out = run_chain(self._post, iter(post_params), y, seen)
            # mean-reduce: fill-drain computes jnp.mean over vmapped losses,
            # so a per-example loss_fn keeps working under 1f1b too
            return jnp.mean(loss_fn(out, label).astype(jnp.float32))

        loss_sum, gbody_c, gfirst_c, glast_c = spmd_pipeline_1f1b(
            stage_fn, body_cast, first_fn, pre_cast, last_fn,
            (post_cast, tied_cast), inputs, labels, M, self.mesh,
            cotangent_seed=scale / M)
        gpost_c, gtied_c = glast_c
        # pre grads: ring-backward contribution + tied-use contribution
        gpre_c = list(gfirst_c)
        for pos, g in zip(tied_pos, gtied_c):
            gpre_c[pos] = jax.tree.map(jnp.add, gpre_c[pos], g)
        match = lambda g, p: jax.tree.map(
            lambda gl, pl: gl.astype(pl.dtype), g, p)
        (gbody,) = body_vjp(match(gbody_c, body_cast))
        (gpost,) = post_vjp(match(gpost_c, post_cast))
        (gpre,) = pre_vjp(match(gpre_c, pre_cast))
        grads = {"pre": gpre, "body": gbody, "post": gpost}
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        return loss_sum * scale / M, grads

    def _get_fused_step(self):
        key = "fused_pipe_step"
        if key not in self._compiled:
            clip = float(self.gradient_clipping() or 0.0)
            scaler = self.loss_scaler

            def train_step(params, opt_state, scaler_state, lr, step, rng, batch):
                M = self.micro_batches
                C = self.max_in_flight

                def loss_of(p, b, n):
                    return self._pipe_loss(p, b, rng, num_micro=n) \
                        * scaler_state.scale

                if self.pipe_schedule == "1f1b":
                    loss, grads = self._pipe_loss_and_grads_1f1b(
                        params, batch, scaler_state.scale)
                elif C and C < M:
                    # 1F1B-class memory bound: differentiate C microbatches
                    # at a time so at most C stage inputs are stashed; the
                    # scan accumulates grads chunk by chunk (reference
                    # TrainSchedule's in-flight bound, schedule.py:189).
                    n_chunks = M // C
                    chunked = jax.tree.map(
                        lambda l: l.reshape(n_chunks, C, *l.shape[1:]), batch)

                    def one_chunk(gacc, cb):
                        l, g = jax.value_and_grad(loss_of)(params, cb, C)
                        return jax.tree.map(jnp.add, gacc, g), l

                    zeros = jax.tree.map(
                        lambda p: jnp.zeros(p.shape, jnp.float32), params)
                    gsum, ls = jax.lax.scan(one_chunk, zeros, chunked)
                    grads = jax.tree.map(lambda g: g / n_chunks, gsum)
                    loss = jnp.mean(ls)
                else:
                    loss, grads = jax.value_and_grad(loss_of)(params, batch, M)
                found_inf = jnp.logical_not(
                    jnp.all(jnp.stack([jnp.all(jnp.isfinite(g))
                                       for g in jax.tree.leaves(grads)])))
                inv = 1.0 / scaler_state.scale
                grads = jax.tree.map(lambda g: g * inv, grads)
                # norm over the UNSCALED grads (clip would otherwise divide
                # by the loss scale)
                gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                     for g in jax.tree.leaves(grads)))
                if clip > 0.0:
                    factor = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                    grads = jax.tree.map(lambda g: g * factor, grads)
                new_params, new_opt = self.optimizer.update(
                    grads, opt_state, params, lr=lr, step=step)
                keep = lambda new, old: jax.tree.map(
                    lambda n, o: jnp.where(found_inf, o, n), new, old)
                new_params = keep(new_params, params)
                new_opt = keep(new_opt, opt_state)
                new_scaler = scaler.update(scaler_state, found_inf)
                return new_params, new_opt, new_scaler, loss * inv, gnorm

            self._compiled[key] = jax.jit(
                train_step,
                donate_argnums=(0, 1, 2),
                out_shardings=(self._plan.param_shardings, self._opt_shardings,
                               None, None, None))
        return self._compiled[key]

    def train_batch(self, data_iter=None, batch=None):
        """One pipelined optimizer step over ``micro_batches`` microbatches
        (reference ``pipe/engine.py:286``)."""
        M = self.micro_batches
        if batch is None:
            mbs = [next(data_iter) for _ in range(M)]
            batch = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                                 *mbs)
        batch = jax.tree.map(jnp.asarray, batch)
        self._lazy_init_pipe(batch)
        self.tput_timer.start()
        lr = jnp.asarray(self.get_lr()[0], jnp.float32)
        step_no = jnp.asarray(self.global_steps + 1, jnp.int32)
        self._rng, rng = jax.random.split(self._rng)
        (self._params, self._opt_state, self._scaler_state, loss, gnorm) = \
            self._get_fused_step()(self._params, self._opt_state,
                                   self._scaler_state, lr, step_no, rng, batch)
        self._last_global_grad_norm = gnorm
        self._last_loss = loss
        self.global_steps += 1
        self.micro_steps += M
        self.global_samples += self.train_batch_size()
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self.tput_timer.stop(global_step=True)
        return loss

    def eval_batch(self, data_iter=None, batch=None):
        M = self.micro_batches
        if batch is None:
            mbs = [next(data_iter) for _ in range(M)]
            batch = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                                 *mbs)
        batch = jax.tree.map(jnp.asarray, batch)
        self._lazy_init_pipe(batch)
        key = "eval_pipe"
        if key not in self._compiled:
            self._compiled[key] = jax.jit(
                lambda p, b, r: self._pipe_loss(p, b, r, train=False))
        self._rng, rng = jax.random.split(self._rng)
        return self._compiled[key](self._params, batch, rng)


def _default_loss(out, labels):
    from deepspeed_tpu.models.transformer import cross_entropy_loss
    if jnp.issubdtype(jnp.asarray(labels).dtype, jnp.integer) and out.ndim >= 2:
        return cross_entropy_loss(out, labels)
    return jnp.mean((out - labels) ** 2)


def _split_batch(batch):
    """Pipeline layers pass a single activation tensor, so inputs reduce to
    the token array; attention_mask (if any) only shapes the labels."""
    if isinstance(batch, (tuple, list)) and len(batch) == 2:
        return batch[0], batch[1]
    if isinstance(batch, dict):
        labels = batch.get("labels")
        mask = batch.get("attention_mask")
        inputs = {k: v for k, v in batch.items()
                  if k not in ("labels", "attention_mask")}
        if len(inputs) == 1:
            inputs = next(iter(inputs.values()))
        elif "input_ids" in inputs:
            inputs = inputs["input_ids"]
        else:
            raise ValueError(
                f"pipeline batch dict must contain a single input tensor or "
                f"'input_ids'; got keys {sorted(batch)}")
        if labels is None:
            from deepspeed_tpu.models.transformer import derive_causal_labels
            labels = derive_causal_labels(inputs, mask)
        return inputs, labels
    raise ValueError("pipeline batch must be (inputs, labels) or a dict")


def _zeros_like_abs(abs_tree):
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), abs_tree)


def _first_tensor(b):
    if isinstance(b, (tuple, list)):
        return jnp.asarray(b[0])
    if isinstance(b, dict):
        return jnp.asarray(b.get("input_ids", next(iter(b.values()))))
    return jnp.asarray(b)
