"""Kernel-injection / HF-conversion tests — the analog of reference
``tests/unit/inference/test_inference.py``'s parametrized HF-model matrix:
build a tiny random HF model per architecture, convert through the policy,
and demand logit parity between the HF torch forward and our jitted flax
forward.  This validates every layout transform (transpose, fused-qkv
split, rope variant, alibi, residual topology) end to end."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.module_inject import (convert_hf_model, policy_for,
                                         get_tp_rules)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


TINY = dict(hidden=32, layers=2, heads=4, vocab=97, ffn=64, seq=24)


def tiny_hf_model(model_type):
    t = TINY
    if model_type == "opt":
        cfg = transformers.OPTConfig(
            vocab_size=t["vocab"], hidden_size=t["hidden"],
            num_hidden_layers=t["layers"], num_attention_heads=t["heads"],
            ffn_dim=t["ffn"], max_position_embeddings=64,
            word_embed_proj_dim=t["hidden"])
        return transformers.OPTForCausalLM(cfg)
    if model_type == "gpt2":
        cfg = transformers.GPT2Config(
            vocab_size=t["vocab"], n_embd=t["hidden"], n_layer=t["layers"],
            n_head=t["heads"], n_positions=64, n_inner=t["ffn"])
        return transformers.GPT2LMHeadModel(cfg)
    if model_type == "llama":
        cfg = transformers.LlamaConfig(
            vocab_size=t["vocab"], hidden_size=t["hidden"],
            num_hidden_layers=t["layers"], num_attention_heads=t["heads"],
            num_key_value_heads=2, intermediate_size=t["ffn"],
            max_position_embeddings=64)
        return transformers.LlamaForCausalLM(cfg)
    if model_type == "bloom":
        cfg = transformers.BloomConfig(
            vocab_size=t["vocab"], hidden_size=t["hidden"],
            n_layer=t["layers"], n_head=t["heads"])
        return transformers.BloomForCausalLM(cfg)
    if model_type == "gpt_neox":
        cfg = transformers.GPTNeoXConfig(
            vocab_size=t["vocab"], hidden_size=t["hidden"],
            num_hidden_layers=t["layers"], num_attention_heads=t["heads"],
            intermediate_size=t["ffn"], max_position_embeddings=64,
            rotary_pct=0.5)
        return transformers.GPTNeoXForCausalLM(cfg)
    if model_type == "gptj":
        cfg = transformers.GPTJConfig(
            vocab_size=t["vocab"], n_embd=t["hidden"], n_layer=t["layers"],
            n_head=t["heads"], n_positions=64, rotary_dim=4,
            n_inner=t["ffn"])
        return transformers.GPTJForCausalLM(cfg)
    if model_type == "gpt_neo":
        # window_size < seq so the local layer's band mask really bites
        cfg = transformers.GPTNeoConfig(
            vocab_size=t["vocab"], hidden_size=t["hidden"],
            num_layers=t["layers"], num_heads=t["heads"],
            intermediate_size=t["ffn"], max_position_embeddings=64,
            attention_types=[[["global", "local"], t["layers"] // 2]],
            window_size=5)
        return transformers.GPTNeoForCausalLM(cfg)
    raise ValueError(model_type)


def hf_logits(hf_model, ids):
    hf_model.eval()
    with torch.no_grad():
        return hf_model(torch.from_numpy(ids)).logits.float().numpy()


ARCHS = ["opt", "gpt2", "llama", "bloom", "gpt_neox", "gptj", "gpt_neo"]


@pytest.mark.parametrize("arch", ARCHS)
def test_hf_logit_parity(arch):
    hf_model = tiny_hf_model(arch)
    ids = np.random.default_rng(0).integers(
        0, TINY["vocab"], (2, TINY["seq"])).astype(np.int32)
    expected = hf_logits(hf_model, ids)

    model, params = convert_hf_model(hf_model, use_flash_attention=False,
                                     dtype="float32")
    got = np.asarray(jax.jit(
        lambda p, i: model.apply(p, i, method=type(model).logits))(params, ids))

    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("arch", ["opt", "llama", "gpt_neo"])
def test_decode_matches_full_forward(arch):
    """KV-cached incremental decode must reproduce full-context logits."""
    from deepspeed_tpu.model_implementations import DeepSpeedTransformerInference
    hf_model = tiny_hf_model(arch)
    model, params = convert_hf_model(hf_model, use_flash_attention=False,
                                     dtype="float32")
    ids = np.random.default_rng(1).integers(0, TINY["vocab"], (1, 10)).astype(np.int32)

    full = np.asarray(model.apply(params, jnp.asarray(ids),
                                  method=type(model).logits))

    ds = DeepSpeedTransformerInference(model.config, params=params,
                                      max_batch=1, max_seq_len=32)
    prefill = ds.forward(ids[:, :6])
    np.testing.assert_allclose(np.asarray(prefill), full[:, :6], atol=1e-3,
                               rtol=1e-2)
    for tkn in range(6, 10):
        step = ds.forward(ids[:, tkn:tkn + 1])
        np.testing.assert_allclose(np.asarray(step), full[:, tkn:tkn + 1],
                                   atol=1e-3, rtol=1e-2)


def test_init_inference_takes_torch_model():
    hf_model = tiny_hf_model("opt")
    engine = deepspeed_tpu.init_inference(
        hf_model, config={"dtype": "float32",
                          "tensor_parallel": {"tp_size": 2}})
    ids = np.random.default_rng(2).integers(0, TINY["vocab"], (1, 8)).astype(np.int32)
    logits = engine.forward(ids)
    expected = hf_logits(hf_model, ids)
    np.testing.assert_allclose(np.asarray(logits), expected, atol=2e-3,
                               rtol=2e-2)
    # TP must actually shard something
    assert any(not l.sharding.is_fully_replicated
               for l in jax.tree.leaves(engine.params))


@pytest.mark.parametrize("arch", ["llama", "gpt2", "bloom"])
def test_autotp_rules(arch):
    """AutoTP must emit rules over *converted* names even when the HF
    architecture uses fused/renamed projections (c_attn, query_key_value)."""
    hf_model = tiny_hf_model(arch)
    rules = get_tp_rules(hf_model)
    kinds = dict((pat, kind) for pat, kind in rules)
    assert any("q_proj" in p and k == "col" for p, k in kinds.items()), rules
    assert any("o_proj" in p and k == "row" for p, k in kinds.items()), rules
    assert any("down_proj" in p and k == "row" for p, k in kinds.items()), rules
    assert any(k == "vocab" for k in kinds.values())

    # and the rules must actually shard the converted params
    from deepspeed_tpu.runtime.zero.partition import tp_spec_for
    from deepspeed_tpu.parallel.topology import initialize_topology, reset_topology
    reset_topology()
    topo = initialize_topology(tp=2)
    spec = tp_spec_for("layers/attn/q_proj/kernel", (32, 4, 8), topo.mesh,
                       rules=rules)
    assert "tp" in str(spec), spec
    reset_topology()


def test_policy_for_unknown_raises():
    class FakeCfg:
        model_type = "some_unknown_arch"
    with pytest.raises(NotImplementedError):
        policy_for(FakeCfg())


def test_megatron_checkpoint_loads_with_tp_merge(tmp_path):
    """Megatron-GPT container (reference ``containers/megatron_gpt.py``):
    a GPT-2 computation re-emitted as Megatron-v2 TP shards (fused
    query_key_value in [H,3,D] row order, dense_h_to_4h naming) must merge
    through MegatronSDLoader and reproduce the HF logits exactly."""
    from deepspeed_tpu.module_inject import load_megatron_model

    t = TINY
    hf = tiny_hf_model("gpt2")
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    H, D = t["heads"], t["hidden"] // t["heads"]
    L = t["layers"]

    def v2_qkv(w_in_3h):                       # [in, 3h] → megatron [3h, in]
        w = w_in_3h.T                          # [3h, in], rows [3, H, D]
        return np.ascontiguousarray(
            w.reshape(3, H, D, -1).transpose(1, 0, 2, 3).reshape(3 * H * D, -1))

    def v2_qkv_bias(b):
        return np.ascontiguousarray(
            b.reshape(3, H, D).transpose(1, 0, 2).reshape(-1))

    meg = {"word_embeddings.weight": sd["transformer.wte.weight"],
           "position_embeddings.weight": sd["transformer.wpe.weight"],
           "transformer.final_layernorm.weight": sd["transformer.ln_f.weight"],
           "transformer.final_layernorm.bias": sd["transformer.ln_f.bias"]}
    for i in range(L):
        src, dst = f"transformer.h.{i}", f"transformer.layers.{i}"
        meg[f"{dst}.input_layernorm.weight"] = sd[f"{src}.ln_1.weight"]
        meg[f"{dst}.input_layernorm.bias"] = sd[f"{src}.ln_1.bias"]
        meg[f"{dst}.attention.query_key_value.weight"] = \
            v2_qkv(sd[f"{src}.attn.c_attn.weight"])
        meg[f"{dst}.attention.query_key_value.bias"] = \
            v2_qkv_bias(sd[f"{src}.attn.c_attn.bias"])
        meg[f"{dst}.attention.dense.weight"] = sd[f"{src}.attn.c_proj.weight"].T
        meg[f"{dst}.attention.dense.bias"] = sd[f"{src}.attn.c_proj.bias"]
        meg[f"{dst}.post_attention_layernorm.weight"] = sd[f"{src}.ln_2.weight"]
        meg[f"{dst}.post_attention_layernorm.bias"] = sd[f"{src}.ln_2.bias"]
        meg[f"{dst}.mlp.dense_h_to_4h.weight"] = sd[f"{src}.mlp.c_fc.weight"].T
        meg[f"{dst}.mlp.dense_h_to_4h.bias"] = sd[f"{src}.mlp.c_fc.bias"]
        meg[f"{dst}.mlp.dense_4h_to_h.weight"] = sd[f"{src}.mlp.c_proj.weight"].T
        meg[f"{dst}.mlp.dense_4h_to_h.bias"] = sd[f"{src}.mlp.c_proj.bias"]

    # split into 2 Megatron TP shards: column-parallel → out dim (axis 0),
    # row-parallel → in dim (axis 1); embeddings/norm/row-bias replicated
    from deepspeed_tpu.runtime.state_dict_factory import _classify
    shards = [{}, {}]
    for name, w in meg.items():
        kind = _classify(name)
        if kind == "column":
            axis = 0 if name.endswith("weight") else 0
            parts = np.split(w, 2, axis=axis)
        elif kind == "row" and name.endswith("weight"):
            parts = np.split(w, 2, axis=1)
        else:
            parts = [w, w]
        for r in range(2):
            shards[r][name] = parts[r]
    paths = []
    for r in range(2):
        p = tmp_path / f"mp_rank_{r:02d}_model_states.npz"
        np.savez(p, **shards[r])
        paths.append(str(p))

    model, params = load_megatron_model(paths, num_heads=H,
                                        dtype="float32",
                                        use_flash_attention=False)
    ids = np.random.default_rng(7).integers(0, t["vocab"],
                                            (2, 16)).astype(np.int32)
    got = np.asarray(jax.jit(
        lambda p, i: model.apply(p, i, method=type(model).logits))(params, ids))
    np.testing.assert_allclose(got, hf_logits(hf, ids), atol=1e-4, rtol=1e-4)


def test_megatron_moe_checkpoint_loads():
    """Megatron-DeepSpeed MoE-GPT container (reference
    ``containers/megatron_gpt_moe.py`` MegatronMoELayerPolicy, standard
    moe_type): per-expert MLPs under mlp.deepspeed_moe.experts.
    deepspeed_experts.{e}.* plus the gate wg — auto-detected by
    load_megatron_model, stacked onto the MoE trunk's [E, ...] expert
    params with the gate transposed to [M, E]."""
    from deepspeed_tpu.module_inject import load_megatron_model

    rng = np.random.default_rng(11)
    M, F, H, L, E, V, S = 32, 64, 4, 4, 4, 97, 32
    r = lambda *s: rng.standard_normal(s).astype(np.float32) * 0.05
    sd = {"word_embeddings.weight": r(V, M),
          "position_embeddings.weight": r(S, M),
          "transformer.final_layernorm.weight": np.ones(M, np.float32),
          "transformer.final_layernorm.bias": np.zeros(M, np.float32)}
    for i in range(L):
        p = f"transformer.layers.{i}"
        sd[f"{p}.input_layernorm.weight"] = np.ones(M, np.float32)
        sd[f"{p}.input_layernorm.bias"] = np.zeros(M, np.float32)
        sd[f"{p}.attention.query_key_value.weight"] = r(3 * M, M)
        sd[f"{p}.attention.query_key_value.bias"] = r(3 * M)
        sd[f"{p}.attention.dense.weight"] = r(M, M)
        sd[f"{p}.attention.dense.bias"] = r(M)
        sd[f"{p}.post_attention_layernorm.weight"] = np.ones(M, np.float32)
        sd[f"{p}.post_attention_layernorm.bias"] = np.zeros(M, np.float32)
        if i % 2 == 1:          # expert_interval=2: layers 1, 3 are MoE
            moe = f"{p}.mlp.deepspeed_moe"
            sd[f"{moe}.gate.wg.weight"] = r(E, M)
            for e in range(E):
                ep = f"{moe}.experts.deepspeed_experts.{e}"
                sd[f"{ep}.dense_h_to_4h.weight"] = r(F, M)
                sd[f"{ep}.dense_h_to_4h.bias"] = r(F)
                sd[f"{ep}.dense_4h_to_h.weight"] = r(M, F)
                sd[f"{ep}.dense_4h_to_h.bias"] = r(M)
        else:
            sd[f"{p}.mlp.dense_h_to_4h.weight"] = r(F, M)
            sd[f"{p}.mlp.dense_h_to_4h.bias"] = r(F)
            sd[f"{p}.mlp.dense_4h_to_h.weight"] = r(M, F)
            sd[f"{p}.mlp.dense_4h_to_h.bias"] = r(M)

    model, params = load_megatron_model(dict(sd), num_heads=H,
                                        dtype="float32",
                                        use_flash_attention=False)
    cfg = model.config
    assert cfg.moe_num_experts == E and cfg.moe_every == 2
    assert cfg.moe_expert_bias and not cfg.scan_layers

    # mapping exactness: gate transposed, experts stacked in index order
    moe1 = params["params"]["layers_1"]["moe_mlp"]
    np.testing.assert_array_equal(
        np.asarray(moe1["gate_kernel"]),
        sd["transformer.layers.1.mlp.deepspeed_moe.gate.wg.weight"].T)
    exp = moe1["ExpertsMLP_0"]
    for e in range(E):
        ep = f"transformer.layers.1.mlp.deepspeed_moe.experts." \
             f"deepspeed_experts.{e}"
        np.testing.assert_array_equal(
            np.asarray(exp["experts_wi"])[e], sd[f"{ep}.dense_h_to_4h.weight"].T)
        np.testing.assert_array_equal(
            np.asarray(exp["experts_bi"])[e], sd[f"{ep}.dense_h_to_4h.bias"])
        np.testing.assert_array_equal(
            np.asarray(exp["experts_wo"])[e], sd[f"{ep}.dense_4h_to_h.weight"].T)
        np.testing.assert_array_equal(
            np.asarray(exp["experts_bo"])[e], sd[f"{ep}.dense_4h_to_h.bias"])

    ids = np.random.default_rng(7).integers(0, V, (2, 16)).astype(np.int32)
    logits = np.asarray(jax.jit(
        lambda p, i: model.apply(p, i, method=type(model).logits))(params, ids))
    assert np.isfinite(logits).all()

    # a dense-layer-only checkpoint still routes to the plain GPT policy
    dense_sd = {k: v for k, v in sd.items() if ".deepspeed_moe." not in k}
    for i in (1, 3):
        p = f"transformer.layers.{i}"
        dense_sd[f"{p}.mlp.dense_h_to_4h.weight"] = r(F, M)
        dense_sd[f"{p}.mlp.dense_h_to_4h.bias"] = r(F)
        dense_sd[f"{p}.mlp.dense_4h_to_h.weight"] = r(M, F)
        dense_sd[f"{p}.mlp.dense_4h_to_h.bias"] = r(M)
    model2, _ = load_megatron_model(dense_sd, num_heads=H, dtype="float32",
                                    use_flash_attention=False)
    assert model2.config.moe_num_experts == 0

    # residual moe_type (dense blend branch mlp.mlp.* + mlp.coefficient.*)
    # must be rejected loudly, not silently dropped
    res_sd = dict(sd)
    res_sd["transformer.layers.1.mlp.coefficient.weight"] = r(2, M)
    with pytest.raises(NotImplementedError, match="residual"):
        load_megatron_model(res_sd, num_heads=H)

    # megatron-deepspeed arg name for top-k is 'topk'
    from deepspeed_tpu.module_inject.containers import MegatronGPTMoEPolicy

    class _Args:
        vocab_size, hidden_size, num_layers = V, M, L
        num_attention_heads, ffn_hidden_size = H, F
        max_position_embeddings = S
        num_experts, expert_interval, topk = E, 2, 2

    cfg_topk = MegatronGPTMoEPolicy().build_config(_Args())
    assert cfg_topk.moe_top_k == 2


def test_megatron_moe_offset_pattern_loads():
    """MoE layers that don't start at ``interval - 1`` (here layers 0, 2
    with interval 2) are regular too — the interval comes from the spacing
    between consecutive MoE layers, with the start offset preserved
    (``moe_layer_offset``).  Genuinely irregular spacings still fail
    loudly."""
    from deepspeed_tpu.module_inject import load_megatron_model
    from deepspeed_tpu.module_inject.containers import MegatronGPTMoEPolicy
    from deepspeed_tpu.models.transformer import _is_moe_layer

    rng = np.random.default_rng(13)
    M, F, H, L, E, V, S = 32, 64, 4, 4, 4, 97, 32
    r = lambda *s: rng.standard_normal(s).astype(np.float32) * 0.05
    sd = {"word_embeddings.weight": r(V, M),
          "position_embeddings.weight": r(S, M),
          "transformer.final_layernorm.weight": np.ones(M, np.float32),
          "transformer.final_layernorm.bias": np.zeros(M, np.float32)}
    for i in range(L):
        p = f"transformer.layers.{i}"
        sd[f"{p}.input_layernorm.weight"] = np.ones(M, np.float32)
        sd[f"{p}.input_layernorm.bias"] = np.zeros(M, np.float32)
        sd[f"{p}.attention.query_key_value.weight"] = r(3 * M, M)
        sd[f"{p}.attention.query_key_value.bias"] = r(3 * M)
        sd[f"{p}.attention.dense.weight"] = r(M, M)
        sd[f"{p}.attention.dense.bias"] = r(M)
        sd[f"{p}.post_attention_layernorm.weight"] = np.ones(M, np.float32)
        sd[f"{p}.post_attention_layernorm.bias"] = np.zeros(M, np.float32)
        if i % 2 == 0:          # MoE at layers 0, 2: offset 0, interval 2
            moe = f"{p}.mlp.deepspeed_moe"
            sd[f"{moe}.gate.wg.weight"] = r(E, M)
            for e in range(E):
                ep = f"{moe}.experts.deepspeed_experts.{e}"
                sd[f"{ep}.dense_h_to_4h.weight"] = r(F, M)
                sd[f"{ep}.dense_h_to_4h.bias"] = r(F)
                sd[f"{ep}.dense_4h_to_h.weight"] = r(M, F)
                sd[f"{ep}.dense_4h_to_h.bias"] = r(M)
        else:
            sd[f"{p}.mlp.dense_h_to_4h.weight"] = r(F, M)
            sd[f"{p}.mlp.dense_h_to_4h.bias"] = r(F)
            sd[f"{p}.mlp.dense_4h_to_h.weight"] = r(M, F)
            sd[f"{p}.mlp.dense_4h_to_h.bias"] = r(M)

    assert MegatronGPTMoEPolicy.detect_moe(sd) == (E, 2, 0)
    model, params = load_megatron_model(dict(sd), num_heads=H,
                                        dtype="float32",
                                        use_flash_attention=False)
    cfg = model.config
    assert cfg.moe_every == 2 and cfg.moe_layer_offset == 0
    assert [_is_moe_layer(cfg, i) for i in range(L)] == \
        [True, False, True, False]
    # the stacked expert params landed on the offset layers
    assert "moe_mlp" in params["params"]["layers_0"]
    assert "moe_mlp" not in params["params"]["layers_1"]
    ids = np.random.default_rng(7).integers(0, V, (2, 16)).astype(np.int32)
    logits = np.asarray(jax.jit(
        lambda p, i: model.apply(p, i, method=type(model).logits))(params, ids))
    assert np.isfinite(logits).all()

    # truncated pattern (MoE at 0, 2 but dense at the predicted layer 4 of
    # a 6-layer model) fails loudly too — not a KeyError later in mapping
    trunc = {k: v for k, v in sd.items()}
    for i in (4, 5):
        p = f"transformer.layers.{i}"
        trunc[f"{p}.input_layernorm.weight"] = np.ones(M, np.float32)
        trunc[f"{p}.mlp.dense_h_to_4h.weight"] = r(F, M)
    with pytest.raises(ValueError, match="expert-interval"):
        MegatronGPTMoEPolicy.detect_moe(trunc)

    # irregular spacing (0, 2, 3) still fails loudly
    bad = {k: v for k, v in sd.items()}
    moe = "transformer.layers.3.mlp.deepspeed_moe"
    bad[f"{moe}.gate.wg.weight"] = r(E, M)
    for e in range(E):
        ep = f"{moe}.experts.deepspeed_experts.{e}"
        bad[f"{ep}.dense_h_to_4h.weight"] = r(F, M)
        bad[f"{ep}.dense_h_to_4h.bias"] = r(F)
        bad[f"{ep}.dense_4h_to_h.weight"] = r(M, F)
        bad[f"{ep}.dense_4h_to_h.bias"] = r(M)
    with pytest.raises(ValueError, match="expert-interval"):
        MegatronGPTMoEPolicy.detect_moe(bad)


def test_clip_text_encoder_parity():
    """CLIP text tower (reference ``containers/clip.py``): causal pre-LN
    quick-gelu encoder; our hidden_states must match HF last_hidden_state."""
    torch.manual_seed(5)
    cfg = transformers.CLIPTextConfig(
        vocab_size=99, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, hidden_act="quick_gelu")
    hf = transformers.CLIPTextModel(cfg).eval()
    model, params = convert_hf_model(hf, use_flash_attention=False,
                                     dtype="float32")
    ids = np.random.default_rng(3).integers(0, 99, (2, 12)).astype(np.int32)
    with torch.no_grad():
        want = hf(input_ids=torch.tensor(ids.astype(np.int64)))\
            .last_hidden_state.numpy()
    got = np.asarray(jax.jit(
        lambda p, i: model.apply(p, i, method=type(model).hidden_states))(
            params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_opt_350m_layout_parity():
    """The REAL opt-350m layout (DeepSpeed-Chat's default actor):
    word_embed_proj_dim != hidden_size (project_in/out) AND post-LN blocks
    with no final norm — exact logit parity with HF."""
    cfg = transformers.OPTConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, ffn_dim=64, max_position_embeddings=64,
        word_embed_proj_dim=16, do_layer_norm_before=False)
    hf = transformers.OPTForCausalLM(cfg).eval()
    ids = np.random.default_rng(4).integers(0, 97, (2, 12)).astype(np.int32)
    model, params = convert_hf_model(hf, use_flash_attention=False,
                                     dtype="float32")
    assert model.config.embed_proj_dim == 16
    assert not model.config.pre_layer_norm
    got = np.asarray(jax.jit(
        lambda p, i: model.apply(p, i, method=type(model).logits))(params, ids))
    np.testing.assert_allclose(got, hf_logits(hf, ids), atol=1e-4, rtol=1e-4)

    # KV-cached decode matches the full forward (post-LN + projection)
    from deepspeed_tpu.model_implementations import DeepSpeedTransformerInference
    ds = DeepSpeedTransformerInference(model.config, params=params,
                                       max_batch=2, max_seq_len=32)
    prefill = ds.forward(ids[:, :6])
    np.testing.assert_allclose(np.asarray(prefill), got[:, :6], atol=1e-3,
                               rtol=1e-2)
    step = ds.forward(ids[:, 6:7])
    np.testing.assert_allclose(np.asarray(step), got[:, 6:7], atol=1e-3,
                               rtol=1e-2)

    # chunked loss path with projection (head folds project_out)
    full = float(model.apply(params, {"input_ids": ids}))
    import dataclasses
    ccfg = dataclasses.replace(model.config, loss_seq_chunks=4)
    from deepspeed_tpu.models.transformer import Transformer
    chunked = float(Transformer(ccfg).apply(params, {"input_ids": ids}))
    np.testing.assert_allclose(chunked, full, rtol=1e-5)
