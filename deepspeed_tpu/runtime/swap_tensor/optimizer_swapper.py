"""Optimizer-state swapper: NVMe residency for Adam moments + fp32 masters.

TPU-native equivalent of reference ``runtime/swap_tensor/optimizer_utils.py``
(OptimizerSwapper, ``:112``) and ``partitioned/pipelined_optimizer_swapper.py``:
per-parameter state groups live in swap files; around each host optimizer
step a group is swapped in, updated in place by the C++ Adam
(``csrc/adam/cpu_adam.cpp``), and swapped back out, with the next group's
read overlapped behind the current group's compute (pipeline_read) and the
previous group's write drained lazily (pipeline_write).
"""

import numpy as np

from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper


class SwappedStateGroup:
    """State bundle for one parameter leaf: fp32 master + Adam moments."""

    def __init__(self, name, numel):
        self.name = name
        self.numel = numel
        self.keys = [f"{name}.master", f"{name}.exp_avg", f"{name}.exp_avg_sq"]


class OptimizerSwapper:
    """Manages NVMe residency of per-leaf optimizer state (reference
    ``optimizer_utils.py:112`` OptimizerSwapper; pipelining from
    ``pipelined_optimizer_swapper.py``)."""

    def __init__(self, swap_dir, buffer_count=4, pipeline_read=True,
                 pipeline_write=True, thread_count=4):
        # Separate swappers so prefetch reads never contend with the write
        # drain for pool buffers.
        self._read = AsyncTensorSwapper(swap_dir, buffer_count=buffer_count,
                                        thread_count=thread_count)
        self._write = AsyncTensorSwapper(swap_dir, buffer_count=buffer_count,
                                         thread_count=thread_count)
        self.pipeline_read = pipeline_read
        self.pipeline_write = pipeline_write
        self.groups = {}

    def register(self, name, numel, master, exp_avg, exp_avg_sq):
        """Initial swap-out of a leaf's state (fast_init path: states are
        born on NVMe, reference ``optimizer_utils.py`` initialize_parameters)."""
        g = SwappedStateGroup(name, numel)
        self.groups[name] = g
        for key, arr in zip(g.keys, (master, exp_avg, exp_avg_sq)):
            self._write.swap_out(key, arr)
        self._write.synchronize_writes()
        return g

    def swap_in(self, name, out_master, out_avg, out_avg_sq):
        g = self.groups[name]
        self._read.swap_in(g.keys[0], g.numel, out_master)
        self._read.swap_in(g.keys[1], g.numel, out_avg)
        self._read.swap_in(g.keys[2], g.numel, out_avg_sq)

    def start_swap_in(self, name, bufs):
        """Async read of a group's three state arrays into caller buffers
        (pipeline_read: prefetch behind compute). Buffers must not be
        touched until ``finish_swap_ins``."""
        g = self.groups[name]
        for key, arr in zip(g.keys, bufs):
            self._read.handle.async_pread(arr[:g.numel], self._read.path_for(key))

    def finish_swap_ins(self):
        self._read.handle.wait()

    def swap_out(self, name, master, exp_avg, exp_avg_sq):
        g = self.groups[name]
        for key, arr in zip(g.keys, (master, exp_avg, exp_avg_sq)):
            self._write.swap_out(key, arr[:g.numel])
        if not self.pipeline_write:
            self._write.synchronize_writes()

    def update_master(self, name, master):
        """Overwrite ONLY the master-value file of a group (surgery
        write-back): moments on disk stay untouched."""
        g = self.groups[name]
        self._write.swap_out(g.keys[0], master[:g.numel])
        if not self.pipeline_write:
            self._write.synchronize_writes()

    def drain(self):
        self._write.synchronize_writes()

    def state_files(self):
        return {n: [self._read.path_for(k) for k in g.keys]
                for n, g in self.groups.items()}


class PartitionedParameterSwapper:
    """NVMe tier for *parameter* shards (reference
    ``partitioned_param_swapper.py:36`` AsyncPartitionedParameterSwapper):
    swap bf16/fp32 parameter leaves to files and read them back on demand —
    the storage layer under ``offload_param.device == "nvme"``."""

    def __init__(self, swap_dir, buffer_count=5, thread_count=4):
        self._swap = AsyncTensorSwapper(swap_dir, buffer_count=buffer_count,
                                        thread_count=thread_count)
        self._meta = {}

    def swap_out_param(self, name, array):
        arr = np.ascontiguousarray(array)
        self._meta[name] = (arr.shape, arr.dtype)
        # raw-byte write: view as uint8 through fp32-sized staging is lossy
        # for odd dtypes, so write directly via the handle
        self._swap.handle.async_pwrite(arr.reshape(-1).view(np.uint8),
                                       self._swap.path_for(name))
        self._swap._pending_writes.append(_Hold(arr))

    def synchronize(self):
        self._swap.synchronize_writes()

    def swap_in_param(self, name):
        shape, dtype = self._meta[name]
        out = np.empty(int(np.prod(shape)) * dtype.itemsize, dtype=np.uint8)
        self._swap.handle.sync_pread(out, self._swap.path_for(name))
        return out.view(dtype).reshape(shape)

    def available_params(self):
        return set(self._meta)


class _Hold:
    """Keeps a raw array alive until wait(); mimics SwapBuffer's flag."""

    def __init__(self, arr):
        self.arr = arr
        self.in_flight = True
