"""Attention-kernel registry — the ONE dispatch point for cached attention.

Every cached-attention consumer (the monolithic generate()/serving decode
path, the paged serving path, speculative verify's per-row multi-token
blocks, chunked prefill) routes through this module instead of
hand-threading its own "which kernel can run here?" branch:

* :func:`select_kernel` — the capability-probed dispatch table.  Probes
  are STATIC (shapes, config, backend support — never traced values), so
  the decision is made once at trace time and every caller, including the
  host-side attribution in ``InferenceEngine.prefill_plan()`` and the
  serving engine's stats, sees the same answer the compiled program took.
* :func:`write_and_attend` — the single write-then-attend implementation
  behind ``models.transformer.Attention``: cache-layout resolution
  (monolithic / layer-stacked / paged pool), this step's K/V row write
  (scatter, DUS or kernel-fused aliased write), and the attend through
  the selected kernel.  This collapses what used to be three near-copies
  of the write/gather branch in ``Attention.__call__`` plus the separate
  fused-decode special case.

Modes (the ``KERNEL_MODES`` table, probed in order, first hit wins):

==========================  ==================================================
``pallas_paged_decode``     single-token decode straight over the paged pool
                            (``ops/transformer/paged_attention.py``) — split-K
                            across block-table pages, no gathered virtual view
``pallas_decode``           single-token decode over a monolithic cache
                            (``ops/transformer/decode_attention.py``)
``pallas_chunked_prefill``  multi-token block (chunked prefill, multi-token
                            decode, speculative verify) vs either cache
                            layout, S <= MAX_CHUNK_S
``reference_fallback``      the XLA reference path: paged caches first
                            materialize the ``take_along_axis`` gathered view
                            (``_paged_gather``) and then take whatever
                            ``cached_attention`` does on it — dense masked
                            attention when Pallas is unavailable or a bias
                            rides along.  Paged DECODE landing here is the
                            BENCH_r04 bs128 cliff: it warns once and the
                            serving engine counts it
                            (``stats["paged_attention_fallback"]``)
==========================  ==================================================

A paged cache opts out of the Pallas paged kernels (back to the gather
path, e.g. for A/B benching) via ``ServingConfig.paged_kernel=False``,
which rides the cache dict as a ``paged_kernel_off`` marker — STATIC
pytree structure, so flipping it is a different program, never a retrace
surprise.
"""

import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.flash_attention import pallas_supported
from deepspeed_tpu.utils.logging import warning_once

# the chunk kernel's q block + f32 accumulator scale with S x H x D;
# longer blocks would blow VMEM and keep the dense fallback
MAX_CHUNK_S = 512

# marker key on a cache dict: paged Pallas kernels disabled
# (ServingConfig.paged_kernel=False) — presence only, value unused
PAGED_KERNEL_OFF = "paged_kernel_off"

KERNEL_MODES = (
    "pallas_paged_decode",
    "pallas_decode",
    "pallas_chunked_prefill",
    "reference_fallback",
)


def _probe_paged_decode(s, paged, has_bias, has_window, disabled):
    # the paged kernel has no sliding-window mode: windowed paged decode
    # keeps the gather path (whose monolithic kernel masks the window)
    return (paged and s == 1 and not has_bias and not has_window
            and not disabled and pallas_supported())


def _probe_decode(s, paged, has_bias, has_window, disabled):
    # monolithic decode masks sliding windows in-kernel
    return (not paged and s == 1 and not has_bias and pallas_supported())


def _probe_chunk(s, paged, has_bias, has_window, disabled):
    return (1 < s <= MAX_CHUNK_S and not has_bias and not has_window
            and not (paged and disabled) and pallas_supported())


_REGISTRY = (
    ("pallas_paged_decode", _probe_paged_decode),
    ("pallas_decode", _probe_decode),
    ("pallas_chunked_prefill", _probe_chunk),
)


def select_kernel(*, s, paged=False, has_bias=False, has_window=False,
                  disabled=False):
    """The attention-kernel dispatch decision for one cached-attention
    call.  All inputs are static: ``s`` (this block's token count),
    ``paged`` (block-table pool vs monolithic lanes), ``has_bias``
    (alibi), ``has_window`` (sliding-window layer) and ``disabled``
    (the cache's ``paged_kernel_off`` marker).  Returns a
    :data:`KERNEL_MODES` name; ``reference_fallback`` when no Pallas
    kernel applies."""
    for mode, probe in _REGISTRY:
        if probe(s, paged, has_bias, has_window, disabled):
            return mode
    return "reference_fallback"


def kernel_modes(*, paged, disabled=False, has_bias=False,
                 has_window=False):
    """Host-side attribution of which kernel mode each serving program
    class will take (what ``prefill_plan`` reasons and bench records
    report).  Probes the same table the traced programs dispatch
    through, so the attribution cannot drift from reality."""
    return {
        "decode": select_kernel(s=1, paged=paged, has_bias=has_bias,
                                has_window=has_window, disabled=disabled),
        "prefill_chunk": select_kernel(s=2, paged=paged, has_bias=has_bias,
                                       has_window=has_window,
                                       disabled=disabled),
    }


def _cache_markers(cache):
    """The bookkeeping keys a write must thread through unchanged."""
    return {kk: cache[kk]
            for kk in ("layer", "pages", "per_row", PAGED_KERNEL_OFF)
            if kk in cache}


def _quant_rows(new, kvh):
    """Per-(position, kv-head) symmetric int8 for this step's rows: the
    scale rides a tiny side buffer; the payload keeps the raw
    projection-output layout."""
    B_, S_, KVHD = new.shape
    r = new.reshape(B_, S_, kvh, KVHD // kvh).astype(jnp.float32)
    s = jnp.max(jnp.abs(r), axis=-1) / 127.0
    safe = jnp.where(s == 0.0, 1.0, s)
    pay = jnp.clip(jnp.round(r / safe[..., None]), -127, 127)
    return pay.reshape(B_, S_, KVHD), s


def _write_cache(cache, k_new, v_new, ks_new, vs_new, positions):
    """This step's K/V rows into the cache — ONE implementation of what
    used to be three branch copies: paged pools scatter through the page
    table; monolithic caches (layer-stacked or per-layer) pick the
    per-row-single-token scatter, the per-row multi-token scatter
    (speculative verify) or the row-uniform dynamic_update_slice."""
    import jax
    from deepspeed_tpu.models.transformer import _paged_write
    markers = _cache_markers(cache)
    if "pages" in cache:
        data = _paged_write(cache, k_new, v_new, ks_new, vs_new, positions,
                            per_row=("per_row" in cache))
        return {**data, **markers}
    B_, S_ = k_new.shape[0], k_new.shape[1]
    li = cache.get("layer")
    if "per_row" in cache and S_ == 1:
        # padded-prompt decode: each row writes at ITS OWN position
        # (generated tokens overwrite the right-pad slots, keeping the
        # live cache region contiguous for the decode kernel's length
        # mask).  One native scatter — NOT the default path: the
        # row-uniform dynamic_update_slice below is cheaper and proven
        # on the big stacked cache.
        pos_rows = positions[:, 0]
        rows = jnp.arange(B_)

        def write_rows(buf, new):
            if li is None:
                return buf.at[rows, pos_rows].set(
                    new[:, 0].astype(buf.dtype))
            return buf.at[li, rows, pos_rows].set(
                new[:, 0].astype(buf.dtype))
    elif "per_row" in cache:
        # per-row MULTI-token block (the serving engine's speculative
        # verify): each row writes S_ contiguous positions from ITS OWN
        # start in one batched scatter.  Positions past the buffer (dead
        # lanes' clamped windows) are dropped by scatter's out-of-bounds
        # rule; in-bounds writes land inside the row's own lane.
        rows2d = jnp.arange(B_)[:, None]                 # [B, 1]

        def write_rows(buf, new):
            if li is None:
                return buf.at[rows2d, positions].set(new.astype(buf.dtype))
            return buf.at[li, rows2d, positions].set(new.astype(buf.dtype))
    else:
        # row-uniform write: decode at a shared position, or a
        # multi-token prefill block from the start position
        start = positions[0, 0]

        def write_rows(buf, new):
            if li is None:
                return jax.lax.dynamic_update_slice(
                    buf, new.astype(buf.dtype), (0, start, 0))
            return jax.lax.dynamic_update_slice(
                buf, new[None].astype(buf.dtype), (li, 0, start, 0))

    data = {"k": write_rows(cache["k"], k_new),
            "v": write_rows(cache["v"], v_new)}
    if ks_new is not None:
        data["k_scale"] = write_rows(cache["k_scale"], ks_new)
        data["v_scale"] = write_rows(cache["v_scale"], vs_new)
    return {**data, **markers}


def _fused_decode(cfg, q, k, v, positions, cache, mode, window):
    """Single-token decode through the FUSED-WRITE kernels: the kernel
    writes this step's K/V row (quantizing when the cache is int8) via
    aliased outputs AND attends — no out-of-kernel scatter /
    dynamic_update_slice on the multi-GB cache at all.  Returns
    ``(out [B,1,H,D], new_cache)`` or None when this step must take the
    write-then-attend path (the opt-in int8-MXU mode, unaligned
    layouts, or a non-decode kernel mode).

    Why this exists: the out-of-kernel cache-update chain interleaved
    with the kernel's cache reads makes XLA copy the cache per step once
    it exceeds ~2.2 GB (measured 129 ms/step vs 12.7 fused at
    bs16 x 4k x 24 layers) — the in-place write the reference gets from
    its workspace pointer arithmetic (``inference_context.h:24-87``)
    has to live INSIDE the kernel here."""
    if cfg.decode_int8_matmuls:
        # the int8-MXU score/PV matmuls are unsupported with the fused
        # write (per-row requantization would race the aliased stripe)
        return None
    lengths = (positions[:, 0] + 1).astype(jnp.int32)
    if mode == "pallas_paged_decode":
        if cache["k"].shape[-2] % 8 != 0:
            # write stripes are 8-sublane-aligned; ServingConfig rounds
            # page_size to a multiple of 8, hand-built pools may not
            return None
        from deepspeed_tpu.ops.transformer.paged_attention import (
            paged_decode_attention)
        res = paged_decode_attention(
            q[:, 0], cache["k"], cache["v"], lengths, cache["pages"],
            layer=cache["layer"], k_scale=cache.get("k_scale"),
            v_scale=cache.get("v_scale"), new_k=k[:, 0], new_v=v[:, 0])
    elif mode == "pallas_decode":
        if cache["k"].shape[-2] % 8 != 0:
            # odd cache lengths (hand-allocated test caches) take the
            # unfused path (required_cache_len rounds engine workspaces
            # to a multiple of 8)
            return None
        from deepspeed_tpu.ops.transformer.decode_attention import (
            decode_attention)
        res = decode_attention(
            q[:, 0], cache["k"], cache["v"], lengths,
            layer=cache.get("layer"), k_scale=cache.get("k_scale"),
            v_scale=cache.get("v_scale"), window=window,
            new_k=k[:, 0], new_v=v[:, 0])
    else:
        return None
    if cfg.kv_cache_quant:
        out_f, kc, vc, ksc, vsc = res
        data = {"k": kc, "v": vc, "k_scale": ksc, "v_scale": vsc}
    else:
        out_f, kc, vc = res
        data = {"k": kc, "v": vc}
    return out_f[:, None], {**data, **_cache_markers(cache)}


def _attend(cfg, mode, q, cache, positions, bias, window):
    """The attend half, through the selected kernel mode."""
    from deepspeed_tpu.models.transformer import (_paged_gather,
                                                  cached_attention)
    if "pages" in cache:
        if mode == "pallas_paged_decode":
            from deepspeed_tpu.ops.transformer.paged_attention import (
                paged_decode_attention)
            lengths = (positions[:, 0] + 1).astype(jnp.int32)
            return paged_decode_attention(
                q[:, 0], cache["k"], cache["v"], lengths, cache["pages"],
                layer=cache["layer"], k_scale=cache.get("k_scale"),
                v_scale=cache.get("v_scale"),
                int8_matmuls=cfg.decode_int8_matmuls)[:, None]
        if mode == "pallas_chunked_prefill":
            from deepspeed_tpu.ops.transformer.paged_attention import (
                paged_chunk_prefill_attention)
            starts = positions[:, 0].astype(jnp.int32)
            return paged_chunk_prefill_attention(
                q, cache["k"], cache["v"], starts, cache["pages"],
                layer=cache["layer"], k_scale=cache.get("k_scale"),
                v_scale=cache.get("v_scale"))
        # reference/gather fallback — the pre-kernel paged path: one
        # take_along_axis virtual-view copy per layer, then whatever
        # cached_attention does on the monolithic view.  For DECODE this
        # is the BENCH_r04 bs128 cliff, so it never happens silently.
        if q.shape[1] == 1:
            warning_once(
                "paged decode fell back to the take_along_axis gather "
                "path (" + _fallback_reason(cfg, bias, window, cache)
                + ") — expect the BENCH_r04 bs128 decode cliff; see "
                "docs/serving.md 'Paged attention kernels'")
        g = _paged_gather(cache)
        return cached_attention(
            q, g["k"], g["v"], positions, bias=bias, window=window,
            k_scale=g.get("k_scale"), v_scale=g.get("v_scale"),
            int8_matmuls=cfg.decode_int8_matmuls)
    layer = cache.get("layer")
    return cached_attention(
        q, cache["k"], cache["v"], positions, bias=bias, window=window,
        layer=layer, k_scale=cache.get("k_scale"),
        v_scale=cache.get("v_scale"),
        int8_matmuls=cfg.decode_int8_matmuls)


def _fallback_reason(cfg, bias, window, cache):
    if PAGED_KERNEL_OFF in cache:
        return "serving.paged_kernel=False"
    if bias is not None:
        return "alibi bias"
    if window is not None:
        return "sliding-window layer"
    if not pallas_supported():
        return "no Pallas support on this backend"
    return "unsupported configuration"


def write_and_attend(cfg, q, k, v, positions, cache, *, bias=None,
                     window=None, prefill=False):
    """Write this step's K/V rows into the cache and attend — the single
    entry point behind ``Attention.__call__``'s cached path for EVERY
    cache layout and program class.  Returns ``(out [B,S,H,D],
    new_cache)``.

    ``prefill`` (static): a from-zero multi-token block attends only
    within itself — the attend swaps to causal flash over the fresh
    q/k/v (the dense cached fallback would materialize a [B, H, S,
    S_max] fp32 score tensor, ~33 GB at a 4k prompt); the cache write
    still happens.  (Alibi models keep the dense path: their bias is
    sized to the cache, not the prompt.)"""
    from deepspeed_tpu.models.transformer import _prefill_attention
    B_, S_ = k.shape[0], k.shape[1]
    KVHD = k.shape[-2] * k.shape[-1]
    paged = "pages" in cache
    disabled = PAGED_KERNEL_OFF in cache
    prefill_from_zero = bool(prefill) and S_ > 1 and bias is None
    mode = select_kernel(s=S_, paged=paged, has_bias=bias is not None,
                         has_window=window is not None, disabled=disabled)
    if not prefill_from_zero:
        fused = _fused_decode(cfg, q, k, v, positions, cache, mode, window)
        if fused is not None:
            return fused
    k_new = k.reshape(B_, S_, KVHD)
    v_new = v.reshape(B_, S_, KVHD)
    ks_new = vs_new = None
    if cfg.kv_cache_quant:
        kvh = k.shape[-2]
        k_new, ks_new = _quant_rows(k_new, kvh)
        v_new, vs_new = _quant_rows(v_new, kvh)
    new_cache = _write_cache(cache, k_new, v_new, ks_new, vs_new, positions)
    if prefill_from_zero:
        # one shared prefill attend for every cache layout: the cache
        # was written above; the attention itself is plain causal flash
        # over this block's fresh q/k/v
        out = _prefill_attention(q, k, v, cfg, window=window)
    else:
        out = _attend(cfg, mode, q, new_cache, positions, bias, window)
    return out, new_cache
