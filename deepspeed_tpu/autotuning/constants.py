"""Autotuning constants (reference ``deepspeed/autotuning/constants.py``)."""

AUTOTUNING = "autotuning"
AUTOTUNING_METRIC_LATENCY = "latency"
AUTOTUNING_METRIC_THROUGHPUT = "throughput"
AUTOTUNING_METRIC_FLOPS = "flops"

# Tuner types (reference autotuning/constants.py GRIDSEARCH/RANDOM/MODEL_BASED)
AUTOTUNING_TUNER_GRIDSEARCH = "gridsearch"
AUTOTUNING_TUNER_RANDOM = "random"
AUTOTUNING_TUNER_MODELBASED = "model_based"

# Keys a tuning experiment may override in the DeepSpeed config.
TUNABLE_MICRO_BATCH = "train_micro_batch_size_per_gpu"
TUNABLE_GAS = "gradient_accumulation_steps"
TUNABLE_ZERO_STAGE = "zero_stage"
TUNABLE_REMAT = "remat"

DEFAULT_HBM_BYTES = 16 * (1 << 30)  # v5e-class chip if memory_stats() is mute
DEFAULT_TUNING_MICRO_BATCH_SIZES = (1, 2, 4, 8, 16, 32, 64)

MODEL_INFO_KEY = "model_info"
MODEL_INFO_NUM_PARAMS = "num_params"
MODEL_INFO_PARAM_BYTES = "param_bytes"
