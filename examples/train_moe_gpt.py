"""MoE-GPT training — Megatron-DeepSpeed MoE layout on the TPU trunk:
every 2nd block's MLP is a top-1-gated expert layer sharded over the ``ep``
mesh axis; expert-data-parallel gradient semantics come from the sharding
plan (reference ``deepspeed/moe`` + ``utils/groups.py``).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    DSTPU_ACCELERATOR=cpu python examples/train_moe_gpt.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

# a sitecustomize may pin a hardware platform before this script runs; the
# live jax config must be updated before first device use (env is too late)
if os.environ.get("DSTPU_ACCELERATOR") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def main():
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig

    ep = min(4, jax.device_count())
    cfg = TransformerConfig(
        vocab_size=512, hidden_size=64, num_layers=4, num_heads=4,
        max_seq_len=128, dtype="float32", use_flash_attention=False,
        scan_layers=False, moe_num_experts=2 * ep, moe_every=2,
        moe_top_k=1, moe_ep_size=ep, moe_capacity_factor=1.25)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
                "moe": {"ep_size": ep},
                "zero_optimization": {"stage": 1}})

    rng = np.random.default_rng(0)
    for step in range(10):
        batch = {"input_ids": rng.integers(
            0, 512, (2 * max(engine.topology.dp, 1), 128)).astype(np.int32)}
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        print(f"step {step}: loss {float(jax.device_get(loss)):.4f} "
              f"(incl. aux)")


if __name__ == "__main__":
    main()
