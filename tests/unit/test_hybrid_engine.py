"""Hybrid engine tests — analog of reference ``tests/hybrid_engine``: the
RLHF loop of train-step ↔ rollout-generate on one weight set, plus LoRA
fuse/unfuse."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine


VOCAB = 64


def make_hybrid(zero_stage=3):
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=64, dtype="float32",
                            use_flash_attention=False)
    model = Transformer(cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": zero_stage},
            "hybrid_engine": {"enabled": True},
        })
    return engine


def batch(seed, seq=16):
    r = np.random.default_rng(seed)
    return {"input_ids": r.integers(0, VOCAB, (16, seq)).astype(np.int32)}


def test_initialize_selects_hybrid_engine():
    engine = make_hybrid()
    assert isinstance(engine, DeepSpeedHybridEngine)


def test_quantized_rollouts():
    """hybrid_engine.quantize_rollouts: the inference view holds int8
    payloads (re-derived from the current masters after each step), the
    rollout program dequantizes in-trace, and training always sees the
    exact masters."""
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=64, dtype="float32",
                            use_flash_attention=False)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 3},
            "hybrid_engine": {"enabled": True, "quantize_rollouts": True},
        })
    ids = np.random.default_rng(0).integers(0, VOCAB, (2, 8)).astype(np.int32)
    out = np.asarray(engine.generate(ids, max_new_tokens=6))
    assert out.shape == (2, 14)
    assert (out >= 0).all() and (out < VOCAB).all()
    # the view carries int8 payloads (weights are at-rest quantized)
    from deepspeed_tpu.runtime.weight_quantizer import _is_qw
    view = engine._inference_view()
    qleaves = [l for l in jax.tree.leaves(
        view, is_leaf=_is_qw) if _is_qw(l)]
    assert qleaves, "no quantized leaves in the rollout view"
    # masters stay full precision and training proceeds
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(engine._params)
               if jnp.issubdtype(l.dtype, jnp.floating))
    losses = []
    for i in range(4):
        loss = engine(batch(i))
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0]
    # view re-derives from the stepped masters
    out2 = np.asarray(engine.generate(ids, max_new_tokens=6))
    assert engine._infer_params_step == engine.global_steps
    assert out2.shape == out.shape


def test_quantized_rollouts_with_int8_kv_cache():
    """quantize_rollouts (int8 weight view) composes with kv_cache_quant
    (int8 KV cache) — the full int8 rollout pipeline; training still sees
    exact fp32 masters and the cache knob is inert for training."""
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=64, dtype="float32",
                            use_flash_attention=False, scan_layers=False,
                            kv_cache_quant=True)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 3},
            "hybrid_engine": {"enabled": True, "quantize_rollouts": True},
        })
    ids = np.random.default_rng(0).integers(0, VOCAB, (2, 8)).astype(np.int32)
    out = np.asarray(engine.generate(ids, max_new_tokens=6))
    assert out.shape == (2, 14)
    assert (out >= 0).all() and (out < VOCAB).all()
    losses = []
    for i in range(4):
        loss = engine(batch(i))
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0]
    out2 = np.asarray(engine.generate(ids, max_new_tokens=6))
    assert out2.shape == out.shape


def test_train_generate_interleave():
    engine = make_hybrid(zero_stage=3)
    ids = np.random.default_rng(0).integers(0, VOCAB, (2, 8)).astype(np.int32)

    loss0 = engine(batch(0))
    engine.backward(loss0)
    engine.step()
    out1 = np.asarray(engine.generate(ids, max_new_tokens=6))
    assert out1.shape == (2, 14)
    np.testing.assert_array_equal(out1[:, :8], ids)

    # another train step must invalidate the inference view
    loss1 = engine(batch(1))
    engine.backward(loss1)
    engine.step()
    out2 = np.asarray(engine.generate(ids, max_new_tokens=6))
    assert engine._infer_params_step == engine.global_steps
    # weights moved → rollout should (almost surely) differ
    assert out1.shape == out2.shape

    # training still works after rollouts
    loss2 = engine(batch(2))
    engine.backward(loss2)
    engine.step()
    assert engine.global_steps == 3


def test_generate_matches_inference_engine():
    engine = make_hybrid(zero_stage=2)
    engine(batch(0))  # materialise params
    ids = np.random.default_rng(1).integers(0, VOCAB, (2, 8)).astype(np.int32)
    ours = np.asarray(engine.generate(ids, max_new_tokens=5))

    from deepspeed_tpu.parallel.topology import reset_topology
    reset_topology()
    inf = deepspeed_tpu.init_inference(engine.module,
                                       config={"dtype": "float32"})
    inf.set_params(jax.device_get(engine.params))
    theirs = np.asarray(inf.generate(ids, max_new_tokens=5))
    np.testing.assert_array_equal(ours, theirs)


def test_lora_fuse_unfuse_roundtrip():
    engine = make_hybrid(zero_stage=0)
    engine(batch(0))
    before = jax.device_get(engine.params)

    # rank-2 LoRA on the first layer's up_proj
    from deepspeed_tpu.runtime.zero.partition import path_to_str
    flat = {path_to_str(p): l for p, l in
            jax.tree_util.tree_flatten_with_path(engine.params)[0]}
    target = next(k for k in flat if k.endswith("mlp/up_proj/kernel"))
    shape = flat[target].shape  # [L, in, out]
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((*shape[:-1], 2)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((2, shape[-1])) * 0.1, jnp.float32)

    engine.set_lora({target: (a.reshape(-1, 2), b, 0.5)})
    engine.fuse_lora_weight()
    fused = jax.device_get(engine.params)
    flat_fused = {path_to_str(p): l for p, l in
                  jax.tree_util.tree_flatten_with_path(fused)[0]}
    assert not np.allclose(flat_fused[target], flat[target].addressable_data(0)
                           if hasattr(flat[target], "addressable_data")
                           else flat[target])

    engine.unfuse_lora_weight()
    after = jax.device_get(engine.params)
    for (pa, la), (pb, lb) in zip(
            jax.tree_util.tree_flatten_with_path(before)[0],
            jax.tree_util.tree_flatten_with_path(after)[0]):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=1e-5)


def test_hybrid_rollout_with_padded_prompts():
    """RLHF rollouts take right-padded prompt batches: each row's
    continuation matches its unpadded single-row rollout (greedy)."""
    import numpy as np
    engine = make_hybrid(zero_stage=2)
    rng = np.random.default_rng(11)
    lens = [4, 9, 6]
    P = max(lens)
    ids = np.zeros((3, P), np.int32)
    mask = np.zeros((3, P), np.int32)
    for i, n in enumerate(lens):
        ids[i, :n] = rng.integers(1, engine.module.config.vocab_size, (n,))
        mask[i, :n] = 1
    out = np.asarray(engine.generate(ids, max_new_tokens=5,
                                     attention_mask=mask))
    assert out.shape == (3, P + 5)
    for i, n in enumerate(lens):
        solo = np.asarray(engine.generate(ids[i:i + 1, :n],
                                          max_new_tokens=5))
        np.testing.assert_array_equal(out[i, P:], solo[0, n:])
