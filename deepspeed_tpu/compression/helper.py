"""Pytree-path utilities for the compression subsystem.

The reference walks ``model.named_modules()`` and swaps layers in place
(``deepspeed/compression/helper.py:45 module_replacement``).  TPU-natively a
model is a flax param pytree; a "module" is a subtree whose leaves are
``kernel``/``bias``/``embedding``.  We address modules by '/'-joined paths and
match the config's scope patterns against them.
"""

import fnmatch

import jax


LEAF_NAMES = ("kernel", "bias", "embedding", "scale")


def flatten_params(params):
    """dict {'a/b/kernel': leaf} preserving insertion order."""
    flat = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(prefix + (k,), v)
        else:
            flat["/".join(prefix)] = node

    walk((), params)
    return flat


def unflatten_params(flat):
    root = {}
    for path, leaf in flat.items():
        keys = path.split("/")
        node = root
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = leaf
    return root


def module_paths(params):
    """Paths of 'modules': parents of kernel/embedding leaves."""
    mods = []
    for path in flatten_params(params):
        keys = path.split("/")
        if keys[-1] in ("kernel", "embedding") and len(keys) > 1:
            mod = "/".join(keys[:-1])
            if mod not in mods:
                mods.append(mod)
    return mods


def match_module_scope(pattern, paths):
    """Reference ``compress.py:25 get_module_name``: a scope entry matches by
    wildcard or substring.  Patterns may use '.' or '/' separators."""
    pattern = pattern.replace(".", "/")
    if any(c in pattern for c in "*?["):
        return [p for p in paths if fnmatch.fnmatch(p, pattern)
                or fnmatch.fnmatch(p, "*" + pattern)
                or fnmatch.fnmatch(p, "*" + pattern + "*")]
    return [p for p in paths if pattern in p]


def get_by_path(params, path):
    node = params
    for k in path.split("/"):
        node = node[k]
    return node


def set_by_path(params, path, value):
    """Functional set: returns a new tree (shares unmodified subtrees)."""
    keys = path.split("/")

    def rec(node, i):
        new = dict(node)
        if i == len(keys) - 1:
            new[keys[i]] = value
        else:
            new[keys[i]] = rec(node[keys[i]], i + 1)
        return new

    return rec(params, 0)


def module_weight_path(params, mod_path):
    """The main weight leaf of a module (kernel or embedding)."""
    node = get_by_path(params, mod_path)
    for name in ("kernel", "embedding"):
        if isinstance(node, dict) and name in node:
            return mod_path + "/" + name
    raise KeyError(f"no weight leaf under {mod_path}")


def tree_size(params):
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
