from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper, SwapBuffer
from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import (
    OptimizerSwapper, PartitionedParameterSwapper)
