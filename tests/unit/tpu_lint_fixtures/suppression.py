"""Suppression fixture: line-level, function-level, and a non-matching rule
id that must NOT suppress."""
import jax
from deepspeed_tpu.tools.lint.hotpath import hot_path


@hot_path("fixture.step")
def step_with_line_suppression(loss):
    return loss.item()  # tpu-lint: disable=TL001 -- read once per epoch for logging


@hot_path("fixture.step2")
def step_with_function_suppression(loss):  # tpu-lint: disable=TL001 -- whole function is a host-side drain
    a = loss.item()
    b = jax.device_get(loss)
    return a, b


@hot_path("fixture.step3")
def step_with_wrong_rule(loss):
    return loss.item()  # tpu-lint: disable=TL002 -- wrong id, TL001 still fires
