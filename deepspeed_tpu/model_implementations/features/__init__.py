from deepspeed_tpu.model_implementations.features.cuda_graph import (  # noqa: F401
    CompiledGraphModule)
