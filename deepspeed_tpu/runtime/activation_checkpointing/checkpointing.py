"""Activation checkpointing (rematerialisation) subsystem.

Capability parity with the reference's
``deepspeed/runtime/activation_checkpointing/checkpointing.py``
(``configure() :789``, ``checkpoint() :708``, ``CheckpointFunction :474``,
partitioned activations ``:366``, CPU checkpointing ``:461``,
``CudaRNGStatesTracker :121``, ``model_parallel_cuda_manual_seed :198``) —
designed TPU-first rather than ported:

* The reference re-runs the forward in ``torch.autograd.Function.backward``
  and hand-manages RNG state save/restore.  On TPU the whole mechanism is
  ``jax.checkpoint`` (remat): XLA re-materialises the forward inside the
  backward pass, and JAX's splittable PRNG keys make RNG state tracking
  unnecessary — the same key threaded into the remat region reproduces the
  same dropout mask by construction.
* "Partitioned activations across MP ranks" is a *sharding annotation* here:
  saved residuals are constrained to be sharded over the tp axis
  (``jax.lax.with_sharding_constraint``) instead of being manually
  scattered/gathered.
* "CPU checkpointing" maps to offloading saved residuals to host memory via
  ``jax.checkpoint`` offload policies (``save_and_offload_only_these_names``)
  when available, else a conservative ``nothing_saveable`` policy (recompute
  everything — the strictly-lower-memory option).
* "Contiguous memory" optimisation is XLA's job (buffer assignment); the knob
  is accepted and ignored with a log line for config compatibility.

Usage matches the reference::

    from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
    checkpointing.configure(None, deepspeed_config=cfg)
    y = checkpointing.checkpoint(fn, *args)          # remat'd call
    ckpt_fn = checkpointing.checkpoint_wrapper(fn)   # decorator form
"""

import functools

import jax

from deepspeed_tpu.utils.logging import logger

# ---------------------------------------------------------------------------
# Module state (mirrors the reference's module-level globals :60-100)
# ---------------------------------------------------------------------------
_config = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "number_checkpoints": None,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "policy": "nothing_saveable",
}
_configured = False

# Named policies exposed 1:1 from jax.checkpoint_policies, plus aliases that
# describe intent in reference vocabulary.
_POLICY_ALIASES = {
    "full": "everything_saveable",          # no recompute (checkpointing off)
    "none": "nothing_saveable",             # recompute everything
    "dots": "dots_saveable",
    "dots_no_batch": "dots_with_no_batch_dims_saveable",
}


def _resolve_policy(name):
    if name is None:
        return None
    name = _POLICY_ALIASES.get(name, name)
    pol = getattr(jax.checkpoint_policies, name, None)
    if pol is None:
        logger.warning(f"unknown remat policy {name!r}; using nothing_saveable")
        pol = jax.checkpoint_policies.nothing_saveable
    return pol


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None,
              policy=None):
    """Configure the subsystem (reference ``configure() :789``).

    Accepts either a DeepSpeedConfig-style object with an
    ``activation_checkpointing`` block or explicit kwargs.
    """
    global _configured
    block = {}
    if deepspeed_config is not None:
        getter = getattr(deepspeed_config, "activation_checkpointing_config", None)
        if getter is not None:
            block = dict(getter) if isinstance(getter, dict) else {
                k: getattr(getter, k)
                for k in ("partition_activations", "contiguous_memory_optimization",
                          "cpu_checkpointing", "number_checkpoints",
                          "synchronize_checkpoint_boundary", "profile")
                if hasattr(getter, k)
            }
        elif isinstance(deepspeed_config, dict):
            block = deepspeed_config.get("activation_checkpointing", {})
    for k, v in block.items():
        if k in _config and v is not None:
            _config[k] = v
    overrides = {
        "partition_activations": partition_activations,
        "contiguous_memory_optimization": contiguous_checkpointing,
        "number_checkpoints": num_checkpoints,
        "cpu_checkpointing": checkpoint_in_cpu,
        "synchronize_checkpoint_boundary": synchronize,
        "profile": profile,
        "policy": policy,
    }
    for k, v in overrides.items():
        if v is not None:
            _config[k] = v
    if _config["contiguous_memory_optimization"]:
        logger.info("contiguous_memory_optimization: handled by XLA buffer "
                    "assignment on TPU; accepted as a no-op")
    _configured = True
    logger.info(f"activation checkpointing configured: {_config}")


def is_configured():
    """Reference ``is_configured() :871``."""
    return _configured


def get_config():
    return dict(_config)


def _remat_kwargs():
    pol = _resolve_policy(_config.get("policy"))
    if _config.get("cpu_checkpointing"):
        # Offload saved residuals to host RAM: the analog of the reference's
        # CPU checkpointing (:461).  Requires a policy that names offloadable
        # residuals; the broad form offloads everything that would be saved.
        offload = getattr(jax.checkpoint_policies,
                          "offload_dot_with_no_batch_dims", None)
        if offload is not None:
            try:
                pol = offload("device", "pinned_host")
            except Exception:
                logger.warning("host-offload checkpoint policy unavailable; "
                               "falling back to recompute-all")
                pol = jax.checkpoint_policies.nothing_saveable
        else:
            pol = jax.checkpoint_policies.nothing_saveable
    return {"policy": pol}


# wrapped-callable cache: rebuilding jax.jit per checkpoint() call would
# give every call an empty compilation cache (a full retrace+compile per
# training step on the eager path).  Bounded LRU (a weak-keyed dict cannot
# work here: the wrapper's closure references the function, so entries
# would be immortal); fresh per-call closures at worst cycle the LRU.
def _config_key():
    return (_config.get("policy"), bool(_config.get("cpu_checkpointing")))


@functools.lru_cache(maxsize=64)
def _build_wrapped(function, cfg_key):
    del cfg_key            # part of the cache key; _remat_kwargs reads live config
    fn = jax.checkpoint(function, **_remat_kwargs())
    if _config.get("cpu_checkpointing"):
        # the host-offload policy's TransferToMemoryKind is only legal under
        # jit; wrapping is free inside an outer jit (inlined) and makes the
        # eager/grad-only path legal too
        fn = jax.jit(fn)
    return fn


def _wrapped(function):
    return _build_wrapped(function, _config_key())


def checkpoint(function, *args, **kwargs):
    """Remat'd call of ``function(*args)`` (reference ``checkpoint() :708``).

    Unlike the reference this is traceable — it can (and should) be used
    inside jitted train steps; XLA schedules the recompute.
    """
    return _wrapped(function)(*args, **kwargs)


def checkpoint_wrapper(function):
    """Decorator form: returns a remat'd version of ``function``."""
    return functools.wraps(function)(_wrapped(function))


def partition_activations_in_checkpoint(partition_activation):
    """Reference ``:720`` — toggle activation partitioning."""
    _config["partition_activations"] = bool(partition_activation)


def partition_saved(x, tp_axis="tp"):
    """Constrain a saved activation to be sharded over the tp mesh axis —
    the TPU analog of ``partition_activations(args, ...) :366``.  Call inside
    a model's block on residuals when partition_activations is on."""
    if not _config["partition_activations"]:
        return x
    try:
        from jax.sharding import PartitionSpec as P
        spec = [None] * x.ndim
        # shard the hidden (last) dim over tp, matching Megatron's scheme
        spec[-1] = tp_axis
        return jax.lax.with_sharding_constraint(x, P(*spec))
    except Exception:
        return x


# ---------------------------------------------------------------------------
# RNG tracker parity shims.  JAX PRNG keys are functional: forking a key per
# region replaces the reference's save/restore of CUDA RNG states
# (CudaRNGStatesTracker :121).  These shims keep Megatron-style call sites
# working.
# ---------------------------------------------------------------------------
class RNGStatesTracker:
    """Functional stand-in for ``CudaRNGStatesTracker`` (:121): maps state
    names to PRNG keys; ``fork`` yields a fresh subkey deterministically."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        self.states_[name] = jax.random.key(seed)

    def fork(self, name="model-parallel-rng"):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            if name not in self.states_:
                raise Exception(f"rng state {name} not added")
            self.states_[name], sub = tuple(
                jax.random.split(self.states_[name]))
            yield sub
        return _ctx()


_RNG_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker():
    """Name kept for call-site parity (reference ``:193``)."""
    return _RNG_TRACKER


get_rng_tracker = get_cuda_rng_tracker


def model_parallel_cuda_manual_seed(seed):
    """Reference ``:198``: seed a default state plus a tp-offset state so
    dropout differs across tp ranks where it should."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("model-parallel-rng", seed + 2718)
    _RNG_TRACKER.add("data-parallel-rng", seed)
    return _RNG_TRACKER


model_parallel_manual_seed = model_parallel_cuda_manual_seed


def reset():
    """Test helper: restore defaults."""
    global _configured
    _configured = False
    _config.update({
        "partition_activations": False,
        "contiguous_memory_optimization": False,
        "cpu_checkpointing": False,
        "number_checkpoints": None,
        "synchronize_checkpoint_boundary": False,
        "profile": False,
        "policy": "nothing_saveable",
    })
