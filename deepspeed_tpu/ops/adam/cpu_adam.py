"""Host (CPU) Adam for offloaded optimizer states — ZeRO-Offload's engine.

TPU-native equivalent of reference ``deepspeed/ops/adam/cpu_adam.py:13``
(DeepSpeedCPUAdam) over ``csrc/adam/cpu_adam.cpp``: optimizer states live in
host RAM as fp32 numpy arrays; the update is a C++ OpenMP+SIMD kernel
(``csrc/adam/cpu_adam.cpp`` here, built lazily via ctypes); the updated
params are narrowed to bfloat16 in the same pass for the host->device upload
(reference's fp16 copy-back, ``cpu_adam.cpp`` param_half path).

Falls back to a vectorized numpy implementation when the C++ toolchain is
unavailable so the offload path stays functional everywhere.
"""

import ctypes

import numpy as np

_lib = None
_lib_err = None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        from deepspeed_tpu.ops.native_build import load_library, csrc_path
        lib = load_library("ds_cpu_adam", [csrc_path("adam", "cpu_adam.cpp")])
        lib.ds_adam_step.restype = None
        lib.ds_adam_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_void_p]
        lib.ds_adagrad_step.restype = None
        lib.ds_adagrad_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_void_p]
        _lib = lib
    except Exception as e:  # toolchain missing: numpy fallback
        _lib_err = e
        _lib = None
    return _lib


def is_available():
    """True when the native kernel built (ds_report probing,
    reference op_builder/cpu_adam.py CPUAdamBuilder.is_compatible)."""
    return _load() is not None


def build_error():
    _load()
    return _lib_err


def _ptr(a):
    return a.ctypes.data_as(ctypes.c_void_p)


def adam_step(params, exp_avg, exp_avg_sq, grads, lr, beta1, beta2, eps,
              weight_decay, adamw_mode, bias_correction, step, bf16_out=None):
    """In-place fused Adam over contiguous fp32 numpy arrays."""
    n = params.size
    lib = _load()
    if lib is not None:
        lib.ds_adam_step(_ptr(params), _ptr(exp_avg), _ptr(exp_avg_sq),
                         _ptr(grads), n, lr, beta1, beta2, eps, weight_decay,
                         int(adamw_mode), int(bias_correction), int(step),
                         _ptr(bf16_out) if bf16_out is not None else None)
        return
    # numpy fallback (same math, see csrc/adam/cpu_adam.cpp)
    bc1 = 1.0 - beta1 ** step if bias_correction else 1.0
    bc2 = 1.0 - beta2 ** step if bias_correction else 1.0
    g = grads
    if not adamw_mode and weight_decay > 0.0:
        g = g + weight_decay * params
    np.multiply(exp_avg, beta1, out=exp_avg)
    exp_avg += (1.0 - beta1) * g
    np.multiply(exp_avg_sq, beta2, out=exp_avg_sq)
    exp_avg_sq += (1.0 - beta2) * np.square(g)
    denom = np.sqrt(exp_avg_sq) / np.sqrt(bc2) + eps
    if adamw_mode and weight_decay > 0.0:
        params *= 1.0 - lr * weight_decay
    params -= (lr / bc1) * (exp_avg / denom)
    if bf16_out is not None:
        _np_f32_to_bf16(params, bf16_out)


def adagrad_step(params, exp_avg_sq, grads, lr, eps, weight_decay, bf16_out=None):
    """In-place fused Adagrad (reference csrc/adagrad/cpu_adagrad.cpp)."""
    lib = _load()
    if lib is not None:
        lib.ds_adagrad_step(_ptr(params), _ptr(exp_avg_sq), _ptr(grads),
                            params.size, lr, eps, weight_decay,
                            _ptr(bf16_out) if bf16_out is not None else None)
        return
    g = grads
    if weight_decay > 0.0:
        g = g + weight_decay * params
    exp_avg_sq += np.square(g)
    params -= lr * g / (np.sqrt(exp_avg_sq) + eps)
    if bf16_out is not None:
        _np_f32_to_bf16(params, bf16_out)


def _np_f32_to_bf16(src, out_u16):
    x = src.view(np.uint32)
    rounding = np.uint32(0x7FFF) + ((x >> np.uint32(16)) & np.uint32(1))
    np.copyto(out_u16, ((x + rounding) >> np.uint32(16)).astype(np.uint16))


class DeepSpeedCPUAdam:
    """Stateful host Adam over a list of flat fp32 shards (reference
    ``deepspeed/ops/adam/cpu_adam.py:13`` API shape: per-group step with
    fp16 (here bf16) copy-out).

    ``params`` is a list of 1-D fp32 numpy arrays (the host-resident master
    shards). ``step(grads, bf16_outs)`` updates them in place.
    """

    def __init__(self, params, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, bias_correction=True, adamw_mode=True):
        self.params = [np.ascontiguousarray(p, dtype=np.float32) for p in params]
        self.exp_avg = [np.zeros_like(p) for p in self.params]
        self.exp_avg_sq = [np.zeros_like(p) for p in self.params]
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.adamw_mode = adamw_mode
        self.step_count = 0

    def step(self, grads, bf16_outs=None, lr=None):
        self.step_count += 1
        lr = self.lr if lr is None else lr
        for i, (p, g) in enumerate(zip(self.params, grads)):
            out = bf16_outs[i] if bf16_outs is not None else None
            adam_step(p, self.exp_avg[i], self.exp_avg_sq[i],
                      np.ascontiguousarray(g, dtype=np.float32),
                      lr, self.beta1, self.beta2, self.eps, self.weight_decay,
                      self.adamw_mode, self.bias_correction, self.step_count,
                      bf16_out=out)

    def state_dict(self):
        return {"step": self.step_count, "exp_avg": self.exp_avg,
                "exp_avg_sq": self.exp_avg_sq}

    def load_state_dict(self, sd):
        self.step_count = sd["step"]
        self.exp_avg = [np.ascontiguousarray(a, np.float32) for a in sd["exp_avg"]]
        self.exp_avg_sq = [np.ascontiguousarray(a, np.float32) for a in sd["exp_avg_sq"]]
