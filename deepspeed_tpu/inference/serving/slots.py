"""Slot-lane programs for the continuous-batching serving engine.

The fixed-shape contract (``docs/serving.md``): the KV workspace holds
``num_slots`` cache lanes ``[L, num_slots, cache_len, KVH*D]`` and every
piece of per-slot occupancy state (last token, write position, live flag,
steps remaining, eos id) is a TRACED argument — so admissions, EOS
retirements and request churn never change a program shape, and exactly ONE
decode-step executable serves the whole server lifetime (compiled once per
process — the serving programs bypass the persistent caches, see
``ServingEngine.__init__``).

Two programs:

* :func:`make_decode_block_fn` — the decode step.  One call advances every
  slot ``block`` tokens through the model's per-row decode path (rank-1
  ``start_pos`` selects the scatter cache write and the per-row length
  masks; free/retired lanes write masked garbage that the next occupant
  overwrites position-by-position before ever attending to it).  The cache
  AND the slot state are donated — the workspace updates in place.
* :func:`make_admit_fn` — admission, fused into one dispatch: sample the
  first token from the prefill's last-position logits (the SAME sampling
  rule the decode step uses, ``build_sample_fn`` — keeping serving
  outputs bitwise equal to solo ``generate()`` runs under greedy
  decoding), insert the prefilled single-lane cache into the slot's lane
  (``dynamic_update_slice`` over the traced slot index; cache donated),
  and write the slot's state entries in-program — so the host scheduler
  never synchronizes inside the admission path.

Per-step semantics mirror ``make_generate_fn``'s decode loop exactly
(write K/V at ``pos``, sample from the new logits, emit ``eos`` once done,
advance ``pos``) — that is what makes the scheduler-correctness contract
("every request's tokens == its solo generate() run") hold bitwise.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.tools.lint.hotpath import hot_path

# the slot-state pytree: every leaf is a [num_slots] vector, every one a
# traced argument of the decode step (and donated through it)
SLOT_STATE_KEYS = ("token", "pos", "active", "remaining", "eos")


def init_slot_state(num_slots):
    """Host-side slot state: all lanes free.  ``eos=-1`` never matches a
    sampled token (ids are >= 0), so free lanes emit -1 and retire nothing."""
    import numpy as np
    return {
        "token": np.zeros((num_slots,), np.int32),
        "pos": np.zeros((num_slots,), np.int32),
        "active": np.zeros((num_slots,), bool),
        "remaining": np.zeros((num_slots,), np.int32),
        "eos": np.full((num_slots,), -1, np.int32),
    }


def make_decode_block_fn(module, sample_fn, param_transform, block,
                         cache_len):
    """The single reusable decode-step program:
    ``fn(params, cache, state, rng) -> (tokens [block, N], cache, state)``
    with the cache and slot state donated (argnums 1, 2).

    Each of the ``block`` in-program steps writes every slot's pending
    token at its own ``pos`` (per-row scatter write + per-row length
    mask), samples the next token, emits the slot's ``eos`` for lanes that
    already finished, and flips ``active`` off when a lane emits its eos
    or exhausts ``remaining`` — identical math to ``make_generate_fn``'s
    loop body, so greedy serving tokens match solo ``generate()`` bitwise.
    Retired/free lanes keep decoding as masked no-ops for at most
    ``block - 1`` steps until the host scheduler reclaims them; their
    writes land at a clamped ``pos`` and are overwritten by the next
    occupant before any of its queries can attend to them.
    """
    deq = param_transform if param_transform is not None else (lambda p: p)

    @hot_path("serving.decode_step")
    def decode_block(params, cache, state, rng):
        eos = state["eos"]

        def step(carry, _):
            cache, tok, pos, active, remaining, rng = carry
            logits, cache = module.apply(deq(params), tok[:, None], cache,
                                         pos, method=type(module).decode)
            rng, sub = jax.random.split(rng)
            nxt = sample_fn(logits[:, -1], sub).astype(jnp.int32)
            nxt = jnp.where(active, nxt, eos)
            done_now = active & ((nxt == eos) | (remaining <= 1))
            active = active & jnp.logical_not(done_now)
            # clamp: identity for live lanes (submit() bounds
            # prompt+max_new by cache_len); keeps dead lanes' masked
            # no-op writes inside the buffer forever
            pos = jnp.minimum(pos + 1, cache_len - 1)
            remaining = jnp.maximum(remaining - 1, 0)
            return (cache, nxt, pos, active, remaining, rng), nxt

        (cache, tok, pos, active, remaining, _), toks = jax.lax.scan(
            step, (cache, state["token"], state["pos"], state["active"],
                   state["remaining"], rng), None, length=block)
        new_state = {"token": tok, "pos": pos, "active": active,
                     "remaining": remaining, "eos": eos}
        return toks, cache, new_state

    return jax.jit(decode_block, donate_argnums=(1, 2))


def make_admit_fn(sample_fn):
    """The fused admission program:
    ``fn(cache, state, lane, logits, rng, slot, pos0, max_new, eos)
    -> (cache, state, first_token)`` with the cache and slot state
    donated (argnums 0, 1).

    One dispatch does everything an admission needs ON DEVICE: sample the
    first token from the prefill's last-position logits (same fp32 rule
    as the decode step — ``build_sample_fn`` — so greedy admission tokens
    match solo runs bitwise), write the [L, 1, S, ...] prefilled lane into
    slot ``slot`` of the big cache (``dynamic_update_slice`` over the
    traced slot index), and flip the slot's state entries live — inactive
    when the request already finished at admission (first token == eos,
    or ``max_new == 1``).  Because the state write happens in-program,
    the host scheduler never has to synchronize on the first token before
    the next decode block can be dispatched: it reads ``first_token``
    lazily, one block behind (see ``ServingEngine``)."""

    @hot_path("serving.admit")
    def admit(cache, state, lane, logits, rng, slot, pos0, max_new, eos):
        first = sample_fn(logits[:, 0], rng).astype(jnp.int32)[0]

        def ins(buf, lbuf):
            return jax.lax.dynamic_update_slice(
                buf, lbuf.astype(buf.dtype), (0, slot, 0, 0))

        cache = {k: ins(cache[k], lane[k]) for k in cache}
        # finished-at-admission: eos on the first token (eos=-1 never
        # matches: sampled ids are >= 0), or a 1-token request
        active0 = (max_new > 1) & jnp.logical_not(first == eos)
        upd = lambda arr, val: arr.at[slot].set(val)
        state = {"token": upd(state["token"], first),
                 "pos": upd(state["pos"], pos0),
                 "active": upd(state["active"], active0),
                 "remaining": upd(state["remaining"],
                                  jnp.maximum(max_new - 1, 0)),
                 "eos": upd(state["eos"], eos)}
        return cache, state, first

    return jax.jit(admit, donate_argnums=(0, 1))


# --------------------------------------------------------------------- #
# Paged variants (docs/serving.md "Paged KV cache"): the KV workspace is
# a page POOL [L, num_pages, page_size, KVH*D] shared by all slots, and
# the per-slot page tables ([num_slots, pages_per_slot] int32) arrive as
# a TRACED argument on every dispatch — the host allocates/frees/shares
# pages, the programs' shapes never change.  Prefill writes land in the
# pool directly (make_paged_chunk_fn), so the paged admit has no lane to
# insert: it only samples the first token and flips the slot state.
# --------------------------------------------------------------------- #

def _paged_kernel_marker(paged_kernel):
    """Cache-dict marker for ``serving.paged_kernel=False``: its PRESENCE
    (static pytree structure) routes the attention-kernel registry back to
    the pre-kernel ``take_along_axis`` gather path — A/B benching the
    paged Pallas kernels without a code change.  Built INSIDE the traced
    program so dispatch signatures (and donation) never change."""
    if paged_kernel:
        return {}
    return {"paged_kernel_off": jnp.zeros((), jnp.int32)}


def make_paged_decode_block_fn(module, sample_fn, param_transform, block,
                               cache_len, paged_kernel=True):
    """The paged decode step:
    ``fn(params, cache, state, pages, rng) -> (tokens, cache, state)``
    with the page POOL and the slot state donated (argnums 1, 2) and the
    page table a plain traced input (tiny; rebuilt host-side per
    dispatch).  ``cache_len`` is the VIRTUAL lane length
    (pages_per_slot * page_size) — the dead-lane position clamp bound.
    Per-step math is identical to :func:`make_decode_block_fn`; only the
    cache write/read routes through the page table (see
    ``models/transformer.py`` ``_paged_write``/``_paged_gather``), so
    greedy paged serving stays bitwise equal to solo ``generate()``."""
    deq = param_transform if param_transform is not None else (lambda p: p)

    @hot_path("serving.decode_step_paged")
    def decode_block(params, cache, state, pages, rng):
        eos = state["eos"]

        def step(carry, _):
            cache, tok, pos, active, remaining, rng = carry
            # inactive lanes decode as masked no-ops but still WRITE a
            # k/v row each step — point their whole table row at the
            # trash page so the write can never land in pages the host
            # already handed to a newer occupant.  (The monolithic path
            # tolerates those writes because the next admit re-inserts
            # the whole lane; paged prefill writes the pool directly
            # BEFORE the admit flips `active`, so an unmasked free-lane
            # write here would corrupt a freshly prefilled prompt.)
            safe_pages = jnp.where(active[:, None], pages, 0)
            logits, cache = module.apply(
                deq(params), tok[:, None],
                {**cache, "pages": safe_pages,
                 **_paged_kernel_marker(paged_kernel)},
                pos, method=type(module).decode)
            rng, sub = jax.random.split(rng)
            nxt = sample_fn(logits[:, -1], sub).astype(jnp.int32)
            nxt = jnp.where(active, nxt, eos)
            done_now = active & ((nxt == eos) | (remaining <= 1))
            active = active & jnp.logical_not(done_now)
            # dead lanes clamp to the last virtual position — its table
            # entry is the trash page once the host processed retirement
            pos = jnp.minimum(pos + 1, cache_len - 1)
            remaining = jnp.maximum(remaining - 1, 0)
            return (cache, nxt, pos, active, remaining, rng), nxt

        (cache, tok, pos, active, remaining, _), toks = jax.lax.scan(
            step, (cache, state["token"], state["pos"], state["active"],
                   state["remaining"], rng), None, length=block)
        new_state = {"token": tok, "pos": pos, "active": active,
                     "remaining": remaining, "eos": eos}
        return toks, cache, new_state

    return jax.jit(decode_block, donate_argnums=(1, 2))


def make_paged_chunk_fn(module, param_transform, paged_kernel=True):
    """The paged admission-prefill chunk program:
    ``fn(params, cache, pages, chunk_ids, start, logits_at)`` — same
    body as the engine's per-chunk program but writing straight into the
    slot's pool pages through its ``[1, pages_per_slot]`` table row (no
    single-lane staging cache, no admit-time insert).  The POOL is
    donated (argnum 1); the table row is a separate traced input so the
    donation aliases cleanly."""
    deq = param_transform if param_transform is not None else (lambda p: p)

    @hot_path("serving.prefill_chunk_paged")
    def chunk_step(params, cache, pages, chunk_ids, start, logits_at):
        return module.apply(deq(params), chunk_ids,
                            {**cache, "pages": pages,
                             **_paged_kernel_marker(paged_kernel)}, start,
                            method=type(module).decode,
                            logits_at=logits_at)

    return jax.jit(chunk_step, donate_argnums=(1,))


# --------------------------------------------------------------------- #
# Speculative decoding (docs/serving.md "Speculative decoding"): a small
# DRAFT model proposes k tokens per live slot, the target model verifies
# all of them in ONE batched forward, and the accepted prefix advances
# both KV caches through the existing per-row scatter writes.  Fixed k,
# accept math entirely in-program, the accept-mask and per-slot accepted
# length as traced values riding the donated slot state — so exactly one
# draft-propose program and one verify-and-commit program serve the whole
# server lifetime, like every other slot program.  Greedy committed
# tokens are the TARGET's sample_fn outputs over the committed history,
# which is what keeps speculative serving bitwise equal to the
# non-speculative decode step.
# --------------------------------------------------------------------- #

def _spec_commit(t, draft, state, k, cache_len):
    """The in-program accept-and-commit rule shared by the monolithic and
    paged verify programs.

    ``t`` ``[N, k+1]``: the target's sampled token at every window
    position (``t[:, i]`` is sampled from the logits AFTER feeding
    ``[token, d_1..d_i]``); ``draft`` ``[N, k]``: the draft proposals.
    Token ``t[:, i]`` is committed iff every earlier draft matched
    (``d_j == t_j`` for ``j < i`` — the leading-match prefix, so every
    committed token is exactly what the non-speculative decode step
    would have sampled), the slot still had budget (``i < remaining``),
    no earlier committed token was the slot's ``eos``, and the lane is
    live.  Returns ``(tokens [k+1, N], accepted [N], new_state)`` with
    the same emit/retire conventions as ``make_decode_block_fn``:
    uncommitted positions emit the slot's ``eos``, lanes retire
    in-program on eos or budget exhaustion, dead lanes commit nothing."""
    eos, active = state["eos"], state["active"]
    remaining, pos = state["remaining"], state["pos"]
    # leading-match prefix: how many drafts the target reproduced
    match = (draft == t[:, :k]).astype(jnp.int32)            # [N, k]
    n_match = jnp.sum(jnp.cumprod(match, axis=1), axis=1)    # [N]
    m_raw = 1 + n_match                                      # 1..k+1
    idx = jnp.arange(k + 1)[None, :]
    base = (idx < m_raw[:, None]) & (idx < remaining[:, None]) \
        & active[:, None]
    eos_hit = base & (t == eos[:, None])
    # commit stops AFTER the first committed eos (inclusive) — the same
    # per-step rule the non-spec block applies, folded over the window
    ex_eos = jnp.cumsum(eos_hit.astype(jnp.int32), axis=1) \
        - eos_hit.astype(jnp.int32)
    committed = base & (ex_eos == 0)                         # [N, k+1]
    m_eff = jnp.sum(committed.astype(jnp.int32), axis=1)     # [N]
    last = jnp.take_along_axis(
        t, jnp.clip(m_eff - 1, 0, k)[:, None], axis=1)[:, 0]
    done_now = active & (jnp.any(eos_hit, axis=1)
                         | (remaining <= m_eff))
    new_state = {
        "token": jnp.where(active, last, eos),
        # live lanes stay in bounds by submit()'s spec window reserve;
        # the clamp keeps dead lanes' masked writes inside the buffer
        "pos": jnp.minimum(pos + m_eff, cache_len - 1),
        "active": active & jnp.logical_not(done_now),
        "remaining": jnp.maximum(remaining - m_eff, 0),
        "eos": eos,
    }
    toks = jnp.where(committed, t, eos[:, None]).T           # [k+1, N]
    return toks, m_eff, new_state


def make_draft_propose_fn(draft_module, param_transform, k, cache_len):
    """The draft-propose program:
    ``fn(draft_params, draft_cache, state) -> (draft [N, k], draft_cache)``
    with ONLY the draft KV workspace donated (argnum 1) — the slot state
    is read-only here (the verify program owns its donation).

    ``k+1`` greedy single-token draft steps in one in-program scan:
    write the pending token at ``pos``, argmax the draft logits, repeat.
    The extra (k+1)-th step is WRITE-ONLY bookkeeping (its sample is
    discarded): a fully-accepted window advances ``pos`` by ``k+1``, and
    without it the draft cache would hold a one-position hole at
    ``pos+k`` that the next window's queries would attend as garbage.
    The draft samples greedily regardless of the serving sampling config
    — draft quality only moves the ACCEPT RATE, never the committed
    tokens (those are always the target's)."""
    deq = param_transform if param_transform is not None else (lambda p: p)

    @hot_path("serving.spec_propose")
    def propose(draft_params, draft_cache, state):
        eos, active = state["eos"], state["active"]

        def step(carry, _):
            cache, tok, pos = carry
            logits, cache = draft_module.apply(
                deq(draft_params), tok[:, None], cache, pos,
                method=type(draft_module).decode)
            nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, eos)
            pos = jnp.minimum(pos + 1, cache_len - 1)
            return (cache, nxt, pos), nxt

        (draft_cache, _, _), drafts = jax.lax.scan(
            step, (draft_cache, state["token"], state["pos"]), None,
            length=k + 1)
        return drafts[:k].T, draft_cache            # [N, k]

    return jax.jit(propose, donate_argnums=(1,))


def make_spec_verify_fn(module, sample_fn, param_transform, k, cache_len):
    """The verify-and-commit program:
    ``fn(params, cache, state, draft, rng) -> (tokens [k+1, N],
    accepted [N], cache, state)`` with the TARGET cache and the slot
    state donated (argnums 1, 2).

    ONE batched target forward over ``[token, d_1..d_k]`` per slot
    (per-row start positions — the cache write is the per-row
    MULTI-token scatter, ``models/transformer.py``), then the shared
    :func:`_spec_commit` accept rule.  Every committed token is the
    target's ``sample_fn`` output over exactly the committed history
    (the accepted drafts match it position by position), which is the
    bitwise-greedy contract; K/V written for rejected window positions
    is overwritten position-by-position by later windows before any
    query can attend it — the same argument chunked prefill's padded
    tail already relies on."""
    deq = param_transform if param_transform is not None else (lambda p: p)

    @hot_path("serving.spec_verify")
    def verify(params, cache, state, draft, rng):
        ids = jnp.concatenate([state["token"][:, None], draft], axis=1)
        logits, cache = module.apply(deq(params), ids, cache,
                                     state["pos"],
                                     method=type(module).decode)
        rngs = jax.random.split(rng, k + 1)
        t = jnp.stack([sample_fn(logits[:, i], rngs[i]).astype(jnp.int32)
                       for i in range(k + 1)], axis=1)
        toks, accepted, new_state = _spec_commit(t, draft, state, k,
                                                 cache_len)
        return toks, accepted, cache, new_state

    return jax.jit(verify, donate_argnums=(1, 2))


def make_paged_spec_verify_fn(module, sample_fn, param_transform, k,
                              cache_len, paged_kernel=True):
    """The PAGED verify-and-commit program: pool + slot state donated
    (argnums 1, 2), the per-slot page tables a plain traced input.  Same
    accept math as :func:`make_spec_verify_fn`; like the paged decode
    step, inactive lanes' whole table row redirects to the trash page so
    their window writes can never land in pages the host already handed
    to a newer occupant."""
    deq = param_transform if param_transform is not None else (lambda p: p)

    @hot_path("serving.spec_verify_paged")
    def verify(params, cache, state, pages, draft, rng):
        safe_pages = jnp.where(state["active"][:, None], pages, 0)
        ids = jnp.concatenate([state["token"][:, None], draft], axis=1)
        logits, cache = module.apply(deq(params), ids,
                                     {**cache, "pages": safe_pages,
                                      **_paged_kernel_marker(paged_kernel)},
                                     state["pos"],
                                     method=type(module).decode)
        rngs = jax.random.split(rng, k + 1)
        t = jnp.stack([sample_fn(logits[:, i], rngs[i]).astype(jnp.int32)
                       for i in range(k + 1)], axis=1)
        toks, accepted, new_state = _spec_commit(t, draft, state, k,
                                                 cache_len)
        return toks, accepted, cache, new_state

    return jax.jit(verify, donate_argnums=(1, 2))


def make_draft_chunk_fn(draft_module, param_transform):
    """The draft-side admission-prefill chunk program — same body as the
    engine's per-chunk program, bound to the DRAFT module: speculation
    needs the prompt's K/V in the draft cache too, so admission streams
    every chunk through both models (the draft lane is donated, argnum
    1).  The selected logits are computed for body parity but discarded
    — the first token is sampled by the TARGET admit program."""
    deq = param_transform if param_transform is not None else (lambda p: p)

    @hot_path("serving.spec_draft_prefill")
    def chunk_step(draft_params, lane, chunk_ids, start, logits_at):
        return draft_module.apply(deq(draft_params), chunk_ids, lane,
                                  start, method=type(draft_module).decode,
                                  logits_at=logits_at)

    return jax.jit(chunk_step, donate_argnums=(1,))


def make_draft_admit_fn():
    """The draft-side admission program: insert the prefilled draft lane
    into slot ``slot`` of the draft cache (``dynamic_update_slice`` over
    the traced slot index; draft cache donated, argnum 0).  No sampling,
    no state write — the target admit program owns both."""

    @hot_path("serving.spec_draft_admit")
    def admit(draft_cache, lane, slot):
        def ins(buf, lbuf):
            return jax.lax.dynamic_update_slice(
                buf, lbuf.astype(buf.dtype), (0, slot, 0, 0))

        return {kk: ins(draft_cache[kk], lane[kk]) for kk in draft_cache}

    return jax.jit(admit, donate_argnums=(0,))


def make_paged_admit_fn(sample_fn):
    """The paged admission program:
    ``fn(state, logits, rng, slot, pos0, max_new, eos) -> (state,
    first_token)`` with the slot state donated (argnum 0).  The prefill
    already wrote the prompt's K/V into the slot's pages, so admission
    is just the first-token sample (same ``build_sample_fn`` rule — the
    bitwise contract) plus the in-program slot-state write."""

    @hot_path("serving.admit_paged")
    def admit(state, logits, rng, slot, pos0, max_new, eos):
        first = sample_fn(logits[:, 0], rng).astype(jnp.int32)[0]
        active0 = (max_new > 1) & jnp.logical_not(first == eos)
        upd = lambda arr, val: arr.at[slot].set(val)
        state = {"token": upd(state["token"], first),
                 "pos": upd(state["pos"], pos0),
                 "active": upd(state["active"], active0),
                 "remaining": upd(state["remaining"],
                                  jnp.maximum(max_new - 1, 0)),
                 "eos": upd(state["eos"], eos)}
        return state, first

    return jax.jit(admit, donate_argnums=(0,))
