from deepspeed_tpu.ops.quantizer.kernels import (
    quantize, dequantize, fake_quantize, pack_int4, unpack_int4,
    quantize_ternary, quantize_binary)
