"""Communication verbs over mesh axes.

TPU-native re-design of the reference dispatch module
(``deepspeed/comm/comm.py:214-562``).  The verb set is preserved —
``all_reduce``, ``all_gather_into_tensor``, ``reduce_scatter_tensor``,
``all_to_all_single``, ``ppermute``/``send_recv_next`` (the p2p analog), ``broadcast``,
``barrier`` — but groups are mesh axis names, not NCCL communicators, and the
hot path runs *inside* jitted/shard_mapped programs where XLA schedules the
collectives onto ICI.

Two execution regimes:

* **traced** (inside ``shard_map``): verbs lower directly to ``jax.lax``
  collectives.  This is the hot path; XLA overlaps these with compute.
* **eager** (plain Python, multi-host): verbs operate across JAX *processes*
  via multihost utilities — used for bootstrap, barriers, and scalar control
  decisions, mirroring how the reference uses eager torch.distributed calls
  outside the step function.

Every eager verb is wrapped with ``timed_op`` feeding the ``CommsLogger``
(parity with reference ``comm/comm.py:104`` + ``utils/comms_logging.py:61``).
"""

import functools
import os
import time
from enum import Enum

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.backend import XlaBackend
from deepspeed_tpu.utils.jax_compat import axis_size as _axis_size
from deepspeed_tpu.utils.comms_logging import CommsLogger, get_msg_size_from_args
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.parallel import topology as topo


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4


cdb = None  # "communication data backend" — name kept for parity
comms_logger = CommsLogger()
_timers_enabled = False


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _axes(group):
    """Normalize a group argument to a tuple of mesh axis names.

    ``group=None`` means the data-parallel group (the common case for grad
    reductions).  Expert-parameter gradients must pass
    ``topology.EXPERT_GRAD_AXES`` explicitly — they reduce over expert-data
    parallel only, never over ``ep`` (reference ``stage_1_and_2.py:1781``).
    """
    if group is None:
        return topo.DP_AXES
    if isinstance(group, str):
        return (group,)
    return tuple(group)


# --------------------------------------------------------------------- #
# Init / identity
# --------------------------------------------------------------------- #
def init_distributed(dist_backend="xla", auto_mpi_discovery=True, verbose=True,
                     timeout=None, init_method=None, dist_init_required=None,
                     config=None, rank=-1, world_size=-1):
    """Bootstrap multi-process JAX (analog of reference ``comm.py:562``)."""
    global cdb
    if cdb is not None and cdb.is_initialized():
        return cdb
    if auto_mpi_discovery and "OMPI_COMM_WORLD_SIZE" in os.environ \
            and "DSTPU_COORDINATOR_ADDRESS" not in os.environ:
        mpi_discovery(verbose=verbose)
    cdb = XlaBackend(timeout=timeout, init_method=init_method)
    cdb.init_process_group()
    return cdb


def mpi_discovery(distributed_port=29500, verbose=True):
    """Map OpenMPI env vars to the JAX coordinator env (analog of reference
    ``comm.py:627`` which maps MPI ranks to MASTER_ADDR/RANK/WORLD_SIZE)."""
    rank = int(os.environ["OMPI_COMM_WORLD_RANK"])
    world = int(os.environ["OMPI_COMM_WORLD_SIZE"])
    master = os.environ.get("MASTER_ADDR", "127.0.0.1")
    os.environ.setdefault("DSTPU_COORDINATOR_ADDRESS", f"{master}:{distributed_port}")
    os.environ.setdefault("DSTPU_NUM_PROCESSES", str(world))
    os.environ.setdefault("DSTPU_PROCESS_ID", str(rank))
    if verbose:
        logger.info(f"MPI discovery: rank {rank}/{world} coordinator "
                    f"{os.environ['DSTPU_COORDINATOR_ADDRESS']}")


def is_initialized():
    return cdb is not None and cdb.is_initialized()


def get_rank(group=None):
    """Process rank (eager) — for the in-trace device rank use ``axis_index``."""
    return jax.process_index()


def get_world_size(group=None):
    if group is None:
        return jax.device_count()
    t = topo.get_topology()
    size = 1
    for ax in _axes(group):
        size *= t.axis_size(ax)
    return size


def get_local_rank():
    return int(os.environ.get("LOCAL_RANK", 0))


def axis_index(group):
    """Device coordinate along a group's axes — in-trace rank
    (replaces reference per-communicator ``get_rank``)."""
    axes = _axes(group)
    idx = lax.axis_index(axes[0])
    for ax in axes[1:]:
        idx = idx * _axis_size(ax) + lax.axis_index(ax)
    return idx


def new_group(ranks=None, axes=None):
    """Groups are mesh axes; ``new_group`` just validates and returns the axis
    tuple (reference ``comm.py:380`` creates NCCL communicators here)."""
    if axes is None:
        raise ValueError("TPU groups are mesh axes: pass axes=('dp',...) — "
                         "rank-list groups are not meaningful under GSPMD")
    return tuple(axes)


# --------------------------------------------------------------------- #
# timed_op — eager-path profiling decorator (reference comm.py:104)
# --------------------------------------------------------------------- #
def timed_op(fn):

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        arg0 = args[0] if args else None
        if not comms_logger.enabled or _is_traced(arg0):
            return fn(*args, **kwargs)
        # prof_all=False restricts logging to the prof_ops allowlist
        # (reference comms_logger semantics)
        if not getattr(comms_logger, "prof_all", True):
            name = kwargs.get("log_name", fn.__name__)
            allowed = getattr(comms_logger, "prof_ops", None) or []
            if fn.__name__ not in allowed and name not in allowed:
                return fn(*args, **kwargs)
        t0 = time.perf_counter()
        result = fn(*args, **kwargs)
        try:
            jax.block_until_ready(result)
        except Exception:
            pass
        latency = time.perf_counter() - t0
        comms_logger.append(fn.__name__, kwargs.get("log_name", fn.__name__),
                            latency, get_msg_size_from_args(arg0))
        return result

    return wrapper


def configure(deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None,
              verbose=None, debug=None):
    if deepspeed_config is not None and getattr(deepspeed_config, "comms_config", None):
        comms_logger.configure(deepspeed_config.comms_config)
    if enabled is not None:
        comms_logger.enabled = enabled
    if verbose is not None:
        comms_logger.verbose = verbose
    if debug is not None:
        comms_logger.debug = debug
    if prof_all is not None:
        comms_logger.prof_all = prof_all
    if prof_ops is not None:
        comms_logger.prof_ops = prof_ops


def log_summary():
    return comms_logger.log_all()


# --------------------------------------------------------------------- #
# Collectives
# --------------------------------------------------------------------- #
@timed_op
def all_reduce(tensor, op=ReduceOp.SUM, group=None, async_op=False, log_name=None):
    """SUM/AVG/MAX/MIN/PROD reduction over a mesh-axis group.

    Traced: lowers to ``lax.psum``/``pmax``/``pmin`` (reference
    ``comm.py:454`` → NCCL allreduce).  Eager: reduces across processes via
    allgather + local reduce (control-plane use only).
    """
    axes = _axes(group)
    if _is_traced(tensor):
        if op == ReduceOp.SUM:
            return lax.psum(tensor, axes)
        if op == ReduceOp.AVG:
            return lax.pmean(tensor, axes)
        if op == ReduceOp.MAX:
            return lax.pmax(tensor, axes)
        if op == ReduceOp.MIN:
            return lax.pmin(tensor, axes)
        if op == ReduceOp.PRODUCT:
            return jnp.exp(lax.psum(jnp.log(tensor), axes))
        raise ValueError(f"unsupported op {op}")
    from deepspeed_tpu.utils.jax_compat import process_allgather_stacked
    gathered = process_allgather_stacked(jnp.asarray(tensor))
    reducers = {ReduceOp.SUM: jnp.sum, ReduceOp.AVG: jnp.mean,
                ReduceOp.MAX: jnp.max, ReduceOp.MIN: jnp.min,
                ReduceOp.PRODUCT: jnp.prod}
    return reducers[op](gathered, axis=0)


@timed_op
def all_gather_into_tensor(tensor, group=None, axis=0, tiled=True, log_name=None):
    """Concatenated all-gather (reference ``comm.py:310``
    all_gather_into_tensor)."""
    axes = _axes(group)
    if _is_traced(tensor):
        return lax.all_gather(tensor, axes, axis=axis, tiled=tiled)
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(jnp.asarray(tensor))


# reference comm.py:308 allgather_fn capability fallback — one impl on TPU
allgather_fn = all_gather_into_tensor


@timed_op
def reduce_scatter_tensor(tensor, op=ReduceOp.SUM, group=None, scatter_dimension=0,
                          tiled=True, log_name=None):
    """Reduce+scatter (reference ``comm.py:257`` reduce_scatter_tensor →
    ``lax.psum_scatter``)."""
    axes = _axes(group)
    if not _is_traced(tensor):
        raise RuntimeError("reduce_scatter is a device collective: call inside "
                           "shard_map/jit (eager grads never materialize on host on TPU)")
    out = lax.psum_scatter(tensor, axes, scatter_dimension=scatter_dimension, tiled=tiled)
    if op == ReduceOp.AVG:
        out = out / get_world_size(axes)
    return out


reduce_scatter_fn = reduce_scatter_tensor


@timed_op
def all_to_all_single(tensor, group=None, split_axis=0, concat_axis=0, tiled=True,
                      log_name=None):
    """All-to-all (reference ``comm.py:337``) — the MoE dispatch collective."""
    axes = _axes(group)
    if not _is_traced(tensor):
        raise RuntimeError("all_to_all is a device collective: call inside shard_map")
    return lax.all_to_all(tensor, axes, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def ppermute(tensor, group, perm):
    """Collective permute — the TPU replacement for pipeline ``send``/``recv``
    pairs (reference ``runtime/pipe/p2p.py:50,71``): both halves of the
    exchange are one ``lax.ppermute`` riding ICI neighbors."""
    axes = _axes(group)
    assert len(axes) == 1, "ppermute takes a single axis"
    return lax.ppermute(tensor, axes[0], perm)


def send_recv_next(tensor, group):
    """Shift +1 along the group axis (stage i → stage i+1)."""
    axes = _axes(group)
    n = get_world_size(axes)
    return ppermute(tensor, group, [(i, (i + 1) % n) for i in range(n)])


def send_recv_prev(tensor, group):
    axes = _axes(group)
    n = get_world_size(axes)
    return ppermute(tensor, group, [(i, (i - 1) % n) for i in range(n)])


def p2p(tensor, src, dst, group):
    """Rank-addressed point-to-point as ONE collective: the SPMD rendering
    of a reference ``send(dst)`` / ``recv(src)`` pair (``comm.py:428``).
    Every device calls it; device ``dst`` returns ``src``'s value, all
    others return their own tensor unchanged.  Runs inside
    ``shard_map``/``jit`` like every device collective here."""
    if not any(_is_traced(l) for l in jax.tree.leaves(tensor)):
        raise RuntimeError("p2p is a device collective: call inside "
                           "shard_map/jit")
    axes = _axes(group)
    if len(axes) != 1:
        raise ValueError(f"p2p takes a single mesh axis, got {axes}")
    n = get_world_size(axes)
    if not (0 <= src < n and 0 <= dst < n):
        # an out-of-range endpoint would make the ppermute deliver nothing
        # and the masked merge silently keep every device's own tensor
        raise ValueError(f"p2p src={src}/dst={dst} out of range for axis "
                         f"{axes[0]!r} of size {n}")
    moved = ppermute(tensor, group, [(src, dst)])
    idx = lax.axis_index(axes[0])
    return jax.tree.map(
        lambda m, t: jnp.where(idx == dst, m, t), moved, tensor)


@timed_op
def broadcast(tensor, src=0, group=None, log_name=None):
    """Traced: everyone takes src's value via a masked psum.  Eager on global
    arrays: replicate via device_put (reference ``comm.py:224``)."""
    axes = _axes(group)
    if _is_traced(tensor):
        idx = axis_index(axes)
        masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
        return lax.psum(masked, axes)
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(tensor, is_source=jax.process_index() == src)


def barrier(group=None):
    """Cross-process sync (reference ``comm.py:398``)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("dstpu_barrier")
    else:
        jnp.zeros(()).block_until_ready()


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None):
    # On a mesh every participant holds the reduction; dst is vestigial.
    return all_reduce(tensor, op=op, group=group)


def all_gather(tensor_list_or_tensor, tensor=None, group=None, log_name=None):
    """List-style all_gather (reference ``comm.py:284``): returns the gathered
    shards stacked on a leading axis.  ``tensor_list_or_tensor`` may be the
    torch-style output list (ignored — jax is functional) or the input.
    Timing is owned by the inner ``all_gather_into_tensor`` (one log record
    per call, not two)."""
    x = tensor if tensor is not None else tensor_list_or_tensor
    return all_gather_into_tensor(x, group=group, axis=0, tiled=False,
                                  log_name=log_name or "all_gather")


def gather(tensor, gather_list=None, dst=0, group=None, log_name=None):
    """Gather-to-dst (reference ``comm.py:362``).  On a mesh the all-gather
    result is available everywhere; ``dst`` is vestigial.  Timing owned by
    the inner collective."""
    return all_gather_into_tensor(tensor, group=group, axis=0, tiled=False,
                                  log_name=log_name or "gather")


@timed_op
def scatter(tensor, scatter_list=None, src=0, group=None, log_name=None):
    """Scatter-from-src (reference ``comm.py:375``): each participant takes
    its own slice of src's leading axis (src's value is authoritative via
    broadcast; on a mesh all copies already agree)."""
    axes = _axes(group)
    if not _is_traced(tensor):
        raise RuntimeError("scatter is a device collective: call inside "
                           "shard_map/jit")
    idx = axis_index(axes)
    return jax.lax.dynamic_index_in_dim(tensor, idx, axis=0, keepdims=False)


def isend(tensor, dst, group=None, tag=0):
    """Async point-to-point verbs (reference ``comm.py:420`` isend/irecv)
    are NOT supported as standalone eager ops on TPU — this always raises
    with guidance.  Rank-addressed p2p has no XLA analog outside a compiled
    collective: the one-call SPMD equivalent of a send/recv PAIR is
    :func:`p2p` (or :func:`ppermute` / :func:`send_recv_next` /
    :func:`send_recv_prev`) inside ``shard_map`` — both halves of each
    exchange are one collective-permute riding ICI, which is how the
    pipeline engine moves activations.  Synchronous reference-shaped
    ``send``+``recv`` pairs with static endpoints ARE supported — see
    :func:`send`."""
    raise NotImplementedError(
        "isend/irecv have no eager analog on TPU: call "
        "dist.p2p(tensor, src, dst, group) — the send/recv pair as ONE "
        "collective — or ppermute/send_recv_next inside shard_map "
        "(pipeline p2p rides ICI); statically-paired send()+recv() also "
        "works inside shard_map")


irecv = isend

# outstanding sends awaiting their recv, each keyed by the trace that made
# it (see send/recv below): pairing is only legal WITHIN one traced
# program, and scoping the queue by trace identity means a send whose
# trace aborted can never poison a later, innocent trace with a
# leaked-tracer error.  Foreign entries are NEVER dropped eagerly — a
# nested jit's send must not discard a still-live enclosing trace's
# pending entry — only at a failing recv, where pairing is impossible
# anyway and the stale entries get called out.
_pending_send = []      # [(opaque_trace_state, tensor, dst, axes, tag)]


def _current_trace_state():
    from deepspeed_tpu.utils.jax_compat import get_opaque_trace_state
    return get_opaque_trace_state()


_warned_missing_trace_ref = False


def _check_trace_ref(state):
    """One-time canary: dead-trace pruning leans on the PRIVATE
    ``OpaqueTraceState._trace_ref`` weakref.  If a JAX upgrade renames it,
    the ``getattr`` fallback below degrades to "always live" — correct but
    leak-prone (aborted traces' sends pin their tensors until a failing
    recv) — and that regression must be VISIBLE, not silent.  Guarded by a
    unit test too (tests/unit/test_comm.py)."""
    global _warned_missing_trace_ref
    if _warned_missing_trace_ref or hasattr(state, "_trace_ref"):
        return
    _warned_missing_trace_ref = True
    logger.warning(
        "OpaqueTraceState._trace_ref is missing on this JAX version — "
        "dead-trace pruning of queued send()s is disabled (every queued "
        "send reads as live).  Aborted traces' sends now persist until a "
        "failing recv; update _prune_dead_sends for the new "
        "OpaqueTraceState internals.")


def _prune_dead_sends():
    """Drop queued sends whose trace has been garbage-collected (an aborted
    or completed-without-recv trace).  ``OpaqueTraceState`` holds a WEAKREF
    to its trace, so deadness is precise: a live enclosing trace (nested
    jit) is never touched, but repeated aborted traces cannot accumulate
    entries (each pinning its traced tensor) for the life of the process.
    Called opportunistically from the happy path of send()/recv()."""
    if _pending_send:
        _check_trace_ref(_pending_send[0][0])
    # identity-based filtering: tuple equality would compare the queued
    # TRACED tensors (ambiguous truth value / leaked-tracer errors)
    dead_ids = {id(e) for e in _pending_send
                if getattr(e[0], "_trace_ref", lambda: True)() is None}
    if dead_ids:
        _pending_send[:] = [e for e in _pending_send
                            if id(e) not in dead_ids]
        logger.warning(
            f"send/recv shim: pruned {len(dead_ids)} queued send(s) from "
            f"dead trace(s) (their recv never executed — likely aborted "
            f"traces; send/recv pairs must complete in ONE traced function)")


def _drop_foreign_sends(state):
    """Discard queued sends from other traces.  Called only from a recv
    that found nothing to pair with in ITS trace: at that point the
    foreign entries are either from aborted traces (dead) or evidence of
    a pair split across jit boundaries (a bug being reported right now) —
    either way they must not linger to confuse the next diagnosis."""
    stale = [e for e in _pending_send if e[0] != state]
    if stale:
        _pending_send[:] = [e for e in _pending_send if e[0] == state]
        logger.warning(
            f"send/recv shim: dropping {len(stale)} unmatched send(s) "
            f"queued by an earlier trace (their recv never executed — "
            f"likely an aborted trace or a send/recv pair split across "
            f"jit boundaries; pairs must live in ONE traced function)")


def send(tensor, dst, group=None, tag=0):
    """Compatibility shim for reference-shaped ``send``/``recv`` pairs
    (reference ``comm.py:428``).  Under SPMD every rank executes BOTH
    calls, so a pair with STATIC endpoints

    .. code-block:: python

        dist.send(x, dst=5, group=("edp",))
        out = dist.recv(buf, src=2, group=("edp",))

    is statically resolvable to one mesh-axis permute: the matched pair
    lowers to ONE :func:`p2p` collective (rank ``dst``'s ``recv`` returns
    rank ``src``'s ``x``; every other rank keeps its ``buf``).  Endpoints
    must be Python ints and each ``recv`` pairs with the OLDEST pending
    ``send`` *of the same trace* (FIFO, like tag-free torch p2p
    ordering), matching on group and tag.  Genuinely dynamic patterns
    (traced endpoints, a ``recv`` with no pending ``send``, group/tag
    mismatches) raise with guidance, because no single SPMD program can
    express them.  The pending queue is scoped to the live trace: a
    ``send`` can never pair across traces, so an aborted step cannot
    poison the one after it (stale entries sit inert until a failing
    ``recv`` reports and drops them); a nested jit's own send/recv pair
    coexists with an enclosing trace's pending send."""
    if not any(_is_traced(l) for l in jax.tree.leaves(tensor)):
        raise NotImplementedError(
            "send/recv are compiled collectives here: call the pair inside "
            "shard_map/jit (or use dist.p2p directly)")
    if not isinstance(dst, int):
        raise NotImplementedError(
            "send(dst=...) must be a static Python int: a traced endpoint "
            "is rank-dynamic and has no single-program SPMD lowering — "
            "use dist.p2p/ppermute to express the whole exchange")
    _prune_dead_sends()
    _pending_send.append((_current_trace_state(), tensor, int(dst),
                          _axes(group), tag))
    return tensor


def recv(tensor, src, group=None, tag=0):
    """The receive half of a statically-paired send/recv — see
    :func:`send`.  ``tensor`` is the receive buffer: returned unchanged on
    every rank except the send's ``dst``, which gets rank ``src``'s sent
    value."""
    state = _current_trace_state()
    _prune_dead_sends()
    mine = [e for e in _pending_send if e[0] == state]
    if not mine:
        n_foreign = len(_pending_send)
        _drop_foreign_sends(state)
        raise NotImplementedError(
            "recv() without a preceding send() in this trace: under SPMD "
            "both halves of the exchange execute on every rank — call "
            "send(x, dst) then recv(buf, src) in the SAME traced function, "
            "or use dist.p2p(tensor, src, dst, group) directly"
            + (f" ({n_foreign} stale send(s) from an earlier trace were "
               f"queued and have been dropped)" if n_foreign else ""))
    entry = mine[0]                                   # FIFO pairing
    _pending_send.remove(entry)
    _, sent, dst, saxes, stag = entry
    if not isinstance(src, int):
        raise NotImplementedError(
            "recv(src=...) must be a static Python int (see send())")
    if _axes(group) != saxes or tag != stag:
        raise ValueError(
            f"recv(group={_axes(group)}, tag={tag}) does not match the "
            f"pending send(group={saxes}, tag={stag})")
    moved = p2p(sent, src, dst, group)
    idx = lax.axis_index(saxes[0])
    return jax.tree.map(
        lambda m, buf: jnp.where(idx == dst, m, buf), moved, tensor)


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    """Barrier with failure attribution (reference ``comm.py:405``).  XLA
    collectives already fail loudly on rank drop-out; delegate to barrier."""
    return barrier(group)


def inference_all_reduce(tensor, op=ReduceOp.SUM, group=None):
    """TP allreduce inside injected inference layers (reference
    ``pt_binding.cpp`` inference_all_reduce) — same psum on TPU."""
    return all_reduce(tensor, op=op, group=group)


def destroy_process_group():
    global cdb
    if cdb is not None:
        cdb.destroy_process_group()
        cdb = None
