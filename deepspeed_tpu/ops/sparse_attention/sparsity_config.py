"""Block-sparse attention sparsity layouts.

Capability parity with reference
``deepspeed/ops/sparse_attention/sparsity_config.py`` (``SparsityConfig :10``,
``DenseSparsityConfig :63``, ``FixedSparsityConfig :95``,
``VariableSparsityConfig :239``, ``BigBirdSparsityConfig :411``,
``BSLongformerSparsityConfig :546``, sliding-window ``:674``): each config
produces a layout of shape ``[num_heads, num_blocks, num_blocks]`` with 1 for
kept (block-row attends block-col) and 0 for skipped blocks.

Layouts are host-side numpy and *static* — they parameterise the kernel's
grid/prefetch tables at trace time, which is exactly what the TPU wants
(no dynamic shapes inside jit).  Block default is 64 here (MXU-friendly)
vs the reference's 16 (Triton-friendly).
"""

import numpy as np


class SparsityConfig:
    """Base: block size, per-head layouts (reference ``:10``)."""

    def __init__(self, num_heads, block=64, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(
                f"seq_len {seq_len} must be divisible by block {self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """Everything attends everything (reference ``:63``) — for testing."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Local chunks + periodic global blocks (reference ``:95``; the
    GPT-3-style 'fixed' pattern)."""

    def __init__(self, num_heads, block=64, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention {attention}")
        self.attention = attention
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires "
                             "bidirectional attention")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("different global patterns require "
                             "different_layout_per_head")
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        nb = layout.shape[1]
        for start in range(0, nb, self.num_local_blocks):
            end = min(start + self.num_local_blocks, nb)
            for r in range(start, end):
                cols = range(start, r + 1 if self.attention == "unidirectional"
                             else end)
                layout[h, r, list(cols)] = 1
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        # the last num_global_blocks of each local window act as global
        # representatives; pattern index rotates per head group
        pattern = (h % self.num_different_global_patterns
                   if self.num_different_global_patterns > 1 else 0)
        first = max(0, self.num_local_blocks - (1 + pattern)
                    * self.num_global_blocks)
        for start in range(first, nb, self.num_local_blocks):
            gcols = [c for c in range(start, min(start + self.num_global_blocks, nb))]
            for r in range(nb):
                for c in gcols:
                    if self.attention == "bidirectional" or c <= r:
                        layout[h, r, c] = 1
            if self.horizontal_global_attention:
                for g in gcols:
                    layout[h, g, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Random + variable-width local windows + global (reference ``:239``)."""

    def __init__(self, num_heads, block=64, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False,
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention {attention}")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.rng = np.random.default_rng(seed)

    def set_random_layout(self, h, layout):
        nb = layout.shape[1]
        for r in range(nb):
            if self.num_random_blocks > 0:
                hi = r + 1 if self.attention == "unidirectional" else nb
                k = min(self.num_random_blocks, hi)
                cols = self.rng.choice(hi, size=k, replace=False)
                layout[h, r, cols] = 1
        return layout

    def set_local_layout(self, h, layout):
        nb = layout.shape[1]
        start = 0
        win_i = 0
        while start < nb:
            w = self.local_window_blocks[min(win_i,
                                             len(self.local_window_blocks) - 1)]
            end = min(start + w, nb)
            for r in range(start, end):
                cols = range(start, r + 1 if self.attention == "unidirectional"
                             else end)
                layout[h, r, list(cols)] = 1
            start = end
            win_i += 1
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for lo, hi in spans:
            for c in range(lo, min(hi, nb)):
                for r in range(nb):
                    if self.attention == "bidirectional" or c <= r:
                        layout[h, r, c] = 1
                if self.horizontal_global_attention:
                    layout[h, c, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """random + sliding window + global (reference ``:411``, BigBird paper)."""

    def __init__(self, num_heads, block=64, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional", seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention {attention}")
        self.attention = attention
        self.rng = np.random.default_rng(seed)

    def set_random_layout(self, h, layout):
        nb = layout.shape[1]
        if nb < self.num_random_blocks:
            raise ValueError(f"num_random_blocks {self.num_random_blocks} "
                             f"exceeds {nb} blocks")
        for r in range(nb):
            hi = r + 1 if self.attention == "unidirectional" else nb
            k = min(self.num_random_blocks, hi)
            cols = self.rng.choice(hi, size=k, replace=False)
            layout[h, r, cols] = 1
        return layout

    def set_sliding_window_layout(self, h, layout):
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError("sliding window wider than sequence")
        w = self.num_sliding_window_blocks // 2
        for r in range(nb):
            lo = max(0, r - w)
            hi = min(nb, r + w + 1)
            layout[h, r, lo:hi] = 1
        return layout

    def set_global_layout_itc(self, h, layout):
        nb = layout.shape[1]
        if nb < self.num_global_blocks:
            raise ValueError("more global blocks than blocks")
        g = self.num_global_blocks
        layout[h, 0:g, :] = 1
        layout[h, :, 0:g] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout_itc(h, layout)
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """sliding window + selected global rows/cols (reference ``:546``,
    block-sparse Longformer)."""

    def __init__(self, num_heads, block=64, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def set_sliding_window_layout(self, h, layout):
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for r in range(nb):
            layout[h, r, max(0, r - w):min(nb, r + w + 1)] = 1
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for lo, hi in spans:
            layout[h, :, lo:min(hi, nb)] = 1
            layout[h, lo:min(hi, nb), :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout(h, layout)
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (reference ``:674``)."""

    def __init__(self, num_heads, block=64, num_sliding_window_blocks=3,
                 attention="unidirectional"):
        super().__init__(num_heads, block, different_layout_per_head=False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for r in range(nb):
                lo = max(0, r - (self.num_sliding_window_blocks - 1
                                 if self.attention == "unidirectional" else w))
                hi = r + 1 if self.attention == "unidirectional" \
                    else min(nb, r + w + 1)
                layout[h, r, lo:hi] = 1
        return self.check_and_propagate_first_head_layout(layout)
