"""Tuner strategy base (reference ``deepspeed/autotuning/tuner/base_tuner.py``)."""

from deepspeed_tpu.utils.logging import logger


class BaseTuner:
    """Iterates a list of experiments, tracking the best metric seen and
    stopping early after ``early_stopping`` non-improving trials (reference
    ``BaseTuner.tune``)."""

    def __init__(self, exps, resource_manager, metric="throughput"):
        self.all_exps = list(exps)
        self.rm = resource_manager
        self.metric = metric
        # latency is minimized; throughput/flops maximized
        self.maximize = metric != "latency"
        self.best_iter = 0
        self.best_exp = None
        self.best_metric_val = None

    def _better(self, val):
        if self.best_metric_val is None:
            return True
        return val > self.best_metric_val if self.maximize \
            else val < self.best_metric_val

    def has_next(self):
        return len(self.all_exps) > 0

    def next_batch(self, sample_size=1):
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch

    def update(self):
        """Consume results of the batch just run; subclasses that model the
        space (ModelBasedTuner) refit here."""

    def tune(self, sample_size=1, n_trials=50, early_stopping=None):
        i = 0
        while i < n_trials and self.has_next():
            sampled = self.next_batch(sample_size)
            exps = self.rm.schedule_experiments(sampled)
            for exp in exps:
                metric_val = exp.results.get(self.metric)
                if metric_val is not None and self._better(metric_val):
                    self.best_exp = exp
                    self.best_metric_val = metric_val
                    self.best_iter = i
                i += 1
            self.update()
            if early_stopping and i >= self.best_iter + early_stopping:
                logger.info(
                    f"Tuner early-stopped at trial {i} "
                    f"(no improvement in {early_stopping} trials)")
                break
        return self.best_exp, self.best_metric_val
