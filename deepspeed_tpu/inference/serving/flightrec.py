"""Serving flight recorder — a bounded ring buffer of recent structured
scheduler events that auto-dumps to a JSON file at the moments a serving
process is least able to explain itself (``docs/observability.md``,
"Flight recorder").

The recorder answers the question post-mortems keep asking the serving
stack (the ROADMAP's un-explained bench-r05 blackout, breaker trips with
no context, drain timeouts whose diagnostics start AFTER the wedge):
*what were the last N things the scheduler did?*  Every dispatch
begin/end, scheduler decision (admit/shed/cancel/abort/stall), breaker
transition, lock-wait sample and fault-injection hit is appended as a
plain dict with a sequence number, monotonic and wall timestamps, and
the recording thread's name.

Contracts:

* **Own lock.**  The ring is guarded by its own ``threading.Lock`` —
  never the engine lock — so a reader (``GET /debug/flightrec``,
  SIGUSR2, a crash-path dump) never contends the scheduler hot path,
  and the hot path's ``record()`` is a constant-time append.
* **Bounded.**  ``deque(maxlen=...)``: old events fall off, memory is
  fixed; ``dropped`` counts what the ring forgot.
* **Dump-on-distress.**  The serving engine wires auto-dumps at
  breaker-open, ``DrainTimeout``, ``ConcurrencyViolation`` and
  scheduler-thread death; the HTTP front end adds ``GET
  /debug/flightrec`` and a SIGUSR2 handler.  Dumps are best-effort by
  construction (``dump`` swallows nothing, callers wrap it): a failing
  dump must never mask the original fault.
"""

import json
import os
import tempfile
import threading
import time
from collections import deque

DEFAULT_MAX_EVENTS = 2048


def default_dump_dir():
    """Where auto-dumps land when ``serving.flight_recorder_dir`` is
    unset: a per-user directory under the system temp root."""
    return os.path.join(tempfile.gettempdir(), "dstpu_flightrec")


class FlightRecorder:
    """Bounded, self-locked ring of structured serving events."""

    def __init__(self, max_events=DEFAULT_MAX_EVENTS, dump_dir=None,
                 clock=time.monotonic, wallclock=time.time):
        self._events = deque(maxlen=int(max_events))
        # RLock, deliberately: the SIGUSR2 dump handler runs on the
        # main thread and may interrupt that SAME thread inside
        # record()'s critical section — a plain Lock would self-
        # deadlock the handler (and wedge every other recorder).  The
        # re-entrant snapshot can at worst observe the interrupted
        # append as one transiently-dropped event, which a debug dump
        # tolerates.
        self._lock = threading.RLock()
        self._clock = clock
        self._wallclock = wallclock
        self._seq = 0
        self._dump_seq = 0
        self.dump_dir = dump_dir or default_dump_dir()
        self.last_dump_path = None       # newest auto/manual dump

    def record(self, ev, **fields):
        """Append one event (``ev`` = kind tag, ``fields`` = structured
        payload; ``None`` values dropped).  Constant time, own lock."""
        entry = {"ev": ev, "t_mono": round(self._clock(), 6),
                 "t_wall": round(self._wallclock(), 6),
                 "thread": threading.current_thread().name}
        entry.update((k, v) for k, v in fields.items() if v is not None)
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._events.append(entry)

    def snapshot(self):
        """Point-in-time copy: ``{"events": [...], "recorded": total,
        "dropped": fell-off-the-ring}`` — oldest first."""
        with self._lock:
            events = list(self._events)
            seq = self._seq
        return {"events": events, "recorded": seq,
                "dropped": seq - len(events)}

    def dump(self, reason, path=None):
        """Write the snapshot (plus the dump reason and pid) as JSON to
        ``path`` — default: ``<dump_dir>/flightrec_<reason>_<pid>_<n>
        .json`` — and return the path.  Callers on crash paths wrap
        this in try/except: a failing dump must never mask the fault
        being recorded."""
        snap = self.snapshot()
        snap["reason"] = reason
        snap["pid"] = os.getpid()
        snap["dumped_at_wall"] = self._wallclock()
        if path is None:
            os.makedirs(self.dump_dir, exist_ok=True)
            with self._lock:
                self._dump_seq += 1
                n = self._dump_seq
            safe = "".join(c if c.isalnum() or c in "-_" else "_"
                           for c in str(reason))[:48]
            path = os.path.join(
                self.dump_dir,
                f"flightrec_{safe}_{os.getpid()}_{n}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, path)            # a reader never sees a torn dump
        self.last_dump_path = path
        return path


__all__ = ["FlightRecorder", "DEFAULT_MAX_EVENTS", "default_dump_dir"]
