"""Memory / performance cost models for the autotuner.

The reference prunes its tuning space with a profile run plus an xgboost cost
model (``deepspeed/autotuning/autotuner.py:664``, ``tuner/cost_model.py``).
On TPU we can do strictly better: XLA tells us the exact per-program memory
footprint at *compile time* (``compiled.memory_analysis()``), so OOM configs
are rejected without ever executing — and an analytic ZeRO memory model
(params/grads/optimizer-states divided across the dp axis by stage) prunes
before even compiling.
"""

import os

from deepspeed_tpu.autotuning.constants import DEFAULT_HBM_BYTES


def device_memory_limit():
    """Per-chip memory budget in bytes.

    Order: ``DSTPU_HBM_BYTES`` env override → the accelerator's
    canonical ``memory_snapshot()['bytes_limit']`` (backend-reported on
    real TPU, datasheet fallback on tunneled platforms — the SAME
    number the flops profiler and the serving memory sampler read) →
    conservative default.
    """
    env = os.environ.get("DSTPU_HBM_BYTES")
    if env:
        return int(env)
    try:
        from deepspeed_tpu.accelerator.real_accelerator import \
            get_accelerator
        limit = int(get_accelerator().memory_snapshot()["bytes_limit"])
        if limit:
            return limit
    except Exception:
        pass
    return DEFAULT_HBM_BYTES


def estimate_zero_memory(num_params,
                         dp_size,
                         zero_stage,
                         micro_batch_size,
                         activation_bytes_per_sample=0,
                         param_dtype_bytes=2,
                         master_dtype_bytes=4,
                         optimizer_slots=2):
    """Analytic per-chip memory for a ZeRO stage (the reference's tuning-space
    prune, ``autotuner.py:524`` ``_generate_experiments``).

    Returns bytes: 16-bit params + fp32 grads-accum + fp32 master & optimizer
    slots, each divided over dp according to what the stage shards, plus a
    linear activation term.
    """
    p = num_params
    param_mem = p * param_dtype_bytes / (dp_size if zero_stage >= 3 else 1)
    grad_mem = p * master_dtype_bytes / (dp_size if zero_stage >= 2 else 1)
    opt_mem = (p * master_dtype_bytes * (1 + optimizer_slots)
               / (dp_size if zero_stage >= 1 else 1))
    act_mem = activation_bytes_per_sample * micro_batch_size
    return int(param_mem + grad_mem + opt_mem + act_mem)


def xla_memory_analysis(compiled):
    """Exact compile-time memory of a lowered+compiled XLA program
    (``compiled.memory_analysis()``): argument / output / temp / alias /
    generated-code bytes, plus ``total_bytes`` = arg + out + temp −
    alias (the program's live working set — what it actually costs the
    device on top of buffers it aliases in place).  Exact on TPU,
    stable on the tier-1 CPU backend (the memory/FLOP contracts in
    ``PROGRAMS.lock`` are locked from this).  Returns ``None`` when the
    backend does not expose the analysis.
    """
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        out = {}
        for key in ("temp_size_in_bytes", "argument_size_in_bytes",
                    "output_size_in_bytes", "alias_size_in_bytes",
                    "generated_code_size_in_bytes"):
            out[key] = int(getattr(ma, key, 0) or 0)
        out["total_bytes"] = (out["temp_size_in_bytes"] + out["argument_size_in_bytes"]
                              + out["output_size_in_bytes"] - out["alias_size_in_bytes"])
        return out
    except Exception:
        return None


def xla_cost_analysis(compiled):
    """XLA's raw cost-analysis dict for a compiled program, normalized
    to a plain dict (some backends return a one-element list).  Keys of
    interest: ``'flops'`` and ``'bytes accessed'`` — THE shared cost
    model: the flops profiler, the memory/FLOP program contracts
    (``tools/lint/mem_contract.py``) and the bench roofline blocks all
    read compiled programs through this one extraction."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return dict(ca) if hasattr(ca, "get") else {}
    except Exception:
        return {}


def xla_flops_analysis(compiled):
    """XLA's own flop estimate for the program (feeds the FLOPS metric)."""
    return float(xla_cost_analysis(compiled).get("flops", 0.0))


def compiled_costs(compiled):
    """``{"flops", "bytes_accessed", "transcendentals"}`` (floats) from
    a compiled program's cost analysis — the roofline numerators."""
    ca = xla_cost_analysis(compiled)
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "transcendentals": float(ca.get("transcendentals", 0.0)),
    }
