"""Async HTTP front end for the serving engine (``docs/serving.md``
"Network front end") — the transport between "millions of users" and the
fixed-capacity slot scheduler.  Stdlib-only: an ``asyncio`` HTTP/1.1
server (no framework dependency survives a hermetic TPU pod image).

Endpoints
---------
- ``POST /v1/generate`` — submit one request.  JSON body::

      {"input_ids": [...], "max_new_tokens": 32, "eos_token_id": -1,
       "deadline_s": null, "client_id": "tenant-a", "priority": 0,
       "stream": false}

  Blocking (default): responds once the request reaches a terminal
  status with ``{"rid", "status", "output", "detail", "ttft_s",
  "client_id"}``.  ``"stream": true``: responds immediately with
  ``Transfer-Encoding: chunked`` + ``application/x-ndjson`` and writes
  one JSON line per token event as the host mirror drains it —
  ``{"event": "token", "rid", "index", "token"}`` per token, then
  exactly one ``{"event": "end", "rid", "status", "detail"}`` — so TTFT
  and time-between-tokens are observable on the wire.  A client that
  disconnects mid-stream cancels its request (its slot frees at the
  next scheduling point).
- ``GET /v1/requests/<rid>`` — status poll (``404`` for ids this server
  never issued); terminal requests include the result payload.
- ``DELETE /v1/requests/<rid>`` — cancel (``404`` unknown; ``200`` with
  ``{"cancelled": bool}`` — ``false`` when already terminal).
- ``GET /healthz`` — scheduler snapshot: breaker state, queue depth,
  slot occupancy, in-flight events, uptime (``503`` once the engine is
  closed/preempted).
- ``GET /metrics`` — Prometheus text (``dstpu_serving_*``) from the
  engine's monitor counters, plus per-client fairness window balances.
  Every series carries ``# HELP``/``# TYPE`` lines and label values are
  fully escaped (``\\``, ``"``, newline) — the exposition round-trips
  through the text-format parser the tests ship.  With
  ``serving.tracing`` on, the TTFT / time-between-tokens / queue-wait /
  per-program dispatch-duration / lock-wait histograms are exported too
  (``docs/observability.md``).
- ``GET /debug/flightrec`` — the flight-recorder ring as JSON (``404``
  unless ``serving.flight_recorder`` is on).  SIGUSR2 (when signal
  handlers are installed) dumps the same ring to a file without
  touching the engine lock.
- ``POST /debug/profile?secs=N`` — on-demand ``jax.profiler`` capture
  for device-level traces (``404`` unless ``serving.profile_endpoint``;
  ``409`` while another capture runs); responds with the trace
  directory.

Error mapping: over-quota / full queue → ``429`` (:class:`QueueFull`),
open circuit breaker / closed engine → ``503``, malformed request →
``400``, unknown rid → ``404``.

Threading model (the part the engine's lock alone cannot give you)
------------------------------------------------------------------
THREE kinds of thread, one scheduler owner:

1. The **asyncio loop thread** parses HTTP and serializes responses.
   Handlers only ever call the engine's thread-safe surface
   (``submit``/``result``/``cancel``/``status``/``token_events``) — via
   ``run_in_executor`` so a blocked ``submit()`` (queue_policy="block")
   never stalls the event loop.
2. The **scheduler-owner thread** is the ONLY caller of ``step()`` /
   ``preempt()`` — the engine binds its owner on the first driving call
   and raises for any other thread (the host mirror's lag-one protocol
   is stateful across calls).  Idle, it sleeps on ``srv.wake`` which
   ``submit()``/``restore()`` set, so an empty server burns no CPU.
3. Engine → loop bridging is ``loop.call_soon_threadsafe`` from the
   ``token_events`` ``on_event`` hook (never blocks, safe under the
   engine lock).

One decode executable for the server lifetime: the front end adds ZERO
jitted programs — it is pure orchestration over the engine's existing
traced-argument programs (the ``@hot_path`` registration below is the
lint/contract gate's conscious-orchestrator marker, not a program).

SIGTERM (``install_signal_handlers=True``) requests graceful preemption:
the scheduler thread stops admission, drains under the config budget,
snapshots undrained requests crash-atomically (fairness balances and
priorities ride the snapshot), and every active stream ends with a typed
``PREEMPTED`` event instead of a dead socket.  A restarted server
``restore()``s and finishes them bitwise
(``tests/unit/test_serving_frontend.py``).
"""

import asyncio
import json
import signal
import threading
import time

import numpy as np

from deepspeed_tpu.inference.serving.slo import (CircuitOpen, QueueFull,
                                                 RequestStatus,
                                                 TERMINAL_STATUSES)
from deepspeed_tpu.tools.lint.hotpath import hot_path
from deepspeed_tpu.utils.logging import logger

_MAX_BODY = 8 << 20                      # request bodies past this: 413


class _HTTPError(Exception):
    def __init__(self, code, message):
        super().__init__(message)
        self.code = code


_STATUS_TEXT = {200: "OK", 400: "Bad Request", 404: "Not Found",
                405: "Method Not Allowed", 408: "Request Timeout",
                413: "Payload Too Large", 429: "Too Many Requests",
                500: "Internal Server Error", 503: "Service Unavailable"}


class ServingHTTPFrontend:
    """Asyncio HTTP server over one :class:`ServingEngine`.

    ``port=0`` binds an ephemeral port (read ``self.port`` after
    :meth:`start`).  ``snapshot_dir`` is where SIGTERM preemption
    publishes its crash-atomic snapshot — without it a preempt request
    degrades to ``close()`` (undrained work ABORTED, never silently
    lost).  Use as a context manager or call :meth:`start` /
    :meth:`shutdown` explicitly::

        with ServingHTTPFrontend(srv, snapshot_dir=d) as fe:
            requests.post(f"http://127.0.0.1:{fe.port}/v1/generate", ...)
    """

    def __init__(self, srv, host="127.0.0.1", port=0, snapshot_dir=None,
                 idle_poll_s=0.05, max_body_bytes=_MAX_BODY):
        self.srv = srv
        self.host = host
        self.port = int(port)
        self.snapshot_dir = snapshot_dir
        self.idle_poll_s = float(idle_poll_s)
        self.max_body_bytes = int(max_body_bytes)
        self._loop = None
        self._server = None
        self._loop_thread = None
        self._sched_thread = None
        self._stop = threading.Event()
        self._preempt = threading.Event()
        self._profile_lock = threading.Lock()   # one capture at a time
        self._sched_error = None
        self.preempt_result = None       # (tag, rids, finished) after SIGTERM
        self._t0 = time.monotonic()
        self._prev_handlers = {}

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #
    def start(self):
        """Start the scheduler-owner thread (which claims the engine's
        owner role), then bind the port and the asyncio loop thread —
        in that order, so no HTTP request can race the ownership claim
        (a blocked ``queue_policy="block"`` submit would otherwise bind
        ITSELF as owner and wedge the scheduler).  Returns ``self``
        (``self.port`` holds the bound port)."""
        if self._loop is not None:
            raise RuntimeError("ServingHTTPFrontend already started")
        self._owner_ready = threading.Event()
        self._sched_thread = threading.Thread(
            target=self._scheduler_loop, name="dstpu-serving-scheduler",
            daemon=True)
        self._sched_thread.start()
        if not self._owner_ready.wait(timeout=30):
            self._stop.set()             # unwind the scheduler thread
            self.srv.wake.set()
            raise RuntimeError(
                "scheduler thread failed to claim the engine's owner "
                "role — was the engine already driven by another thread? "
                f"({self._sched_error})")
        if self._sched_error is not None:
            raise RuntimeError(f"scheduler thread failed to start: "
                               f"{self._sched_error}")
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._run_loop, name="dstpu-http-loop", daemon=True)
        self._loop_thread.start()
        fut = asyncio.run_coroutine_threadsafe(self._start_server(),
                                               self._loop)
        try:
            fut.result(timeout=30)
        except Exception:
            # e.g. the port is already bound: unwind BOTH threads — the
            # scheduler releases its owner binding on exit, so a retry
            # frontend (or the caller driving step() directly) can claim
            # the engine instead of finding it wedged forever
            self._stop.set()
            self.srv.wake.set()
            self._sched_thread.join(timeout=10)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop_thread.join(timeout=10)
            raise
        logger.info(f"[serving] HTTP front end listening on "
                    f"{self.host}:{self.port}")
        return self

    def _run_loop(self):
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    async def _start_server(self):
        # the StreamReader limit must cover the largest allowed body:
        # readexactly() on a body larger than the buffer limit deadlocks
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port,
            limit=self.max_body_bytes + 65536)
        self.port = self._server.sockets[0].getsockname()[1]

    def request_preempt(self):
        """Ask the scheduler thread to preempt gracefully (the SIGTERM
        path, callable from any thread/signal handler — sets a flag and
        wakes the owner; never touches the engine directly)."""
        self._preempt.set()
        self.srv.wake.set()

    def install_signal_handlers(self, signals=(signal.SIGTERM,)):
        """Route SIGTERM to :meth:`request_preempt` (main thread only —
        CPython restricts ``signal.signal``).  Previous handlers are
        restored by :meth:`shutdown`.  When the engine carries a flight
        recorder, SIGUSR2 additionally dumps its ring to a file
        (``docs/observability.md`` — the recorder never takes the
        engine lock, so the dump is safe from a signal frame)."""
        for sig in signals:
            self._prev_handlers[sig] = signal.signal(
                sig, lambda *_: self.request_preempt())
        if getattr(self.srv, "flightrec_enabled", False):
            self.install_flightrec_signal_handler()

    def install_flightrec_signal_handler(self, sig=None):
        """Route SIGUSR2 (or ``sig``) to a flight-recorder dump.  Main
        thread only; restored by :meth:`shutdown`."""
        sig = sig if sig is not None else signal.SIGUSR2
        self._prev_handlers[sig] = signal.signal(
            sig, lambda *_: self._dump_flightrec_signal())

    def _dump_flightrec_signal(self):
        try:
            path = self.srv.dump_flightrec(reason="sigusr2")
            logger.warning(f"[serving] SIGUSR2: flight recorder dumped "
                           f"to {path}")
        except Exception as e:           # noqa: BLE001 — signal frame
            logger.warning(f"[serving] SIGUSR2 flight-recorder dump "
                           f"failed: {type(e).__name__}: {e}")

    def _scheduler_loop(self):
        """The single scheduler owner: drives ``step()`` while work is
        pending, sleeps on ``srv.wake`` when idle, and runs the graceful
        preemption on request.  Registered as a conscious ORCHESTRATOR
        with the lint/contract gates — it dispatches the engine's
        existing programs and must never mint one of its own."""
        self._scheduler_body()

    @hot_path("serving.http_frontend_loop")
    def _scheduler_body(self):
        srv = self.srv
        try:
            srv.bind_owner()             # before any request can arrive
        except Exception as e:           # noqa: BLE001
            self._sched_error = f"{type(e).__name__}: {e}"
            self._owner_ready.set()
            return
        self._owner_ready.set()
        try:
            while not self._stop.is_set():
                if self._preempt.is_set():
                    self._do_preempt()
                    return
                if srv.work_pending():   # one lock round-trip, not three
                    srv.step()
                else:
                    srv.wake.wait(timeout=self.idle_poll_s)
                    srv.wake.clear()
        except Exception as e:           # noqa: BLE001 — surfaced via healthz
            self._sched_error = f"{type(e).__name__}: {e}"
            logger.error(f"[serving] scheduler thread died: "
                         f"{self._sched_error}")
            # a dead scheduler is exactly what the flight recorder
            # exists for: dump the ring BEFORE close() clears the scene
            try:
                if getattr(srv, "flightrec_enabled", False):
                    srv._flightrec.record("scheduler_thread_death",
                                          error=self._sched_error[:200])
                    srv.dump_flightrec(reason="scheduler_thread_death")
            except Exception:            # noqa: BLE001 — best effort
                pass
            # nothing will drive the engine again: close it so every
            # in-flight request ends with a typed ABORTED event (waiting
            # handlers unblock) and new submits get 503 instead of
            # queueing into a void
            try:
                srv.close()
            except Exception as ce:      # noqa: BLE001
                logger.error(f"[serving] close after scheduler death "
                             f"failed: {type(ce).__name__}: {ce}")
        finally:
            # the exiting owner releases its binding so a successor
            # driver (a retry frontend after a failed start(), or the
            # caller after shutdown(close_engine=False)) can claim the
            # engine instead of finding it bound to a dead thread
            try:
                srv.release_owner()
            except Exception:            # noqa: BLE001
                pass

    def _do_preempt(self):
        srv = self.srv
        try:
            if self.snapshot_dir:
                self.preempt_result = srv.preempt(self.snapshot_dir)
                tag, snapped, _ = self.preempt_result
                logger.warning(f"[serving] HTTP front end preempted — "
                               f"snapshot {tag!r} holds {len(snapped)} "
                               f"request(s)")
            else:
                logger.warning("[serving] preempt requested with no "
                               "snapshot_dir — closing (undrained work "
                               "ABORTED, typed status preserved)")
                srv.close()
        except Exception as e:           # noqa: BLE001
            self._sched_error = f"{type(e).__name__}: {e}"
            logger.error(f"[serving] preempt failed: {self._sched_error}")
            try:                         # same rationale as scheduler death
                srv.close()
            except Exception:            # noqa: BLE001
                pass

    def shutdown(self, close_engine=False):
        """Stop the scheduler thread, close the listener and the loop;
        ``close_engine=True`` also retires the engine (undrained work
        ABORTED).  Idempotent."""
        self._stop.set()
        self.srv.wake.set()
        if self._sched_thread is not None:
            self._sched_thread.join(timeout=30)
        if self._loop is not None and not self._loop.is_closed():
            async def _close():
                self._server.close()
                await self._server.wait_closed()
                # keep-alive connections park in readuntil() waiting for
                # a next request that will never come — cancel them so
                # the loop stops clean instead of destroying live tasks
                mine = asyncio.current_task()
                pending = [t for t in asyncio.all_tasks()
                           if t is not mine]
                for t in pending:
                    t.cancel()
                await asyncio.gather(*pending, return_exceptions=True)
            try:
                asyncio.run_coroutine_threadsafe(
                    _close(), self._loop).result(timeout=10)
            except Exception:            # noqa: BLE001 — already closing
                pass
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop_thread.join(timeout=10)
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers.clear()
        if close_engine:
            self.srv.close()             # idempotent; takes its own lock

    def join_preempted(self, timeout=60):
        """Block until the scheduler thread has finished a requested
        preemption (snapshot published); returns ``preempt_result``."""
        self._sched_thread.join(timeout=timeout)
        if self._sched_thread.is_alive():
            raise TimeoutError("scheduler thread still running — "
                               "preemption did not complete")
        return self.preempt_result

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # ------------------------------------------------------------------ #
    # HTTP plumbing
    # ------------------------------------------------------------------ #
    async def _handle_conn(self, reader, writer):
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except _HTTPError as e:
                    # malformed head / oversized body: the request
                    # framing can't be trusted past this point — answer
                    # the error, then drop the connection
                    await self._respond(writer, e.code,
                                        {"error": str(e)})
                    break
                if req is None:
                    break
                keep_alive = await self._route(req, writer)
                if not keep_alive:
                    break
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.LimitOverrunError):
            pass                         # client went away / oversized head
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _read_request(self, reader):
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except asyncio.IncompleteReadError:
            return None                  # clean EOF between requests
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, path, _version = lines[0].split(" ", 2)
        except ValueError:
            raise _HTTPError(400, f"malformed request line: {lines[0]!r}")
        headers = {}
        for line in lines[1:]:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        raw_n = headers.get("content-length")
        try:
            n = int(raw_n) if raw_n else 0
        except ValueError:
            raise _HTTPError(400, f"malformed Content-Length: {raw_n!r}")
        if n < 0:
            raise _HTTPError(400, f"negative Content-Length: {raw_n!r}")
        if n > self.max_body_bytes:
            raise _HTTPError(413, f"body of {n} bytes exceeds the "
                                  f"{self.max_body_bytes}-byte limit")
        body = await reader.readexactly(n) if n else b""
        return {"method": method.upper(), "path": path,
                "headers": headers, "body": body}

    @staticmethod
    def _head(code, ctype, extra=""):
        return (f"HTTP/1.1 {code} {_STATUS_TEXT.get(code, 'Unknown')}\r\n"
                f"Content-Type: {ctype}\r\n{extra}")

    async def _respond(self, writer, code, payload, ctype=None):
        if isinstance(payload, (dict, list)):
            body = (json.dumps(payload) + "\n").encode()
            ctype = ctype or "application/json"
        else:
            body = payload if isinstance(payload, bytes) \
                else str(payload).encode()
            ctype = ctype or "text/plain; charset=utf-8"
        writer.write(self._head(code, ctype).encode()
                     + f"Content-Length: {len(body)}\r\n"
                       f"Connection: keep-alive\r\n\r\n".encode() + body)
        await writer.drain()
        return True

    async def _route(self, req, writer):
        method = req["method"]
        path, _, query = req["path"].partition("?")
        try:
            if path == "/v1/generate" and method == "POST":
                return await self._generate(req, writer)
            if path == "/healthz" and method == "GET":
                return await self._healthz(writer)
            if path == "/metrics" and method == "GET":
                return await self._metrics(writer)
            if path == "/debug/flightrec" and method == "GET":
                return await self._debug_flightrec(writer)
            if path == "/debug/profile" and method == "POST":
                return await self._debug_profile(query, writer)
            if path.startswith("/v1/requests/"):
                return await self._request_resource(method, path, writer)
            return await self._respond(
                writer, 404, {"error": f"no route {method} {path}"})
        except _HTTPError as e:
            return await self._respond(writer, e.code, {"error": str(e)})
        except (ConnectionError, asyncio.IncompleteReadError):
            raise
        except Exception as e:           # noqa: BLE001 — 500, keep serving
            logger.error(f"[serving] handler error on {method} {path}: "
                         f"{type(e).__name__}: {e}")
            try:
                return await self._respond(
                    writer, 500, {"error": f"{type(e).__name__}: {e}"})
            except (ConnectionError, OSError):
                return False

    # ------------------------------------------------------------------ #
    # /v1/generate
    # ------------------------------------------------------------------ #
    def _parse_generate(self, body):
        try:
            spec = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError) as e:
            raise _HTTPError(400, f"request body is not JSON: {e}")
        if not isinstance(spec, dict):
            raise _HTTPError(400, "request body must be a JSON object")
        ids = spec.get("input_ids")
        if not isinstance(ids, list) or not ids \
                or not all(isinstance(t, int) for t in ids):
            raise _HTTPError(400, "input_ids: non-empty list of ints "
                                  "required")
        known = {"input_ids", "max_new_tokens", "eos_token_id",
                 "deadline_s", "client_id", "priority", "stream"}
        unknown = set(spec) - known
        if unknown:
            raise _HTTPError(400, f"unknown field(s) {sorted(unknown)} — "
                                  f"accepted: {sorted(known)}")
        return spec

    def _submit_from_spec(self, spec):
        """Engine submit with the HTTP error mapping (runs in an
        executor thread: queue_policy='block' may wait here)."""
        try:
            return self.srv.submit(
                np.asarray(spec["input_ids"], np.int32),
                max_new_tokens=int(spec.get("max_new_tokens", 32)),
                eos_token_id=int(spec.get("eos_token_id", -1)),
                deadline_s=spec.get("deadline_s"),
                client_id=spec.get("client_id"),
                priority=int(spec.get("priority", 0)))
        except QueueFull as e:           # over quota / full queue
            raise _HTTPError(429, str(e))
        except CircuitOpen as e:
            raise _HTTPError(503, str(e))
        except (TypeError, ValueError) as e:
            raise _HTTPError(400, str(e))
        except RuntimeError as e:        # closed engine
            raise _HTTPError(503, str(e))

    def _result_payload(self, rid):
        res = self.srv.result(rid)
        if res is None:                  # PREEMPTED ends without a result
            return {"rid": rid, "status": self.srv.status(rid),
                    "output": None, "detail": "", "ttft_s": None,
                    "client_id": None}
        return {"rid": rid, "status": res.status,
                "output": res.output.tolist()
                if res.output is not None else None,
                "detail": res.detail, "ttft_s": res.ttft_s,
                "client_id": res.client_id}

    async def _generate(self, req, writer):
        spec = self._parse_generate(req["body"])
        loop = asyncio.get_running_loop()
        if not spec.get("stream"):
            rid = await loop.run_in_executor(
                None, self._submit_from_spec, spec)
            done = asyncio.Event()

            def on_ev(ev, _loop=loop, _done=done):
                # called under the engine lock — hand off, never block
                if ev.get("event") == "end":
                    _loop.call_soon_threadsafe(_done.set)

            # engine calls take the engine lock, which the scheduler
            # thread holds across step() — keep them off the loop thread
            await loop.run_in_executor(
                None, self.srv.token_events, rid, on_ev)
            await done.wait()
            payload = await loop.run_in_executor(
                None, self._result_payload, rid)
            return await self._respond(writer, 200, payload)
        # streaming: subscribe BEFORE any await so no event can slip
        # between submit and subscription (token_events replays anyway —
        # this just keeps the replay empty in the common case)
        rid = await loop.run_in_executor(
            None, self._submit_from_spec, spec)
        q = asyncio.Queue()

        def on_ev(ev, _loop=loop, _q=q):
            _loop.call_soon_threadsafe(_q.put_nowait, ev)

        await loop.run_in_executor(
            None, self.srv.token_events, rid, on_ev)
        writer.write(
            self._head(200, "application/x-ndjson",
                       "Transfer-Encoding: chunked\r\n"
                       "Connection: close\r\n"
                       "X-Accel-Buffering: no\r\n").encode() + b"\r\n")
        try:
            while True:
                ev = await q.get()
                line = (json.dumps(ev) + "\n").encode()
                writer.write(f"{len(line):x}\r\n".encode() + line
                             + b"\r\n")
                await writer.drain()     # flush per token event
                if ev.get("event") == "end":
                    break
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except (ConnectionError, OSError):
            # client hung up mid-stream: release its slot
            def _cancel():
                try:
                    self.srv.cancel(rid)
                except KeyError:
                    pass
            await loop.run_in_executor(None, _cancel)
            return False
        return False                     # Connection: close after a stream

    # ------------------------------------------------------------------ #
    # /v1/requests/<rid>
    # ------------------------------------------------------------------ #
    async def _request_resource(self, method, path, writer):
        tail = path[len("/v1/requests/"):]
        try:
            rid = int(tail)
        except ValueError:
            raise _HTTPError(400, f"request id must be an int, got "
                                  f"{tail!r}")
        srv = self.srv
        loop = asyncio.get_running_loop()
        if method == "GET":
            def _status_payload():
                status = srv.status(rid)
                payload = {"rid": rid, "status": status}
                if status in TERMINAL_STATUSES \
                        or status == RequestStatus.PREEMPTED:
                    payload.update(self._result_payload(rid))
                return payload
            try:
                payload = await loop.run_in_executor(
                    None, _status_payload)
            except KeyError as e:
                raise _HTTPError(404, str(e))
            return await self._respond(writer, 200, payload)
        if method == "DELETE":
            def _cancel_payload():
                return {"rid": rid, "cancelled": bool(srv.cancel(rid)),
                        "status": srv.status(rid)}
            try:
                payload = await loop.run_in_executor(
                    None, _cancel_payload)
            except KeyError as e:
                raise _HTTPError(404, str(e))
            return await self._respond(writer, 200, payload)
        raise _HTTPError(405, f"{method} not allowed on {path}")

    # ------------------------------------------------------------------ #
    # /healthz and /metrics
    # ------------------------------------------------------------------ #
    async def _healthz(self, writer):
        # ONE locked engine snapshot, taken off the loop thread: piecing
        # the payload together from unlocked field reads both raced the
        # scheduler and (worse) blocked the event loop on the engine
        # lock across a step() — the TL008/TL009 bug classes
        snap = await asyncio.get_running_loop().run_in_executor(
            None, self.srv.health_snapshot)
        payload = {
            "ok": not snap["closed"] and self._sched_error is None,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            **snap,
            "scheduler_error": self._sched_error,
        }
        return await self._respond(
            writer, 503 if snap["closed"] else 200, payload)

    @staticmethod
    def _esc_label(v):
        """Prometheus text-format label-value escaping: backslash,
        double quote and newline (exposition-format spec)."""
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    @staticmethod
    def _esc_help(v):
        """HELP-line escaping: backslash and newline."""
        return str(v).replace("\\", "\\\\").replace("\n", "\\n")

    def _metrics_body(self):
        """Render the Prometheus text (runs in an executor thread; the
        snapshot is taken under the engine lock — the scheduler thread
        grows ``stats`` and the fairness tracker compacts its window
        map in place, so an unlocked iteration can race both).  Every
        series carries ``# HELP``/``# TYPE``; label values are escaped;
        the round-trip test parses the full output back
        (``tests/unit/test_serving_trace.py``)."""
        srv = self.srv
        with srv._lock:
            mem = None
            if srv._memwatch is not None:
                # the scheduler seam owns the sampling cadence; the
                # scrape only forces a sample when none exists yet (a
                # server scraped before its first step)
                mem = srv._memwatch.last
                if mem is None:
                    mem = srv._memwatch.sample()
                    srv._sample_memory_into_stats(mem)
            stats = dict(srv.stats)
            lock_wait = dict(srv._lock.wait_s)
            snap = {
                "queue_depth": srv.queue_depth,
                "active_slots": srv.active_slots,
                "in_flight": srv.in_flight,
                "breaker_open": srv._breaker.open,
                "paged_util": srv.page_pool_utilization
                if srv.paged else None,
                "fairness": None if srv._fairness is None
                else sorted(srv._fairness.window_usage().items()),
                "fairness_budget": None if srv._fairness is None
                else srv._fairness.budget,
                # serving.memory_telemetry: the newest interval sample
                "memory": mem,
            }
        hist = srv.histograms()          # internally locked; may be None
        lines = []

        def series(name, help_, type_, samples):
            """One metric family: HELP/TYPE exactly once, then every
            sample — ``samples`` is ``[(suffix, labels_dict, value)]``
            (suffix: ``""`` for gauges, ``_bucket``/``_sum``/``_count``
            for histograms)."""
            lines.append(f"# HELP {name} {self._esc_help(help_)}")
            lines.append(f"# TYPE {name} {type_}")
            for suffix, labels, value in samples:
                lab = ""
                if labels:
                    inner = ",".join(
                        f'{k}="{self._esc_label(v)}"'
                        for k, v in labels.items())
                    lab = "{" + inner + "}"
                lines.append(f"{name}{suffix}{lab} {float(value)}")

        def gauge(name, value, help_, labels=None):
            series(f"dstpu_serving_{name}", help_, "gauge",
                   [("", labels or {}, value)])

        for key, val in sorted(stats.items()):
            gauge(key, val, help_=f"serving engine counter {key!r}")
        gauge("queue_depth", snap["queue_depth"],
              "queued + pending prefill")
        gauge("active_slots", snap["active_slots"],
              "host-mirror live slots")
        gauge("slot_occupancy", snap["active_slots"] / srv.num_slots,
              "live / total slots")
        gauge("in_flight_events", snap["in_flight"],
              "dispatched device events not yet processed")
        gauge("breaker_open", 1.0 if snap["breaker_open"] else 0.0,
              "dispatch circuit breaker state")
        gauge("uptime_seconds", time.monotonic() - self._t0,
              "front-end uptime")
        series("dstpu_serving_lock_wait_seconds",
               "cumulative wall time waiting on the engine lock per "
               "thread class", "gauge",
               [("", {"thread_class": cls}, lock_wait[cls])
                for cls in sorted(lock_wait)])
        if snap["paged_util"] is not None:
            gauge("page_pool_utilization", snap["paged_util"],
                  "allocated fraction of the KV page pool")
        if snap["fairness"] is not None:
            series("dstpu_serving_fairness_window_tokens",
                   "per-client decayed window balance", "gauge",
                   [("", {"client": key}, bal)
                    for key, bal in snap["fairness"]])
            gauge("fairness_budget", snap["fairness_budget"],
                  "window budget above which submit() is 429'd")
        if snap["memory"] is not None:
            # serving.memory_telemetry (docs/observability.md "Device
            # memory & roofline"): per-device in-use/peak/limit from the
            # accelerator's canonical reader, the engine's owner
            # reconciliation, and the unattributed gap — the family
            # names are the memwatch.MEMORY_SERIES literal the
            # stats-docs gate pins to the docs
            mem = snap["memory"]
            series("dstpu_device_memory_bytes_in_use",
                   "device bytes in use (accelerator memory_snapshot)",
                   "gauge",
                   [("", {"device": d["device"]}, d["bytes_in_use"])
                    for d in mem["devices"]])
            series("dstpu_device_memory_peak_bytes",
                   "peak device bytes in use since process start",
                   "gauge",
                   [("", {"device": d["device"]}, d["peak_bytes_in_use"])
                    for d in mem["devices"]])
            series("dstpu_device_memory_limit_bytes",
                   "device memory budget (runtime bytes_limit or "
                   "datasheet capacity; 0 = unknown)", "gauge",
                   [("", {"device": d["device"],
                          "source": d["limit_source"]},
                     d["bytes_limit"]) for d in mem["devices"]])
            series("dstpu_device_memory_owned_bytes",
                   "bytes attributed to a known serving-engine owner",
                   "gauge",
                   [("", {"owner": o}, b)
                    for o, b in sorted(mem["owners"].items())])
            series("dstpu_device_memory_unattributed_bytes",
                   "device bytes in use beyond every known owner — "
                   "where leaks hide", "gauge",
                   [("", {}, mem["unattributed_bytes"])])
        if hist is not None:
            # serving.tracing: the TTFT / TBT / queue-wait / dispatch /
            # lock-wait histograms (docs/observability.md)
            for name, help_, samples in hist.collect():
                series(name, help_, "histogram", samples)
        return ("\n".join(lines) + "\n").encode()

    async def _metrics(self, writer):
        body = await asyncio.get_running_loop().run_in_executor(
            None, self._metrics_body)
        return await self._respond(
            writer, 200, body,
            ctype="text/plain; version=0.0.4; charset=utf-8")

    # ------------------------------------------------------------------ #
    # /debug/flightrec and /debug/profile (docs/observability.md)
    # ------------------------------------------------------------------ #
    async def _debug_flightrec(self, writer):
        """The flight-recorder ring as JSON.  The snapshot never takes
        the engine lock (the ring is self-locked), but it copies up to
        ``flight_recorder_events`` dicts — off the loop thread."""
        snap = await asyncio.get_running_loop().run_in_executor(
            None, self.srv.flightrec_snapshot)
        if snap is None:
            raise _HTTPError(
                404, "flight recorder disabled — set "
                     "serving.flight_recorder (docs/observability.md)")
        return await self._respond(writer, 200, snap)

    async def _debug_profile(self, query, writer):
        """On-demand ``jax.profiler`` capture: blocks an executor
        thread for ``secs`` (clamped to 60), never the loop; one
        capture at a time (409 while one runs)."""
        if not getattr(self.srv.config, "profile_endpoint", False):
            raise _HTTPError(
                404, "profiling endpoint disabled — set "
                     "serving.profile_endpoint (docs/observability.md)")
        import math
        import urllib.parse
        params = urllib.parse.parse_qs(query)
        try:
            secs = float(params.get("secs", ["1"])[0])
        except ValueError:
            raise _HTTPError(400, f"secs must be a number, got "
                                  f"{params.get('secs')!r}")
        if not math.isfinite(secs):      # NaN slips through min/max
            raise _HTTPError(400, f"secs must be finite, got {secs!r}")
        secs = min(max(secs, 0.0), 60.0)

        def capture():
            if not self._profile_lock.acquire(blocking=False):
                raise _HTTPError(409, "a profile capture is already "
                                      "running — retry when it ends")
            try:
                import tempfile
                import jax
                d = tempfile.mkdtemp(prefix="dstpu_profile_")
                jax.profiler.start_trace(d)
                try:
                    time.sleep(secs)
                finally:
                    jax.profiler.stop_trace()
                return d
            finally:
                self._profile_lock.release()

        d = await asyncio.get_running_loop().run_in_executor(
            None, capture)
        return await self._respond(
            writer, 200, {"trace_dir": d, "secs": secs})


def serve_http(srv, **kwargs):
    """Convenience: ``ServingHTTPFrontend(srv, **kwargs).start()``."""
    return ServingHTTPFrontend(srv, **kwargs).start()


__all__ = ["ServingHTTPFrontend", "serve_http"]
