"""Public ZeRO API — reference ``deepspeed.zero`` surface.

* ``zero.Init`` (reference ``partition_parameters.py:603``): sharded-at-birth
  parameter initialization.  The reference monkey-patches ``nn.Module`` so
  every parameter is partitioned the moment it is constructed; under GSPMD
  the same contract is an ``out_shardings`` on the jitted init program — the
  full weights never materialize on any single device.  The engine does this
  automatically (``engine.py _lazy_init``); this context exists for
  reference-API users who initialize params outside the engine.

* ``zero.GatheredParameters`` (reference ``partition_parameters.py:1553``):
  temporarily materialize full (unsharded) values of ZeRO-partitioned params
  for inspection or surgery, then re-scatter with the original shardings on
  exit — the functional analog of the reference's gather → modify →
  re-partition protocol (DeepSpeed-Chat uses this for LoRA/EMA surgery).
"""

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.zero.partition import (ZeroShardingPlan,  # noqa: F401
                                                  build_sharding_plan)

_ACTIVE_INIT = []


class Init:
    """Sharded-at-birth init context.

    Usage (engine-external; inside the engine this happens automatically)::

        with zero.Init(config=ds_config) as zinit:
            params = zinit.materialize(model.init, rng, sample_batch)

    ``materialize`` builds the ZeRO sharding plan from the abstract shapes
    (``jax.eval_shape`` — no memory) and runs the init program with sharded
    ``out_shardings``; the plan is exposed as ``.plan``.
    """

    def __init__(self, module=None, config=None, config_dict_or_path=None,
                 mesh=None, dtype=None, enabled=True, **_compat_ignored):
        self.enabled = enabled
        self.dtype = dtype
        self.plan = None
        cfg = config if config is not None else config_dict_or_path
        self._zero_config = self._resolve_zero_config(cfg)
        self._mesh = mesh

    @staticmethod
    def _resolve_zero_config(cfg):
        if cfg is None:
            # the reference zero.Init partitions unconditionally — default
            # to stage 3 so the sharded-at-birth contract holds with no cfg
            cfg = {"zero_optimization": {"stage": 3}}
        if isinstance(cfg, str):          # path to a DeepSpeed config json
            import json
            with open(cfg) as f:
                cfg = json.load(f)
        if isinstance(cfg, dict):
            from deepspeed_tpu.runtime.config import DeepSpeedConfig
            full = dict(cfg)
            full.setdefault("train_micro_batch_size_per_gpu", 1)
            return DeepSpeedConfig(full).zero_config
        return getattr(cfg, "zero_config", cfg)

    def __enter__(self):
        if self.enabled:
            _ACTIVE_INIT.append(self)
        return self

    def __exit__(self, *exc):
        if self.enabled and _ACTIVE_INIT and _ACTIVE_INIT[-1] is self:
            _ACTIVE_INIT.pop()
        return False

    @staticmethod
    def is_active():
        return bool(_ACTIVE_INIT)

    def materialize(self, init_fn, rng, *args, **kwargs):
        """Run ``init_fn(rng, *args, **kwargs)`` with ZeRO-sharded outputs."""
        if not self.enabled:              # pure passthrough, no side effects
            return init_fn(rng, *args, **kwargs)
        from deepspeed_tpu.parallel.topology import get_topology
        topo = get_topology()
        if self._mesh is not None and self._mesh != topo.mesh:
            raise ValueError(
                "zero.Init(mesh=...) differs from the live topology's mesh — "
                "shardings are built on the global topology; call "
                "initialize_topology(...) with the desired axes first")
        abstract = jax.eval_shape(lambda r: init_fn(r, *args, **kwargs), rng)
        if self.dtype is not None:
            abstract = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(
                    s.shape, self.dtype
                    if jnp.issubdtype(s.dtype, jnp.floating) else s.dtype),
                abstract)
        self.plan = build_sharding_plan(abstract, topo, self._zero_config)
        cast = (lambda p: p.astype(self.dtype)
                if self.dtype is not None
                and jnp.issubdtype(p.dtype, jnp.floating) else p)
        init_jit = jax.jit(
            lambda r: jax.tree.map(cast, init_fn(r, *args, **kwargs)),
            out_shardings=self.plan.param_shardings)
        return init_jit(rng)


class GatheredParameters:
    """Materialize sharded params as host numpy arrays, re-shard on exit.

    ::

        with zero.GatheredParameters(engine.params) as g:
            g.full["embed_tokens"]["embedding"][:vocab] = new_rows
        engine.load_params(g.params)    # re-sharded pytree

    ``full`` is a pytree of *mutable* numpy arrays (in-place surgery is the
    point); ``params`` (available after exit) is the re-sharded device tree.
    ``modifier_rank`` is accepted for API parity — under SPMD every process
    executes the same surgery, which IS the rank-0-then-broadcast semantics
    of the reference.  ``enabled=False`` (reference: params not
    ZeRO-partitioned, nothing to gather) is a zero-cost passthrough:
    ``full``/``params`` are the live — immutable — device tree; surgery
    requires ``enabled=True`` (jax arrays cannot be mutated in place).
    """

    def __init__(self, params, modifier_rank=0, fwd_module=None, enabled=True):
        self.enabled = enabled
        self._src = params
        self.full = None
        self.params = None
        self._shardings = None

    def __enter__(self):
        if not self.enabled:
            self.full = self._src
            return self
        self._shardings = jax.tree.map(lambda l: l.sharding, self._src)

        def gather(l):
            if hasattr(l, "is_fully_addressable") and \
                    not l.is_fully_addressable:
                # multi-host: shards live on non-addressable devices — pull
                # every process's shards (the reference gathers via NCCL)
                from jax.experimental import multihost_utils
                return np.array(multihost_utils.process_allgather(
                    l, tiled=True))
            return np.array(jax.device_get(l))
        self.full = jax.tree.map(gather, self._src)
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is not None or not self.enabled:
            self.params = self._src
            return False
        # device_put straight from host numpy: each device receives only its
        # shard — wrapping in jnp.asarray first would commit the FULL tensor
        # to one device before resharding (an HBM spike that defeats ZeRO)
        self.params = jax.tree.map(
            lambda arr, sh: jax.device_put(arr, sh),
            self.full, self._shardings)
        return False
