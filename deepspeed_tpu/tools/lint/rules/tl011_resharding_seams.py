"""TL011 — implicit resharding seams.

A mesh program's sharding story should be decided at BUILD time (the
``parallel/topology.py`` helpers) and locked by the comm-cost contracts.
Two source patterns smuggle resharding decisions past that story:

* ``jax.device_put`` / ``with_sharding_constraint`` inside a registered
  ``@hot_path`` body — a mid-step placement change is an unscheduled,
  host-synchronized reshard: it serializes the dispatch pipeline and its
  collective traffic appears in no locked budget.  Placement belongs in
  setup code; a constraint XLA genuinely needs in the step gets a
  suppression with the reason.
* a ``shard_map`` whose literal ``in_specs``/``out_specs`` (or a traced
  collective's literal ``axis_name``) names a mesh axis that does not
  exist in the canonical topology (``parallel/topology.py`` AXIS_ORDER:
  pp/mdp/edp/ep/sp/tp) — an unknown axis either crashes at runtime or,
  worse, silently no-ops the sharding and replicates (GSPMD treats an
  unmatched axis as size 1).  Variable axis names (the common idiom) are
  out of static reach; the canonical-literal check catches the typo class.

``_CANONICAL_AXES`` mirrors ``topology.AXIS_ORDER`` as a pure literal (the
linter never imports the code under analysis);
``tests/unit/test_tpu_lint.py`` asserts the two stay identical.
"""

import ast

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule
from deepspeed_tpu.tools.lint.rules.tl010_replicated_sharding import (
    _callee_leaf, shard_map_applications, spec_entries)

# mirror of parallel.topology.AXIS_ORDER — registry-matched by a test
_CANONICAL_AXES = ("pp", "mdp", "edp", "ep", "sp", "tp")

_RESHARD_CALLS = ("device_put", "with_sharding_constraint")
# traced collectives whose first string argument is a mesh axis name
_AXIS_COLLECTIVES = ("psum", "pmean", "pmax", "pmin", "all_gather",
                     "all_to_all", "ppermute", "psum_scatter",
                     "axis_index", "pbroadcast")


def _literal_axis_names(node):
    """String axis names in a P(...) entry: constants and tuples of
    constants; anything non-literal is skipped."""
    out = []
    if not isinstance(node, ast.Call) or \
            _callee_leaf(node.func) not in ("P", "PartitionSpec"):
        return out
    for arg in node.args:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, arg))
        elif isinstance(arg, (ast.Tuple, ast.List)):
            for e in arg.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append((e.value, e))
    return out


@rule("TL011", "implicit resharding seams")
def check(module):
    # (a) mid-step placement changes inside hot paths
    for fn in module.hot_functions():
        nested = set()
        for child in ast.walk(fn.node):
            if child is not fn.node and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.update(ast.walk(child))
        for node in ast.walk(fn.node):
            if node in nested or not isinstance(node, ast.Call):
                continue
            leaf = _callee_leaf(node.func)
            if leaf in _RESHARD_CALLS:
                yield Finding(
                    "TL011", module.path, node.lineno, node.col_offset,
                    f"{leaf} inside hot path '{fn.hot_name or fn.name}' — "
                    f"a mid-step reshard is host-synchronized and its "
                    f"collective traffic is in no locked comm budget; "
                    f"place buffers at setup time (suppress with the "
                    f"reason when the constraint is the design)")

    # (b) literal axis names the canonical topology does not define
    for line, col, kwargs, _params in shard_map_applications(module):
        for key in ("in_specs", "out_specs"):
            entries = spec_entries(module, kwargs.get(key), line) or []
            for entry in entries:
                for sub in ast.walk(entry):
                    for axis, node in _literal_axis_names(sub):
                        if axis not in _CANONICAL_AXES:
                            yield Finding(
                                "TL011", module.path, node.lineno,
                                node.col_offset,
                                f"shard_map {key} names mesh axis "
                                f"{axis!r} — not a canonical topology "
                                f"axis {_CANONICAL_AXES}; an unmatched "
                                f"axis silently replicates (GSPMD treats "
                                f"it as size 1)")
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call)
                and _callee_leaf(node.func) in _AXIS_COLLECTIVES):
            continue
        axis_args = [a for a in node.args[:2]
                     if isinstance(a, ast.Constant)
                     and isinstance(a.value, str)]
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis") and \
                    isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str):
                axis_args.append(kw.value)
        for arg in axis_args:
            if arg.value not in _CANONICAL_AXES:
                yield Finding(
                    "TL011", module.path, arg.lineno, arg.col_offset,
                    f"collective {_callee_leaf(node.func)} over literal "
                    f"axis {arg.value!r} — not a canonical topology axis "
                    f"{_CANONICAL_AXES}; the topology helpers "
                    f"(parallel/topology.py) are the one source of axis "
                    f"names")
