"""Tests for indexed_dataset, DataAnalyzer map/reduce, and multinode
runners (analogs of reference tests/unit/{runtime/test_data,launcher})."""

import argparse
import sys

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder, make_dataset)
from deepspeed_tpu.runtime.data_pipeline.data_sampler import DataAnalyzer
from deepspeed_tpu.launcher.multinode_runner import (
    MVAPICHRunner, OpenMPIRunner, PDSHRunner, SlurmRunner, build_runner)


def test_indexed_dataset_roundtrip(tmp_path):
    prefix = str(tmp_path / "corpus")
    b = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    samples = [np.arange(n, dtype=np.int32) for n in (5, 1, 9, 3)]
    for s in samples:
        b.add_item(s)
    b.finalize()
    ds = MMapIndexedDataset(prefix)
    assert len(ds) == 4
    for i, s in enumerate(samples):
        np.testing.assert_array_equal(ds[i], s)
    # partial reads (curriculum-seqlen hook)
    np.testing.assert_array_equal(ds.get(2, offset=2, length=3), [2, 3, 4])
    # slice protocol
    assert len(ds[1:3]) == 2
    assert make_dataset(prefix).dtype == np.int32


def test_indexed_dataset_merge(tmp_path):
    p1, p2 = str(tmp_path / "a"), str(tmp_path / "b")
    for p, vals in ((p1, [1, 2]), (p2, [3])):
        b = MMapIndexedDatasetBuilder(p, dtype=np.int64)
        for v in vals:
            b.add_item(np.full(v, v, np.int64))
        b.finalize()
    merged = MMapIndexedDatasetBuilder(str(tmp_path / "m"), dtype=np.int64)
    merged.merge_file_(p1)
    merged.merge_file_(p2)
    merged.finalize()
    ds = MMapIndexedDataset(str(tmp_path / "m"))
    assert len(ds) == 3
    np.testing.assert_array_equal(ds[2], [3, 3, 3])


def test_data_analyzer_map_reduce(tmp_path):
    data = [np.arange(n) for n in (3, 7, 2, 9, 5, 1)]
    an = DataAnalyzer(data, metric_names=["seqlen"],
                      metric_functions=[len], save_path=str(tmp_path),
                      num_workers=3)
    vals = an.run()
    np.testing.assert_array_equal(vals, [3, 7, 2, 9, 5, 1])
    s2m, m2s = DataAnalyzer.load_metric(str(tmp_path), "seqlen")
    np.testing.assert_array_equal(s2m, [3, 7, 2, 9, 5, 1])
    np.testing.assert_array_equal(m2s["9"], [3])


def _args(**kw):
    ns = argparse.Namespace(user_script="train.py", user_args=["--x", "1"],
                            hostfile="hf", comment="")
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def test_multinode_runner_cmds():
    resources = {"host1": 4, "host2": 4}
    pdsh = PDSHRunner(_args())
    pdsh.add_export("DSTPU_COORDINATOR_ADDRESS", "host1:29500")
    cmd = pdsh.get_cmd({}, resources)
    assert cmd[0] == "pdsh" and "host1,host2" in cmd
    joined = cmd[-1]
    assert "DSTPU_COORDINATOR_ADDRESS=host1:29500" in joined
    assert "DSTPU_PROCESS_ID=%n" in joined and "train.py --x 1" in joined

    mpi = OpenMPIRunner(_args())
    mpi.add_export("A", "b")
    cmd = mpi.get_cmd({}, resources)
    assert cmd[:3] == ["mpirun", "-n", "2"] and "-x" in cmd and "A=b" in cmd
    # filtered hosts (not the raw hostfile) + rank-var → process-id wrapper
    assert "host1,host2" in cmd
    assert "train.py --x 1" in cmd[-1]
    assert "DSTPU_PROCESS_ID=${OMPI_COMM_WORLD_RANK}" in cmd[-1]

    slurm = SlurmRunner(_args())
    slurm.add_export("E", "f")
    cmd = slurm.get_cmd({}, resources)
    assert cmd[0] == "srun" and "--export=ALL,E=f" in cmd

    mv = build_runner("mvapich", _args())
    assert isinstance(mv, MVAPICHRunner)
    mv.add_export("G", "h")
    cmd = mv.get_cmd({}, resources)
    assert "-genv" in cmd and "-ppn" in cmd


def test_runner_main_dispatches_multinode(tmp_path, monkeypatch):
    """deepspeed CLI with --launcher slurm must hand off to the
    MultiNodeRunner-built command with the coordinator env exported."""
    from deepspeed_tpu.launcher import runner as runner_mod
    hf = tmp_path / "hostfile"
    hf.write_text("host1 slots=4\nhost2 slots=4\n")
    captured = {}

    class FakeResult:
        returncode = 0

    def fake_run(cmd, env=None):
        captured["cmd"] = cmd
        return FakeResult()

    monkeypatch.setattr(runner_mod.subprocess, "run", fake_run)
    with pytest.raises(SystemExit) as e:
        runner_mod.main(["-H", str(hf), "--launcher", "slurm",
                         "train.py", "--lr", "1"])
    assert e.value.code == 0
    cmd = captured["cmd"]
    assert cmd[0] == "srun" and "-N" in cmd and "2" in cmd
    assert any("DSTPU_COORDINATOR_ADDRESS=host1:" in c for c in cmd)
    assert "train.py" in cmd[-1]
    assert "DSTPU_PROCESS_ID=${SLURM_PROCID}" in cmd[-1]


def test_build_runner_unknown():
    with pytest.raises(ValueError):
        build_runner("bogus", _args())
