"""Autotuning helpers (reference ``deepspeed/autotuning/utils.py``)."""

import copy

import numpy as np


def memory_to_string(n, precision=2):
    for unit, div in (("TB", 1 << 40), ("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
        if n >= div:
            return f"{n / div:.{precision}f}{unit}"
    return f"{int(n)}B"


def number_to_string(n):
    for unit, div in (("T", 1e12), ("B", 1e9), ("M", 1e6), ("K", 1e3)):
        if n >= div:
            return f"{n / div:.2f}{unit}"
    return str(int(n))


def dict_deep_update(base, overrides):
    """Recursive dict merge returning a new dict (experiment-config builder)."""
    out = copy.deepcopy(base)
    for k, v in overrides.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = dict_deep_update(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out


def resize_batch(sample_batch, micro_batch_size):
    """Build a micro-batch of the requested size by tiling a sample batch's
    leading dimension (the autotuner's synthetic-data generator)."""
    import jax

    def rsz(x):
        x = np.asarray(x)
        return np.resize(x, (micro_batch_size,) + x.shape[1:])

    return jax.tree.map(rsz, sample_batch)


def powers_of_two(lo, hi):
    out, v = [], 1
    while v <= hi:
        if v >= lo:
            out.append(v)
        v *= 2
    return out
