"""DeepSpeed-schema JSON config for the TPU engine.

Analog of reference ``runtime/config.py:674`` (``DeepSpeedConfig``): one
JSON/dict drives every subsystem.  Key names match the reference schema
(``docs/_pages/config-json.md``) so existing DeepSpeed configs work unchanged;
TPU-only knobs (mesh sizes, remat policy) are additive blocks.

The batch-size triple (``train_batch_size = micro_batch * grad_accum *
data-parallel world``) is auto-completed and validated exactly like the
reference (``runtime/config.py`` _batch_assertion / _set_batch_related_parameters).
"""

import json
import os
from typing import Any, Dict, List, Optional, Union

from pydantic import Field

from deepspeed_tpu.runtime.compile_cache import CompileCacheConfig
from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.runtime.fault.config import FaultConfig
from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.utils.logging import logger


# --------------------------------------------------------------------- #
# Subsystem config models
# --------------------------------------------------------------------- #
class FP16Config(DeepSpeedConfigModel):
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0          # 0 → dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False
    # Memory-lean deviation (off by default): keep the persistent master
    # params in bf16 instead of fp32, saving 4 bytes/param of HBM.  The
    # optimizer still does its arithmetic in fp32.  Combine with the
    # optimizer's ``state_dtype: bfloat16`` to fit models whose fp32
    # master+moments (12 bytes/param) exceed a single chip's HBM.
    master_weights_in_bf16: bool = False


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    device: str = "none"             # none | cpu | nvme
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False


class ZeroConfig(DeepSpeedConfigModel):
    """Reference ``runtime/zero/config.py:266`` — same keys.  On TPU, stages
    are realized as GSPMD sharding specs (see runtime/zero/partition.py)."""
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    # hierarchical secondary partition (later reference versions' ZeRO++):
    # on TPU the hierarchical layout IS the mesh — use mics_shard_size
    zero_hpz_partition_size: int = 1
    mics_shard_size: int = -1        # MiCS: shard group size (reference mics.py)
    mics_hierarchical_params_gather: bool = False
    round_robin_gradients: bool = False
    ignore_unused_parameters: bool = True
    param_persistence_threshold: int = 100_000
    # single-chip memory lever (TPU-native analog of the reference's
    # bucketed gradient handling): compute each micro-step's backward in
    # N passes, each materializing gradients for only ~1/N of the
    # parameters (the other leaves enter as constants), so grad
    # temporaries never hold the full tree next to params + accumulator.
    # Costs (N-1) extra backward sweeps of FLOPs — the right trade when
    # the step is host-link- or memory-bound (2.7B on one 16 GB chip).
    grad_partition_groups: int = 1


class OptimizerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """Reference ``runtime/activation_checkpointing/checkpointing.py:789``
    configure() keys.  On TPU these select a ``jax.checkpoint`` policy."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: named jax.ad_checkpoint policy
    policy: str = "nothing_saveable"  # or dots_saveable / dots_with_no_batch_dims_saveable / everything_saveable


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class NebulaConfig(DeepSpeedConfigModel):
    """Reference ``deepspeed/nebula/config.py`` block: async tiered
    checkpoint save.  On TPU 'nebula' selects the async Orbax engine."""
    enabled: bool = False
    persistent_storage_path: Optional[str] = None
    persistent_time_interval: int = 100
    num_of_version_in_retention: int = 2
    enable_nebula_load: bool = True


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(default_factory=list)


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class MonitorConfig(DeepSpeedConfigModel):
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)

    @property
    def enabled(self):
        return self.tensorboard.enabled or self.wandb.enabled or self.csv_monitor.enabled


class TensorParallelConfig(DeepSpeedConfigModel):
    """TPU-native: tp mesh-axis size + sharding rules (reference keeps TP in
    an external mpu for training and AutoTP for inference)."""
    tp_size: int = 1
    autotp: bool = True               # infer sharding rules from param names


class PipelineConfig(DeepSpeedConfigModel):
    stages: int = 1
    micro_batches: Optional[int] = None
    partition_method: str = "parameters"
    activation_checkpoint_interval: int = 0
    # 1F1B-class memory bound (reference TrainSchedule, schedule.py:189,
    # bounds in-flight microbatches to ~stages): differentiate chunks of
    # this many microbatches at a time, so at most this many stage inputs
    # are ever stashed.  0 = unbounded fill-drain (lowest bubble).
    max_in_flight_microbatches: int = 0
    # "fill_drain" (default; GPipe-order, bubble (P-1)/(M+P-1), O(M) stash)
    # or "1f1b": interleaved one-forward-one-backward ticks with an O(P)
    # input ring (reference TrainSchedule's memory bound) at bubble
    # 2(P-1)/(M+2(P-1)) — see parallel/pipeline.py for the SPMD tick math.
    schedule: str = "fill_drain"


class SequenceParallelConfig(DeepSpeedConfigModel):
    """TPU-native superset: the reference v0.9.3 has no sequence parallelism
    (SURVEY §2.3) — ring attention over an ``sp`` mesh axis is idiomatic here."""
    sp_size: int = 1
    mode: str = "ring"                # ring | allgather


class MoEConfig(DeepSpeedConfigModel):
    enabled: bool = False
    ep_size: int = 1
    num_experts: int = 1
    top_k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_residual: bool = False


class AIOConfig(DeepSpeedConfigModel):
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True


class CompressionConfig(DeepSpeedConfigModel):
    weight_quantization: Dict[str, Any] = Field(default_factory=dict)
    activation_quantization: Dict[str, Any] = Field(default_factory=dict)
    sparse_pruning: Dict[str, Any] = Field(default_factory=dict)
    row_pruning: Dict[str, Any] = Field(default_factory=dict)
    head_pruning: Dict[str, Any] = Field(default_factory=dict)
    channel_pruning: Dict[str, Any] = Field(default_factory=dict)
    layer_reduction: Dict[str, Any] = Field(default_factory=dict)


class CurriculumLegacyConfig(DeepSpeedConfigModel):
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = Field(default_factory=dict)


class DataEfficiencyConfig(DeepSpeedConfigModel):
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = Field(default_factory=dict)
    data_routing: Dict[str, Any] = Field(default_factory=dict)


class AutotuningConfig(DeepSpeedConfigModel):
    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = False
    metric: str = "throughput"
    start_profile_step: int = 3
    end_profile_step: int = 5
    num_tuning_micro_batch_sizes: int = 3
    tuner_type: str = "gridsearch"
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = 1
    # ResourceManager slots (reference scheduler.py:33): >1 parallelizes
    # experiment dispatch — safe for compile-precheck / simulated / multi-
    # host run_fns; keep 1 for on-chip measurement runs (HBM contention)
    num_workers: int = 1
    exp_timeout: Optional[float] = None


# --------------------------------------------------------------------- #
class DeepSpeedConfig:
    """Parse + validate the full config dict (reference
    ``runtime/config.py:674``)."""

    def __init__(self, config: Union[str, dict], mesh_world_size: Optional[int] = None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise FileNotFoundError(f"DeepSpeed config path does not exist: {config}")
            with open(config) as f:
                self._param_dict = json.load(f)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        else:
            raise ValueError(f"config must be a dict or path, got {type(config)}")

        pd = self._param_dict
        self.fp16 = FP16Config(**pd.get(C.FP16, {}))
        self.bf16 = BF16Config(**pd.get(C.BF16, pd.get("bfloat16", {})))
        self.zero_config = ZeroConfig(**pd.get(C.ZERO_OPTIMIZATION, {}))
        if self.zero_config.zero_hpz_partition_size not in (0, 1):
            # don't silently ignore a memory-affecting knob: on TPU the
            # hierarchical secondary partition is expressed as a mesh
            # layout, not a gather-time cache
            raise ValueError(
                "zero_hpz_partition_size is not supported on TPU — use "
                "zero_optimization.mics_shard_size (hierarchical sharding "
                "as mdp×edp mesh axes) instead")
        self.optimizer = OptimizerConfig(**pd.get(C.OPTIMIZER, {})) if C.OPTIMIZER in pd else None
        self.scheduler = SchedulerConfig(**pd.get(C.SCHEDULER, {})) if C.SCHEDULER in pd else None
        self.activation_checkpointing = ActivationCheckpointingConfig(
            **pd.get(C.ACTIVATION_CHECKPOINTING, {}))
        self.flops_profiler = FlopsProfilerConfig(**pd.get(C.FLOPS_PROFILER, {}))
        self.comms_config = CommsLoggerConfig(**pd.get(C.COMMS_LOGGER, {}))
        self.monitor_config = MonitorConfig(
            tensorboard=TensorBoardConfig(**pd.get(C.MONITOR_TENSORBOARD, {})),
            wandb=WandbConfig(**pd.get(C.MONITOR_WANDB, {})),
            csv_monitor=CSVConfig(**pd.get(C.MONITOR_CSV, {})),
        )
        self.tensor_parallel = TensorParallelConfig(**pd.get(C.TENSOR_PARALLEL, {}))
        self.pipeline = PipelineConfig(**pd.get(C.PIPELINE_PARALLEL, {})) \
            if isinstance(pd.get(C.PIPELINE_PARALLEL, {}), dict) else PipelineConfig()
        self.sequence_parallel = SequenceParallelConfig(**pd.get(C.SEQUENCE_PARALLEL, {}))
        self.moe = MoEConfig(**pd.get("moe", {}))
        self.aio_config = AIOConfig(**pd.get(C.AIO, {}))
        self.elasticity = ElasticityConfig(**pd.get(C.ELASTICITY, {}))
        self.compression_config = CompressionConfig(**pd.get(C.COMPRESSION_TRAINING, {}))
        self.curriculum_learning_legacy = CurriculumLegacyConfig(
            **pd.get(C.CURRICULUM_LEARNING_LEGACY, {}))
        self.data_efficiency = DataEfficiencyConfig(**pd.get(C.DATA_EFFICIENCY, {}))
        self.autotuning_config = AutotuningConfig(**pd.get(C.AUTOTUNING, {}))
        self.nebula_config = NebulaConfig(**pd.get("nebula", {}))
        self.compile_cache = CompileCacheConfig(**pd.get("compile_cache", {}))
        self.fault = FaultConfig(**pd.get("fault", {}))

        self.gradient_clipping = pd.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = pd.get(C.PRESCALE_GRADIENTS, False)
        self.gradient_predivide_factor = pd.get(C.GRADIENT_PREDIVIDE_FACTOR, 1.0)
        self.sparse_gradients_enabled = pd.get(C.SPARSE_GRADIENTS, False)
        self.steps_per_print = pd.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.wall_clock_breakdown = pd.get(C.WALL_CLOCK_BREAKDOWN, False)
        self.dump_state = pd.get(C.DUMP_STATE, False)
        self.zero_allow_untested_optimizer = pd.get("zero_allow_untested_optimizer", False)
        self.seed = pd.get("seed", 42)
        self.gradient_accumulation_dtype = pd.get("data_types", {}).get(
            "grad_accum_dtype", None)
        self.communication_data_type = pd.get("communication_data_type", None)

        # Batch triple resolution
        self.train_batch_size = pd.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = pd.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = pd.get(C.GRADIENT_ACCUMULATION_STEPS)
        self._mesh_world_size = mesh_world_size
        self._configure_train_batch_size()

    # ------------------------------------------------------------------ #
    @property
    def zero_enabled(self):
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self):
        return self.zero_config.stage

    def _configure_train_batch_size(self):
        """Complete/validate the triple against the DP world size
        (reference ``runtime/config.py`` _set_batch_related_parameters)."""
        dp_world = self._mesh_world_size or 1
        tbs, mbs, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                         self.gradient_accumulation_steps)
        if tbs is not None and mbs is not None and gas is not None:
            if tbs != mbs * gas * dp_world:
                raise ValueError(
                    f"train_batch_size ({tbs}) != micro_batch ({mbs}) * "
                    f"grad_accum ({gas}) * dp_world ({dp_world})")
        elif tbs is not None and mbs is not None:
            gas = tbs // (mbs * dp_world)
            if gas * mbs * dp_world != tbs:
                raise ValueError(
                    f"train_batch_size {tbs} not divisible by micro_batch*world "
                    f"{mbs * dp_world}")
        elif tbs is not None and gas is not None:
            mbs = tbs // (gas * dp_world)
            if mbs * gas * dp_world != tbs:
                raise ValueError("batch triple inconsistent")
        elif mbs is not None:
            gas = gas or 1
            tbs = mbs * gas * dp_world
        elif tbs is not None:
            mbs = tbs // dp_world
            gas = 1
            if mbs * dp_world != tbs:
                raise ValueError(f"train_batch_size {tbs} not divisible by dp world {dp_world}")
        else:
            mbs, gas = 1, 1
            tbs = dp_world
            logger.warning("no batch config given; defaulting to micro_batch=1, grad_accum=1")
        self.train_batch_size = tbs
        self.train_micro_batch_size_per_gpu = mbs
        self.gradient_accumulation_steps = gas

    def print_config(self, name="DeepSpeedConfig"):
        logger.info(f"{name}:\n{json.dumps(self._param_dict, indent=2, default=str)}")
