"""Optimizer factory + the simpler optimizers.

Analog of reference ``runtime/engine.py:1193 _configure_basic_optimizer``:
maps the config ``optimizer.type`` string to an optimizer instance.  All
optimizers share the functional protocol ``init(params)``/
``update(grads, state, params, lr, step)`` and run fused inside the jitted
train step.
"""

from typing import NamedTuple, Any

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.fused_adam import FusedAdam, FusedAdamW
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb
from deepspeed_tpu.runtime import constants as C


class SGDState(NamedTuple):
    momentum: Any


class SGD:

    def __init__(self, lr=1e-3, momentum=0.0, weight_decay=0.0, nesterov=False):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov

    def init(self, params):
        if self.momentum == 0.0:
            return SGDState(momentum=None)
        return SGDState(momentum=jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def update(self, grads, state, params, lr=None, step=1):
        lr = self.lr if lr is None else lr
        wd, mu = self.weight_decay, self.momentum

        if mu == 0.0:
            def leaf(p, g):
                g32 = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
                return (p.astype(jnp.float32) - lr * g32).astype(p.dtype)
            return jax.tree.map(leaf, params, grads), state

        def leaf(p, g, b):
            g32 = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
            b = mu * b + g32
            d = g32 + mu * b if self.nesterov else b
            return (p.astype(jnp.float32) - lr * d).astype(p.dtype), b

        out = jax.tree.map(leaf, params, grads, state.momentum)
        is_t = lambda t: isinstance(t, tuple)
        return (jax.tree.map(lambda t: t[0], out, is_leaf=is_t),
                SGDState(jax.tree.map(lambda t: t[1], out, is_leaf=is_t)))


class AdagradState(NamedTuple):
    accum: Any


class Adagrad:
    """TPU analog of reference ``csrc/adagrad/cpu_adagrad.cpp`` (vectorized
    host Adagrad) — as a fused device update."""

    def __init__(self, lr=1e-2, eps=1e-10, weight_decay=0.0, initial_accumulator_value=0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.init_acc = initial_accumulator_value

    def init(self, params):
        return AdagradState(accum=jax.tree.map(
            lambda p: jnp.full(p.shape, self.init_acc, jnp.float32), params))

    def update(self, grads, state, params, lr=None, step=1):
        lr = self.lr if lr is None else lr

        def leaf(p, g, acc):
            g32 = g.astype(jnp.float32) + self.weight_decay * p.astype(jnp.float32)
            acc = acc + g32 * g32
            return (p.astype(jnp.float32) - lr * g32 / (jnp.sqrt(acc) + self.eps)).astype(p.dtype), acc

        out = jax.tree.map(leaf, params, grads, state.accum)
        is_t = lambda t: isinstance(t, tuple)
        return (jax.tree.map(lambda t: t[0], out, is_leaf=is_t),
                AdagradState(jax.tree.map(lambda t: t[1], out, is_leaf=is_t)))


class LionState(NamedTuple):
    momentum: Any


class Lion:

    def __init__(self, lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.weight_decay = weight_decay

    def init(self, params):
        return LionState(momentum=jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def update(self, grads, state, params, lr=None, step=1):
        lr = self.lr if lr is None else lr
        b1, b2, wd = self.beta1, self.beta2, self.weight_decay

        def leaf(p, g, m):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            upd = jnp.sign(b1 * m + (1.0 - b1) * g32) + wd * p32
            m = b2 * m + (1.0 - b2) * g32
            return (p32 - lr * upd).astype(p.dtype), m

        out = jax.tree.map(leaf, params, grads, state.momentum)
        is_t = lambda t: isinstance(t, tuple)
        return (jax.tree.map(lambda t: t[0], out, is_leaf=is_t),
                LionState(jax.tree.map(lambda t: t[1], out, is_leaf=is_t)))


def build_optimizer(opt_config):
    """Map config ``optimizer`` block to an instance (reference
    ``engine.py:1193``)."""
    if opt_config is None or opt_config.type is None:
        return FusedAdamW()
    name = opt_config.type.lower()
    params = dict(opt_config.params)
    params.pop("torch_adam", None)
    params.pop("adam_w_mode", None) if name == C.ADAMW_OPTIMIZER else None
    if name in (C.ADAM_OPTIMIZER, C.FUSED_ADAM_OPTIMIZER, C.CPU_ADAM_OPTIMIZER):
        # reference ADAM_W_MODE_DEFAULT=True (engine.py:1205-1208): "Adam"
        # means decoupled weight decay unless adam_w_mode=false is set.
        adam_w = params.pop("adam_w_mode", True)
        return FusedAdam(adam_w_mode=adam_w, **params)
    if name == C.ADAMW_OPTIMIZER:
        return FusedAdamW(**params)
    if name == C.LAMB_OPTIMIZER:
        return FusedLamb(**params)
    if name == C.ONEBIT_LAMB_OPTIMIZER:
        from deepspeed_tpu.ops.lamb.onebit_lamb import OnebitLamb
        return OnebitLamb(**params)
    if name == C.ONEBIT_ADAM_OPTIMIZER:
        from deepspeed_tpu.ops.adam.onebit_adam import OnebitAdam
        return OnebitAdam(**params)
    if name == C.ZERO_ONE_ADAM_OPTIMIZER:
        from deepspeed_tpu.ops.adam.onebit_adam import ZeroOneAdam
        return ZeroOneAdam(**params)
    if name == C.SGD_OPTIMIZER:
        return SGD(**params)
    if name == C.ADAGRAD_OPTIMIZER:
        return Adagrad(**params)
    if name == C.LION_OPTIMIZER:
        return Lion(**params)
    raise ValueError(f"unknown optimizer type: {opt_config.type}")
