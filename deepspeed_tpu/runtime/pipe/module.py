"""Pipeline module front-end.

Parity with reference ``runtime/pipe/module.py`` (``PipelineModule:85``,
``LayerSpec:29``, ``TiedLayerSpec:76``): a model expressed as a sequence of
layers that the pipeline engine partitions across the ``pp`` mesh axis.

Each layer is a (init_fn, apply_fn) pair — typically a flax Module built from
a ``LayerSpec`` — and partitioning follows ``partition_method``:
``uniform`` (equal layer counts), ``parameters`` (equal parameter counts), or
``type:regex`` (layer-class-name matches count as cut points), same strings
as reference ``module.py:353``.
"""

import re

import numpy as np

import jax


class LayerSpec:
    """Deferred layer construction (reference ``pipe/module.py:29``) — the
    layer class is instantiated lazily so building a 100-layer model doesn't
    materialize anything before partitioning."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not callable(typename):
            raise RuntimeError("LayerSpec requires a callable/class typename")

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    @property
    def name(self):
        return getattr(self.typename, "__name__", str(self.typename))


class TiedLayerSpec(LayerSpec):
    """Weight-tied layer (reference ``pipe/module.py:76``): layers sharing a
    ``key`` share parameters (e.g. embedding / unembedding).  On TPU tying is
    realized by routing both call sites at the same param subtree — no
    cross-stage grad allreduce is needed because GSPMD owns the single copy."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="embedding", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


class PipelineModule:
    """Sequence-of-layers model for pipeline parallelism
    (reference ``pipe/module.py:85``)."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, partition_method="parameters",
                 activation_checkpoint_interval=0, seed_layers=False,
                 base_seed=1234):
        self.layer_specs = [l if isinstance(l, LayerSpec) else LayerSpec(l)
                            for l in layers]
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self._built = None

    def build_layers(self):
        if self._built is None:
            self._built = [spec.build() for spec in self.layer_specs]
        return self._built

    def num_layers(self):
        return len(self.layer_specs)

    # ------------------------------------------------------------------ #
    def partition_layers(self, num_stages, abstract_params_per_layer=None):
        """Return stage boundaries: list of (start, stop) per stage.

        ``parameters``: balance per-layer parameter counts
        (reference ``module.py:353`` partition_balanced); ``uniform``: equal
        layer counts; ``type:regex``: balance layers whose class name matches.
        """
        n = self.num_layers()
        method = self.partition_method.lower()
        if method == "uniform":
            weights = [1] * n
        elif method == "parameters":
            if abstract_params_per_layer is not None:
                weights = [int(sum(np.prod(l.shape) for l in jax.tree.leaves(p)))
                           for p in abstract_params_per_layer]
            else:
                weights = [1] * n
        elif method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = [1 if re.search(pattern, spec.name, re.IGNORECASE) else 0
                       for spec in self.layer_specs]
        else:
            raise NotImplementedError(f"partition_method {self.partition_method}")
        return partition_balanced(weights, num_stages)


def partition_balanced(weights, num_parts):
    """Prefix-sum balanced partition (reference
    ``deepspeed/runtime/utils.py partition_balanced``)."""
    prefix = np.concatenate([[0], np.cumsum(weights)])
    total = prefix[-1]
    bounds = [0]
    for p in range(1, num_parts):
        target = total * p / num_parts
        idx = int(np.searchsorted(prefix, target))
        idx = max(bounds[-1] + 1, min(idx, len(weights) - (num_parts - p)))
        bounds.append(idx)
    bounds.append(len(weights))
    return [(bounds[i], bounds[i + 1]) for i in range(num_parts)]
