"""Engine end-to-end tests — the analog of reference
``tests/unit/runtime/test_ds_initialize.py`` + ``zero/test_zero.py`` basics:
initialize, train a few steps at every ZeRO stage, verify loss decreases and
state shards land where the plan says."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from simple_model import SimpleModel, random_batch


def base_config(**over):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 0},
    }
    cfg.update(over)
    return cfg


def train_steps(engine, steps=5, seed=0):
    # one fixed batch: training must memorize it, so the loss decrease is
    # deterministic (fresh noise every step makes the assert a coin flip)
    losses = []
    for i in range(steps):
        batch = random_batch(batch_size=16, seed=seed)
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_train(stage):
    engine, optimizer, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config=base_config(zero_optimization={"stage": stage}))
    losses = train_steps(engine, steps=8)
    assert losses[-1] < losses[0], f"stage {stage}: loss did not decrease: {losses}"


def test_zero3_param_sharding():
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config=base_config(zero_optimization={"stage": 3}))
    engine(random_batch())
    # at least one param leaf must actually be sharded over the dp axes
    shardings = [l.sharding for l in jax.tree.leaves(engine.params)]
    assert any(not s.is_fully_replicated for s in shardings), \
        "ZeRO-3 produced no sharded parameters"


def test_zero1_opt_state_sharded_params_replicated():
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config=base_config(zero_optimization={"stage": 1}))
    engine(random_batch())
    for leaf in jax.tree.leaves(engine.params):
        assert leaf.sharding.is_fully_replicated, "ZeRO-1 must not shard params"
    opt_shardings = [l.sharding for l in jax.tree.leaves(engine._opt_state)]
    assert any(not s.is_fully_replicated for s in opt_shardings), \
        "ZeRO-1 must shard optimizer state"


def test_gradient_accumulation():
    cfg = base_config(gradient_accumulation_steps=4)
    engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(), config=cfg)
    assert engine.gradient_accumulation_steps() == 4
    for i in range(4):
        loss = engine(random_batch(seed=i))
        engine.backward(loss)
        engine.step()
        if i < 3:
            # no optimizer step until the 4th micro-batch
            assert engine.global_steps == 0
            assert engine._grad_acc is not None
    assert engine.global_steps == 1
    assert engine._grad_acc is None


def test_train_batch_fused():
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(),
        config=base_config(gradient_accumulation_steps=2,
                           zero_optimization={"stage": 2}))
    mbs = [random_batch(seed=i) for i in range(2)]
    batch = jax.tree.map(lambda *xs: np.stack(xs), *mbs)
    l0 = float(jax.device_get(engine.train_batch(batch=batch)))
    l1 = float(jax.device_get(engine.train_batch(batch=batch)))
    assert l1 < l0
    assert engine.global_steps == 2


def test_bf16_training():
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(),
        config=base_config(bf16={"enabled": True}, zero_optimization={"stage": 2}))
    losses = train_steps(engine, steps=6)
    assert losses[-1] < losses[0]
    assert engine.compute_dtype == jnp.bfloat16


def test_fp16_dynamic_loss_scale():
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(),
        config=base_config(fp16={"enabled": True, "initial_scale_power": 8}))
    losses = train_steps(engine, steps=6)
    assert losses[-1] < losses[0]
    scale = float(jax.device_get(engine._scaler_state.scale))
    assert scale > 0


def test_gradient_clipping_applied():
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(),
        config=base_config(gradient_clipping=1e-6))
    train_steps(engine, steps=2)
    gnorm = float(jax.device_get(engine.get_global_grad_norm()))
    assert gnorm >= 0


def test_lr_scheduler_warmup():
    cfg = base_config(scheduler={"type": "WarmupLR",
                                 "params": {"warmup_min_lr": 0.0,
                                            "warmup_max_lr": 1e-2,
                                            "warmup_num_steps": 10,
                                            "warmup_type": "linear"}})
    engine, _, _, sched = deepspeed_tpu.initialize(model=SimpleModel(), config=cfg)
    lrs = []
    for i in range(5):
        loss = engine(random_batch(seed=i))
        engine.backward(loss)
        engine.step()
        lrs.append(engine.get_lr()[0])
    assert lrs == sorted(lrs), f"warmup lr must be non-decreasing: {lrs}"
    assert lrs[-1] > 0


def test_eval_mode_forward():
    engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(), config=base_config())
    engine(random_batch())  # init params in train mode
    engine.eval()
    out = engine(random_batch())
    assert np.isfinite(float(jax.device_get(out)))
    engine.train()


def test_checkpoint_save_load(tmp_path):
    cfg = base_config(zero_optimization={"stage": 2})
    engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(), config=cfg)
    train_steps(engine, steps=3)
    ref_loss = float(jax.device_get(engine(random_batch(seed=99))))
    engine.save_checkpoint(str(tmp_path), tag="tag1")

    engine2, *_ = deepspeed_tpu.initialize(model=SimpleModel(), config=cfg)
    engine2(random_batch())  # materialize params
    engine2.load_checkpoint(str(tmp_path), tag="tag1")
    assert engine2.global_steps == engine.global_steps
    loss2 = float(jax.device_get(engine2(random_batch(seed=99))))
    assert abs(loss2 - ref_loss) < 1e-4


def test_memory_lean_optimizer_states(tmp_path):
    """The documented memory-lean deviation (bf16 master weights + bf16
    Adam moments, fp32 arithmetic) trains and stores what it claims —
    the mode bench.py uses for the OPT-1.3B north star on one 16 GB chip."""
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config=base_config(
            optimizer={"type": "AdamW",
                       "params": {"lr": 1e-2, "state_dtype": "bfloat16"}},
            bf16={"enabled": True, "master_weights_in_bf16": True},
            zero_optimization={"stage": 1}))
    losses = train_steps(engine, steps=10)
    assert losses[-1] < losses[0], f"lean mode: no learning: {losses}"
    for leaf in jax.tree.leaves(engine.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16, leaf.dtype
    for leaf in jax.tree.leaves(engine._opt_state):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16, leaf.dtype
    # ckpt roundtrip preserves the lean dtypes
    engine.save_checkpoint(str(tmp_path))
    engine.load_checkpoint(str(tmp_path))
    assert all(l.dtype == jnp.bfloat16
               for l in jax.tree.leaves(engine.params)
               if jnp.issubdtype(l.dtype, jnp.floating))


def test_lean_state_dtype_default_is_reference_exact():
    """Without the lean flags, masters and moments stay fp32."""
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config=base_config(bf16={"enabled": True}))
    train_steps(engine, steps=1)
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(engine.params)
               if jnp.issubdtype(l.dtype, jnp.floating))
    assert all(l.dtype == jnp.float32
               for l in jax.tree.leaves(engine._opt_state)
               if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating))


def test_batch_config_validation():
    with pytest.raises(ValueError):
        deepspeed_tpu.DeepSpeedConfig(
            {"train_batch_size": 7, "train_micro_batch_size_per_gpu": 2,
             "gradient_accumulation_steps": 2}, mesh_world_size=8)


def test_hpz_partition_size_redirects_to_mics():
    """zero_hpz_partition_size is a memory-affecting knob this framework
    expresses differently (MiCS mesh axes) — it must fail loudly, not be
    silently ignored."""
    with pytest.raises(ValueError, match="mics_shard_size"):
        deepspeed_tpu.DeepSpeedConfig(
            {"train_micro_batch_size_per_gpu": 2,
             "zero_optimization": {"stage": 3,
                                   "zero_hpz_partition_size": 4}},
            mesh_world_size=8)


def test_fresh_engine_load_module_only(tmp_path):
    """load_checkpoint(..., load_module_only=True) into a FRESH engine:
    weights come from the checkpoint, optimizer state is freshly built
    (reference load_module_only semantics), and training proceeds —
    exercises the metadata-driven restore path building the plan before
    the module-only branch."""
    from deepspeed_tpu.parallel.topology import reset_topology

    def fresh():
        reset_topology()
        engine, *_ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=16),
            config=base_config(zero_optimization={"stage": 2}, seed=0))
        return engine

    e1 = fresh()
    train_steps(e1, steps=3)
    e1.save_checkpoint(str(tmp_path))
    w_ref = np.asarray(jax.tree.leaves(e1.params)[0], np.float32)

    e2 = fresh()
    e2.load_checkpoint(str(tmp_path), load_module_only=True)
    w_loaded = np.asarray(jax.tree.leaves(e2.params)[0], np.float32)
    np.testing.assert_allclose(w_loaded, w_ref, rtol=1e-6)
    # fresh optimizer state: training continues from the loaded weights
    losses = train_steps(e2, steps=3, seed=7)
    assert np.isfinite(losses).all(), losses


def test_grad_partition_groups_matches_full_backward():
    """zero_optimization.grad_partition_groups: N partial backward passes
    (each materializing ~1/N of the gradient tree) must accumulate the
    SAME gradients as the one-pass path — identical loss trajectory over
    several accumulation boundaries."""
    import numpy as np
    import deepspeed_tpu
    from tests.unit.simple_model import SimpleModel

    def run(groups):
        engine, *_ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=16),
            config={"train_micro_batch_size_per_gpu": 2,
                    "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
                    "zero_optimization": {"stage": 0,
                                          "grad_partition_groups": groups},
                    "gradient_clipping": 1.0})
        rng = np.random.default_rng(0)
        losses = []
        for step in range(3):
            for micro in range(2):
                batch = {
                    "x": rng.standard_normal((2 * engine.topology.dp, 16))
                    .astype(np.float32),
                    "y": rng.integers(0, 16, (2 * engine.topology.dp,))
                    .astype(np.int32)}
                loss = engine(batch)
                engine.backward(loss)
                losses.append(float(jax.device_get(loss)))
            engine.step()
        return losses

    ref = run(1)
    got = run(3)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
