"""docs/config-json.md must cover every config block and field the parser
accepts (the reference ships a 1,655-line full schema,
``docs/_pages/config-json.md``; drift between parser and docs fails here).

The check walks the pydantic models ``DeepSpeedConfig`` instantiates plus
``DeepSpeedInferenceConfig`` and asserts each block has a doc section
naming every field."""

import os
import re

import pytest

DOC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "..", "..", "docs", "config-json.md")

# top-level JSON key -> config model
def _blocks():
    from deepspeed_tpu.runtime import config as rc
    from deepspeed_tpu.runtime.fault.config import FaultConfig
    from deepspeed_tpu.inference.config import (DeepSpeedInferenceConfig,
                                                QuantizationConfig)
    from deepspeed_tpu.inference.serving.config import ServingConfig
    return {
        "fp16": rc.FP16Config,
        "bf16": rc.BF16Config,
        "zero_optimization": rc.ZeroConfig,
        "zero_optimization.offload_optimizer":
            rc.DeepSpeedZeroOffloadOptimizerConfig,
        "zero_optimization.offload_param":
            rc.DeepSpeedZeroOffloadParamConfig,
        "optimizer": rc.OptimizerConfig,
        "scheduler": rc.SchedulerConfig,
        "activation_checkpointing": rc.ActivationCheckpointingConfig,
        "flops_profiler": rc.FlopsProfilerConfig,
        "comms_logger": rc.CommsLoggerConfig,
        "tensorboard": rc.TensorBoardConfig,
        "wandb": rc.WandbConfig,
        "csv_monitor": rc.CSVConfig,
        "tensor_parallel": rc.TensorParallelConfig,
        "pipeline": rc.PipelineConfig,
        "sequence_parallel": rc.SequenceParallelConfig,
        "moe": rc.MoEConfig,
        "aio": rc.AIOConfig,
        "elasticity": rc.ElasticityConfig,
        "compression_training": rc.CompressionConfig,
        "curriculum_learning": rc.CurriculumLegacyConfig,
        "data_efficiency": rc.DataEfficiencyConfig,
        "autotuning": rc.AutotuningConfig,
        "nebula": rc.NebulaConfig,
        "compile_cache": rc.CompileCacheConfig,
        "fault": FaultConfig,
        "init_inference": DeepSpeedInferenceConfig,
        "init_inference.quant": QuantizationConfig,
        "init_inference.fault": FaultConfig,
        "init_inference.serving": ServingConfig,
    }


def _doc_sections():
    """Split the doc into (heading, body) pairs at '##' headings."""
    with open(DOC) as f:
        text = f.read()
    parts = re.split(r"^#{2,3} +(.+)$", text, flags=re.M)
    head = parts[0]
    sections = {}
    for i in range(1, len(parts), 2):
        sections[parts[i].strip()] = parts[i + 1]
    return head, sections


def _section_for(block, sections):
    """The section whose heading mentions the block's JSON key."""
    key = block.split(".")[-1]
    for heading, body in sections.items():
        tokens = re.findall(r"[`\w.]+", heading)
        if any(key == t.strip("`") or t.strip("`").endswith("." + key)
               or key in t.strip("`").split(",")
               for t in tokens) or f"`{key}`" in heading:
            return heading, body
    # monitoring blocks share one section; inference sub-blocks are rows
    # of the init_inference table
    for heading, body in sections.items():
        if f"`{key}`" in body or key in heading.lower():
            return heading, body
    return None, None


def test_every_config_block_documented():
    _, sections = _doc_sections()
    missing = []
    for block in _blocks():
        heading, _ = _section_for(block, sections)
        if heading is None:
            missing.append(block)
    assert not missing, f"config blocks with no doc section: {missing}"


def test_every_config_field_documented():
    _, sections = _doc_sections()
    problems = []
    for block, model in _blocks().items():
        heading, body = _section_for(block, sections)
        if body is None:
            problems.append(f"{block}: no section")
            continue
        for name, field in model.model_fields.items():
            spellings = {name}
            if field.alias:
                spellings.add(field.alias)
            if not any(s in body for s in spellings):
                problems.append(f"{block}.{name} missing from section "
                                f"{heading!r}")
    assert not problems, "undocumented config fields:\n" + \
        "\n".join(problems)


def test_top_level_scalars_documented():
    """The scalar keys DeepSpeedConfig reads directly (outside any block
    model) must appear in the doc too."""
    with open(DOC) as f:
        text = f.read()
    for key in ("gradient_clipping", "prescale_gradients",
                "gradient_predivide_factor", "sparse_gradients",
                "steps_per_print", "wall_clock_breakdown", "dump_state",
                "zero_allow_untested_optimizer", "seed",
                "communication_data_type", "grad_accum_dtype",
                "train_batch_size", "train_micro_batch_size_per_gpu",
                "gradient_accumulation_steps",
                "hybrid_engine", "quantize_rollouts", "rollout_quant_bits"):
        assert key in text, f"top-level config key {key} undocumented"


def test_doc_parity_scale():
    """Guard against the docs regressing to a stub: the reference schema
    doc is 1,655 lines; ours must stay a real schema document."""
    with open(DOC) as f:
        n = len(f.read().splitlines())
    assert n >= 300, f"config-json.md shrank to {n} lines"
