"""``fault`` config block — every fault-tolerance knob in one model.

Shared by the training config (``runtime/config.py``) and the inference
config (``inference/config.py``); ``enabled: false`` (the default) keeps
exact seed behavior everywhere.  See ``docs/fault_tolerance.md`` and the
``fault`` section of ``docs/config-json.md``.
"""

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class FaultConfig(DeepSpeedConfigModel):
    # master switch: off = seed behavior (no manifest protocol, no retries,
    # no verification; the atomicity BUG fixes — temp-file 'latest' and
    # meta.pkl writes — are unconditional, they change no semantics)
    enabled: bool = False

    # ---- crash-atomic checkpoint protocol ---------------------------- #
    # verify MANIFEST.json (sizes + checksums) before trusting a tag on
    # load; a failed tag is skipped and load walks back to the newest
    # valid one
    verify_on_load: bool = True
    # per-file checksum algorithm recorded in the manifest:
    # "sha256" (cryptographic) or "crc32" (fast, bit-rot-grade)
    checksum: str = "sha256"
    # retention: keep the newest N valid tags, GC older ones and orphaned
    # <tag>.tmp dirs after every successful save; 0 = keep everything
    keep_last_n: int = 0

    # ---- transient-failure retry policy ------------------------------ #
    # bounded retries with exponential backoff + jitter for transient
    # I/O during save and executable load during inference
    max_retries: int = 3
    backoff_base_secs: float = 0.5
    backoff_max_secs: float = 30.0
    # fraction of the backoff added as deterministic jitter (decorrelates
    # herds of preempted workers re-reading the same store)
    backoff_jitter: float = 0.25

    # ---- auto-resume supervisor (run_resilient) ---------------------- #
    # give up after this many reload-and-continue recoveries; the
    # supervisor returns ("failed", steps) instead of looping forever
    max_resumes: int = 10
    # heartbeat watchdog: a step taking longer than this dumps all thread
    # stacks and (emergency_checkpoint_on_hang) saves before recovering;
    # 0 = watchdog off
    heartbeat_timeout_secs: float = 0.0
    emergency_checkpoint_on_hang: bool = True
    # steps between periodic supervisor checkpoints; 0 = only emergency /
    # final checkpoints
    save_interval: int = 0

    # ---- inference graceful degradation ------------------------------ #
    # under strict_memory, a generation program over the memory guard
    # splits the batch in half (recursively, down to batch 1) and runs
    # the halves sequentially instead of raising — documented
    # bucket-downshift fallback (docs/fault_tolerance.md)
    bucket_downshift: bool = False
