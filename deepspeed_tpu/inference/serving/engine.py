"""Continuous-batching serving engine — iteration-level scheduling over
``InferenceEngine`` (Orca, Yu et al. OSDI'22; slot/paged KV management in
the spirit of vLLM's PagedAttention, Kwon et al. SOSP'23 — here with the
TPU constraint that every program keeps FIXED shapes).

The scheduler loop per iteration (:meth:`ServingEngine.step`):

1. **Admission** — while a KV slot is free and the queue is non-empty,
   pop a request (``fcfs`` or ``shortest_first``) and stream its prompt
   through the engine's donated per-chunk prefill executable
   (``_get_chunk_fn(C, 1)`` — the same program the split-prefill
   ``generate()`` path replays) into a single-lane cache, spending at most
   ``prefill_token_budget`` prompt tokens per iteration so a long prompt
   cannot starve decoding.  A finished prefill dispatches ONE fused admit
   program (first-token sample + lane insert + in-program slot-state
   write).
2. **Decode** — ONE call of the single reusable decode-step program
   advances every live slot ``decode_block`` tokens (cache + slot state
   donated).  Rows that emit their ``eos`` (or exhaust ``max_new_tokens``)
   retire IN-PROGRAM; the host mirrors the retirement bookkeeping from the
   emitted tokens, frees their slots mid-flight, and hands the lanes to
   the admission queue — no request ever waits for a batch to finish.

**Latency-hiding (the tunneled-device lesson — each separate dispatch
costs ~0.1 s there):** the slot state lives ON DEVICE and every program
chains through it by data dependency, so the host never synchronizes
inside the dispatch path.  Token reads lag ONE event behind: the host
dispatches the next decode block first and only then materializes the
previous block's tokens, so the device (and the tunnel) stay busy while
the host does its scheduling bookkeeping.  The price is that a slot freed
in block N is re-admittable only from block N+2 — at most one block of
idle per retirement.

Because slot occupancy rides traced arguments, the whole server lifetime
compiles exactly ONE decode-step executable per (num_slots, cache_len,
block, sampling) configuration — persisted through the ``compile_cache``
block and reloaded (not recompiled) across server restarts.
"""

import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.serving.config import ServingConfig
from deepspeed_tpu.inference.serving.slots import (init_slot_state,
                                                   make_admit_fn,
                                                   make_decode_block_fn)
from deepspeed_tpu.utils.logging import log_dist, logger


@dataclass
class ServeRequest:
    """One queued/running generation request (host bookkeeping only)."""
    rid: int
    ids: np.ndarray                  # [P] int32 prompt
    max_new: int
    eos: int                         # -1 = never stop early
    submitted_it: int = 0
    tokens: list = field(default_factory=list)
    slot: Optional[int] = None
    finished_it: Optional[int] = None


class _PendingPrefill:
    """An admission in progress: the slot is reserved, the prompt streams
    chunk-by-chunk into the lane cache across scheduler iterations."""

    def __init__(self, req, slot, lane, ids_pad, n_chunks):
        self.req, self.slot, self.lane = req, slot, lane
        self.ids_pad = ids_pad           # [1, n_chunks*C] int32
        self.n_chunks = n_chunks
        self.ci = 0                      # chunks completed
        self.sel = None                  # last-real-position logits [1,1,V]


class _LanePool:
    """Reusable single-lane prefill caches.  Several admissions can be in
    flight at once (the admit op that consumes a lane is processed one
    event behind), so this is a pool, not a single workspace slot — with
    the same donated-and-dead liveness check ``KVCacheWorkspace`` does."""

    def __init__(self, module):
        self._module = module
        self._lanes = []

    def take(self, cache_len, dtype):
        while self._lanes:
            lane = self._lanes.pop()
            if not any(getattr(l, "is_deleted", lambda: False)()
                       for l in jax.tree.leaves(lane)):
                return lane
        return self._module.init_cache(1, cache_len, dtype=dtype)

    def give_back(self, lane):
        self._lanes.append(lane)

    def release(self):
        self._lanes.clear()


class ServingEngine:
    """Slot-based continuous batching over an :class:`InferenceEngine`.

    ``submit()`` enqueues a request and returns its id; ``step()`` runs one
    scheduler iteration; ``drain()`` loops until everything submitted has
    finished and returns ``{rid: np.ndarray}`` where each output follows
    the ``generate()`` contract ``[prompt..., generated...]`` of length
    ``len(prompt) + max_new_tokens`` (eos-padded past early stops — under
    greedy decoding, bitwise what ``engine.generate()`` returns for the
    same request solo)."""

    def __init__(self, engine, monitor=None, **overrides):
        assert engine.params is not None, \
            "no parameters: set_params/init_params first"
        cfg = getattr(engine._config, "serving", None) or ServingConfig()
        if overrides:
            cfg = ServingConfig(**{**cfg.model_dump(), **overrides})
        self.engine = engine
        self.module = engine.module
        self.config = cfg
        self.monitor = monitor
        self.num_slots = int(cfg.num_slots)
        if self.num_slots < 1:
            raise ValueError(f"serving.num_slots={cfg.num_slots}: need >= 1")
        # lane length: multiple of 8 (the fused decode kernel's sublane
        # alignment — same rounding as required_cache_len)
        self.cache_len = -(-int(cfg.max_cache_len) // 8) * 8
        # admission chunk: align like the engine's prefill_chunk_size
        # (multiple of 8, floor 8, cap 512 — the chunk kernel's bounds)
        self.chunk = min(512, max(8, -(-int(cfg.prefill_chunk) // 8) * 8))
        max_seq = getattr(getattr(self.module, "config", None),
                          "max_seq_len", None)
        if max_seq is not None and self.cache_len > max_seq:
            logger.warning(
                f"serving.max_cache_len={self.cache_len} exceeds the "
                f"model's max_seq_len={max_seq} — positions past it will "
                f"fault on learned position embeddings")
        if cfg.admission not in ("fcfs", "shortest_first"):
            raise ValueError(f"serving.admission={cfg.admission!r}: "
                             f"one of 'fcfs', 'shortest_first'")
        self.block = max(1, int(cfg.decode_block))

        from deepspeed_tpu.inference.engine import (KVCacheWorkspace,
                                                    build_sample_fn)
        sample_fn = build_sample_fn(bool(cfg.do_sample),
                                    float(cfg.temperature),
                                    int(cfg.top_k), float(cfg.top_p))
        sampling_key = (bool(cfg.do_sample), float(cfg.temperature),
                        int(cfg.top_k), float(cfg.top_p))
        self._decode_fn = make_decode_block_fn(
            self.module, sample_fn, engine._deq, self.block, self.cache_len)
        self._admit_fn = make_admit_fn(sample_fn)
        # stable program tags → the engine's AOT path persists/reloads
        # these executables through the compile_cache store
        engine._tags[id(self._decode_fn)] = (
            "serving_decode", self.num_slots, self.cache_len, self.block,
            sampling_key)
        engine._tags[id(self._admit_fn)] = (
            "serving_admit", self.num_slots, self.cache_len, sampling_key)
        self._chunk_fn = engine._get_chunk_fn(self.chunk, 1)

        self._cache_ws = KVCacheWorkspace(self.module)
        self._lane_pool = _LanePool(self.module)
        self._cache = None
        self._state = None               # device-resident slot state
        # host mirror of slot occupancy, updated as events are PROCESSED
        # (it lags the device by the in-flight events — by design)
        self._mirror_active = np.zeros((self.num_slots,), bool)
        self._slots = [None] * self.num_slots      # slot -> ServeRequest
        self._free = deque(range(self.num_slots))
        self._queue = deque()
        self._pending = None
        # dispatched-but-unprocessed device work, processed FIFO one
        # event behind the newest dispatch: ("decode", toks_dev) |
        # ("admit", req, slot, lane, first_dev)
        self._events = deque()
        self._rng = jax.random.key(int(cfg.seed))
        self._next_rid = 0
        self._it = 0
        # observability (docs/serving.md): scheduler counters + the
        # slot-occupancy trace the correctness test asserts EOS-mid-flight
        # retirement against
        self.stats = {"iterations": 0, "decode_calls": 0,
                      "decode_tokens": 0, "prefill_tokens": 0,
                      "completed": 0, "admitted": 0, "wall_secs": 0.0,
                      "sync_secs": 0.0}
        self.occupancy_trace = []                  # (iteration, n_active)

    # ------------------------------------------------------------------ #
    # Public API
    # ------------------------------------------------------------------ #
    def submit(self, input_ids, max_new_tokens=32, eos_token_id=-1):
        """Enqueue one prompt; returns the request id.  The request must
        fit a slot lane: ``ceil(P/chunk)*chunk <= max_cache_len`` (chunked
        prefill writes the padded tail) and ``P + max_new_tokens <=
        max_cache_len``."""
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        P = int(ids.shape[0])
        max_new = int(max_new_tokens)
        if P < 1:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new_tokens={max_new}: need >= 1")
        padded = -(-P // self.chunk) * self.chunk
        need = max(P + max_new, padded)
        if need > self.cache_len:
            raise ValueError(
                f"request needs {need} cache positions (prompt {P} + new "
                f"{max_new}, chunk-padded {padded}) but slot lanes hold "
                f"{self.cache_len} — raise serving.max_cache_len or split "
                f"the request")
        req = ServeRequest(self._next_rid, ids, max_new, int(eos_token_id),
                           submitted_it=self._it)
        self._next_rid += 1
        self._queue.append(req)
        return req.rid

    def step(self):
        """One scheduler iteration: admission prefill under the token
        budget, one decode-block dispatch, then process device results one
        event behind (latency-hiding).  Returns ``{rid: output}`` for the
        requests whose results were processed this iteration."""
        t0 = time.perf_counter()
        self._ensure_workspace()
        finished = {}
        self._admit()
        dispatched = self._dispatch_decode()
        # lag-one processing: with fresh work in flight, leave the newest
        # event unread so the device/tunnel keeps running while the host
        # does bookkeeping; once nothing new was dispatched, flush fully
        self._process_events(finished, keep=1 if dispatched else 0)
        self._emit_metrics()
        self.stats["iterations"] += 1
        self.stats["wall_secs"] += time.perf_counter() - t0
        self._it += 1
        return finished

    def drain(self):
        """Run the scheduler until every submitted request has finished;
        returns ``{rid: np.ndarray}`` for everything completed during the
        call."""
        results = {}
        while self._queue or self._pending is not None or self._events \
                or self._mirror_active.any():
            results.update(self.step())
        return results

    def close(self):
        """Return the KV workspaces (the big slot cache, the slot state
        and the prefill lanes); a later ``step()`` reallocates them.
        In-flight requests (if any) are aborted — only the queue
        survives."""
        finished = {}
        try:
            self._process_events(finished, keep=0)
        except Exception as e:               # dead buffers from a failure
            logger.warning(f"serving close(): discarding unreadable "
                           f"in-flight events ({type(e).__name__}: {e})")
        if finished:
            logger.warning(f"serving close(): {len(finished)} finished "
                           f"request(s) discarded unread")
        self._abort_in_flight("close()")
        if self._cache is not None:
            self._cache_ws.give_back(self._cache)
            self._cache = None
        self._state = None
        self._cache_ws.release()
        self._lane_pool.release()

    def _abort_in_flight(self, why):
        """Drop every request past admission (its KV rows live in buffers
        that are dead or about to be re-initialized) and restore the slot
        bookkeeping to all-free — queued requests survive and the next
        ``step()`` runs on a fresh workspace.  Without this, a failed
        decode dispatch would leak the occupied slots forever (drain()
        then spins: nothing free to admit, nothing active to decode) and
        stale events would replay against the fresh all-inactive state."""
        lost = [r.rid for r in self._slots if r is not None]
        if self._pending is not None:
            lost.append(self._pending.req.rid)
            self._lane_pool.give_back(self._pending.lane)
            self._pending = None
        self._events.clear()
        self._slots = [None] * self.num_slots
        self._free = deque(range(self.num_slots))
        self._mirror_active[:] = False
        self._state = None
        if lost:
            self.stats["aborted"] = self.stats.get("aborted", 0) + len(lost)
            logger.warning(f"serving {why}: aborted {len(lost)} in-flight "
                           f"request(s) {lost} — queued requests survive")

    @property
    def queue_depth(self):
        return len(self._queue) + (1 if self._pending is not None else 0)

    @property
    def active_slots(self):
        """Live slots as of the last PROCESSED event (the host mirror)."""
        return int(np.sum(self._mirror_active))

    @property
    def in_flight(self):
        """Dispatched device events not yet processed."""
        return len(self._events)

    # ------------------------------------------------------------------ #
    # Warmup — compile (or reload) the expensive programs up front
    # ------------------------------------------------------------------ #
    def warmup(self, monitor=None):
        """AOT-compile the expensive serving programs (the decode block
        and the admission prefill chunk) against abstract arguments —
        with the ``compile_cache`` block on, a restarted server RELOADS
        them instead of recompiling (watch
        ``compile_cache.stats().executable_hits``).  Returns
        ``{program: compile_seconds}`` (0.0 = warm/store hit).

        The fused admit program deliberately compiles on first use
        instead: it takes no ``params``, so an abstract-args compile would
        pin it to single-device input shardings while its runtime inputs
        (chunk-program outputs) carry the mesh's replicated sharding —
        first-use compilation sees the real shardings and still
        round-trips the executable store like everything else."""
        eng = self.engine
        N, S, C = self.num_slots, self.cache_len, self.chunk
        dtype = eng.compute_dtype
        cache = jax.eval_shape(
            lambda: self.module.init_cache(N, S, dtype=dtype))
        lane = jax.eval_shape(
            lambda: self.module.init_cache(1, S, dtype=dtype))
        state = {
            "token": jax.ShapeDtypeStruct((N,), jnp.int32),
            "pos": jax.ShapeDtypeStruct((N,), jnp.int32),
            "active": jax.ShapeDtypeStruct((N,), jnp.bool_),
            "remaining": jax.ShapeDtypeStruct((N,), jnp.int32),
            "eos": jax.ShapeDtypeStruct((N,), jnp.int32),
        }
        rng = jax.eval_shape(lambda: jax.random.key(0))
        report = {}

        def warm(fn, args, name):
            from deepspeed_tpu.runtime import compile_cache as cc
            sig = (id(fn),) + cc.abstract_signature(args)
            if sig in eng._aot:
                return {name: 0.0}
            compiled, dt, hit = eng._aot_compile(fn, args)
            if compiled is None:
                logger.warning(f"serving warmup: {name} failed to "
                               f"AOT-compile — it compiles on first use")
                return {}
            eng._aot[sig] = compiled
            return {name: 0.0 if hit else dt}

        cargs = (eng._params, lane,
                 jax.ShapeDtypeStruct((1, C), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.int32),
                 jax.ShapeDtypeStruct((1,), jnp.int32))
        report.update(warm(self._chunk_fn, cargs, f"serving_prefill:c{C}"))
        report.update(warm(self._decode_fn,
                           (eng._params, cache, state, rng),
                           f"serving_decode:n{N}s{S}b{self.block}"))
        for name, dt in report.items():
            log_dist(f"serving warmup[{name}]: "
                     + ("cached" if dt == 0.0 else f"{dt:.1f}s"), ranks=[0])
        mon = monitor or self.monitor
        if mon is not None and getattr(mon, "enabled", True):
            mon.write_events([(f"Compile/{name}_secs", dt, 0)
                              for name, dt in report.items()])
        return report

    # ------------------------------------------------------------------ #
    # Admission: queue -> prefill chunks -> fused admit dispatch
    # ------------------------------------------------------------------ #
    def _pop_request(self):
        if self.config.admission == "shortest_first":
            req = min(self._queue, key=lambda r: (len(r.ids), r.rid))
            self._queue.remove(req)
            return req
        return self._queue.popleft()

    def _admit(self):
        limit = self.config.prefill_token_budget or math.inf
        spent = 0
        while spent < limit:
            if self._pending is None:
                if not self._queue or not self._free:
                    return
                self._pending = self._start_prefill(self._pop_request())
            done = self._run_prefill_chunk(self._pending)
            spent += self.chunk
            if done:
                pend, self._pending = self._pending, None
                self._dispatch_admit(pend)

    def _start_prefill(self, req):
        slot = self._free.popleft()
        req.slot = slot
        P = len(req.ids)
        n = -(-P // self.chunk)
        ids_pad = np.zeros((1, n * self.chunk), np.int32)
        ids_pad[0, :P] = req.ids
        lane = self._lane_pool.take(self.cache_len,
                                    self.engine.compute_dtype)
        return _PendingPrefill(req, slot, lane, ids_pad, n)

    def _run_prefill_chunk(self, p):
        C = self.chunk
        P = len(p.req.ids)
        local = int(min(max(P - 1 - p.ci * C, 0), C - 1))
        try:
            logits, p.lane = self.engine._run_guarded(
                self._chunk_fn,
                (self.engine._params, p.lane,
                 jnp.asarray(p.ids_pad[:, p.ci * C:(p.ci + 1) * C]),
                 jnp.asarray(p.ci * C, jnp.int32),
                 jnp.asarray([local], jnp.int32)))
        except BaseException:
            # the donated lane may be dead — drop only THIS admission
            # (the decode workspace is untouched by a prefill failure)
            self._lane_pool.give_back(p.lane)
            self._free.append(int(p.slot))
            self._pending = None
            logger.warning(f"serving prefill failed — request "
                           f"{p.req.rid} dropped")
            raise
        if (P - 1) // C == p.ci:
            # this chunk held the prompt's last real position — its
            # selected logits seed the first sampled token (device-side;
            # never synchronized here)
            p.sel = logits
        p.ci += 1
        self.stats["prefill_tokens"] += C
        return p.ci >= p.n_chunks

    def _dispatch_admit(self, p):
        """Prefill complete: ONE fused dispatch samples the first token,
        inserts the lane and writes the slot state in-program.  The first
        token is read lazily when the event is processed."""
        req = p.req
        self._rng, sub = jax.random.split(self._rng)
        try:
            self._cache, self._state, first = self.engine._run_guarded(
                self._admit_fn,
                (self._cache, self._state, p.lane, p.sel, sub,
                 jnp.asarray(p.slot, jnp.int32),
                 jnp.asarray(len(req.ids), jnp.int32),
                 jnp.asarray(req.max_new, jnp.int32),
                 jnp.asarray(req.eos, jnp.int32)))
        except BaseException:
            # cache/state were donated — same recovery as a decode
            # failure (this admission's request is lost with them)
            self._cache_ws.give_back(self._cache)
            self._cache = None
            self._lane_pool.give_back(p.lane)
            self._abort_in_flight(f"admit dispatch failed "
                                  f"(request {req.rid} lost)")
            raise
        self._slots[p.slot] = req
        self._events.append(("admit", req, p.slot, p.lane, first))
        self.stats["admitted"] += 1

    # ------------------------------------------------------------------ #
    # Decode: one block of the single reusable decode-step program
    # ------------------------------------------------------------------ #
    def _dispatch_decode(self):
        # dispatch when anything can be live on device: a slot active as
        # of the mirror, or an unprocessed admit that (probably) went live
        if not (self._mirror_active.any()
                or any(e[0] == "admit" for e in self._events)):
            return False
        self._rng, sub = jax.random.split(self._rng)
        try:
            toks, self._cache, self._state = self.engine._run_guarded(
                self._decode_fn,
                (self.engine._params, self._cache, self._state, sub))
        except BaseException:
            # the donated cache/state may be dead — drop them so the next
            # step's workspace take() reallocates, and abort everything
            # past admission (its KV rows died with the buffers; stale
            # events/slot bookkeeping must not survive into the fresh
            # state).  Queued requests are untouched.
            self._cache_ws.give_back(self._cache)
            self._cache = None
            self._abort_in_flight("decode dispatch failed")
            raise
        self._events.append(("decode", toks))
        self.stats["decode_calls"] += 1
        return True

    # ------------------------------------------------------------------ #
    # Event processing (the host's lagging mirror of the device)
    # ------------------------------------------------------------------ #
    def _process_events(self, finished, keep=0):
        while len(self._events) > keep:
            ev = self._events.popleft()
            if ev[0] == "admit":
                self._process_admit(ev, finished)
            else:
                self._process_decode(ev, finished)

    def _process_admit(self, ev, finished):
        _, req, slot, lane, first_dev = ev
        t0 = time.perf_counter()
        first = int(np.asarray(first_dev))
        self.stats["sync_secs"] += time.perf_counter() - t0
        self._lane_pool.give_back(lane)
        req.tokens = [first]
        # mirror the admit program's activation rule
        if (req.eos >= 0 and first == req.eos) or req.max_new == 1:
            self._slots[slot] = None
            self._free.append(int(slot))
            finished[req.rid] = self._finalize(req)
        else:
            self._mirror_active[slot] = True

    def _process_decode(self, ev, finished):
        t0 = time.perf_counter()
        toks = np.asarray(ev[1])                         # [block, N]
        self.stats["sync_secs"] += time.perf_counter() - t0
        # mirror the in-program retirement rule step by step: an emitted
        # eos (or max_new reached) ends the request and frees its slot
        for t in range(toks.shape[0]):
            row = toks[t]
            for s in np.nonzero(self._mirror_active)[0]:
                req = self._slots[s]
                tok = int(row[s])
                req.tokens.append(tok)
                self.stats["decode_tokens"] += 1
                if (req.eos >= 0 and tok == req.eos) \
                        or len(req.tokens) >= req.max_new:
                    self._mirror_active[s] = False
                    self._slots[s] = None
                    self._free.append(int(s))
                    finished[req.rid] = self._finalize(req)
        self.occupancy_trace.append(
            (self._it, int(self._mirror_active.sum())))

    def _finalize(self, req):
        """The ``generate()`` output contract: ``[prompt..., tokens...]``
        of length ``P + max_new_tokens``, eos-padded past an early stop."""
        req.finished_it = self._it
        self.stats["completed"] += 1
        P = len(req.ids)
        pad = req.eos if req.eos >= 0 else 0
        out = np.full((P + req.max_new,), pad, np.int32)
        out[:P] = req.ids
        out[P:P + len(req.tokens)] = np.asarray(req.tokens, np.int32)
        return out

    # ------------------------------------------------------------------ #
    # Plumbing
    # ------------------------------------------------------------------ #
    def _ensure_workspace(self):
        if self._cache is None:
            self._cache = self._cache_ws.take(
                self.num_slots, self.cache_len, self.engine.compute_dtype)
        if self._state is None:
            self._state = {k: jnp.asarray(v) for k, v in
                           init_slot_state(self.num_slots).items()}
            self._mirror_active[:] = False

    def _emit_metrics(self):
        mon = self.monitor
        if mon is None or not getattr(mon, "enabled", True):
            return
        wall = self.stats["wall_secs"]
        mon.write_events([
            ("Serving/queue_depth", self.queue_depth, self._it),
            ("Serving/slot_occupancy",
             self.active_slots / self.num_slots, self._it),
            ("Serving/decode_tok_s",
             self.stats["decode_tokens"] / wall if wall > 0 else 0.0,
             self._it),
            ("Serving/prefill_decode_ratio",
             self.stats["prefill_tokens"]
             / max(self.stats["decode_tokens"], 1), self._it),
            ("Serving/completed", self.stats["completed"], self._it),
        ])
