"""Per-collective communication logging.

Analog of the reference's ``deepspeed/utils/comms_logging.py:61``
(``CommsLogger``): per-op counts, message sizes, latency, and algorithmic /
bus bandwidth, fed by the ``timed_op`` wrapper in the comm layer.
"""

import math
from collections import defaultdict

from deepspeed_tpu.utils.logging import log_dist


def get_msg_size_from_args(arrays):
    """Total payload bytes of the arrays involved in a collective."""
    total = 0
    leaves = arrays if isinstance(arrays, (list, tuple)) else [arrays]
    for a in leaves:
        size = getattr(a, "size", None)
        itemsize = getattr(getattr(a, "dtype", None), "itemsize", None)
        if size is not None and itemsize is not None:
            total += int(size) * int(itemsize)
    return total


def calc_bw_log(comm_op, size, duration):
    """algbw/busbw in GB/s — same correction factors as the reference
    (``comms_logging.py`` ring-algorithm factors)."""
    n = max(duration, 1e-9)
    algbw = size / n
    if comm_op in ("all_reduce",):
        busbw = algbw * 2  # ring allreduce moves ~2x payload
    else:
        busbw = algbw
    return algbw / 1e9, busbw / 1e9


class CommsLogger:

    def __init__(self, verbose=False, debug=False, prof_ops=None, enabled=False):
        self.enabled = enabled
        self.verbose = verbose
        self.debug = debug
        self.prof_ops = prof_ops or []
        self.comms_dict = defaultdict(lambda: defaultdict(lambda: [0, 0.0, 0.0, 0.0]))

    def configure(self, comms_config):
        self.enabled = comms_config.enabled
        self.verbose = comms_config.verbose
        self.debug = comms_config.debug
        self.prof_ops = comms_config.prof_ops

    def append(self, raw_name, record_name, latency, msg_size):
        algbw, busbw = calc_bw_log(raw_name, msg_size, latency)
        entry = self.comms_dict[record_name][msg_size]
        entry[0] += 1
        entry[1] += latency
        entry[2] += algbw
        entry[3] += busbw
        if self.verbose:
            log_dist(
                f"comm op: {record_name} | time (ms): {latency*1e3:.2f} | "
                f"msg size: {msg_size} | algbw (GB/s): {algbw:.2f} | busbw (GB/s): {busbw:.2f}",
                ranks=[0])

    def log_all(self):
        header = f"{'Comm. Op':<20}{'Message Size':>15}{'Count':>10}{'Total Lat(ms)':>16}{'Avg Lat(ms)':>14}{'algbw(GB/s)':>14}{'busbw(GB/s)':>14}"
        lines = [header]
        for record_name, sizes in sorted(self.comms_dict.items()):
            for size, (count, lat, algbw, busbw) in sorted(sizes.items()):
                lines.append(
                    f"{record_name:<20}{_fmt_size(size):>15}{count:>10}"
                    f"{lat*1e3:>16.2f}{lat*1e3/max(count,1):>14.2f}"
                    f"{algbw/max(count,1):>14.2f}{busbw/max(count,1):>14.2f}")
        log_dist("\n".join(lines), ranks=[0])
        return "\n".join(lines)


def _fmt_size(num_bytes):
    if num_bytes == 0:
        return "0B"
    units = ("B", "KB", "MB", "GB", "TB")
    i = min(int(math.log(num_bytes, 1024)), len(units) - 1)
    return f"{num_bytes / (1024 ** i):.2f} {units[i]}"
