"""Quantization tests — analog of reference ``tests/unit/ops/quantizer/`` and
``tests/unit/runtime/test_ds_config`` MoQ paths: kernels vs fp32 reference,
MoQ schedule, eigenvalue power iteration, PLD schedule."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.quantizer import (
    quantize, dequantize, fake_quantize, pack_int4, unpack_int4,
    quantize_ternary, quantize_binary)
from deepspeed_tpu.runtime.quantize import Quantizer, Eigenvalue
from deepspeed_tpu.runtime.progressive_layer_drop import (
    ProgressiveLayerDrop, layer_keep_prob, maybe_drop_layer)


def test_int8_symmetric_roundtrip_error_small():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(16, 64)), jnp.float32)
    q, s, z = quantize(x, num_groups=16, num_bits=8)
    assert q.dtype == jnp.int8
    back = dequantize(q, s, z, 8, shape=x.shape)
    err = float(jnp.max(jnp.abs(back - x)))
    # max error bounded by half a quantization step per group
    step = float(jnp.max(s))
    assert err <= step * 0.51 + 1e-6


def test_int8_asymmetric_roundtrip():
    x = jnp.asarray(np.random.default_rng(1).uniform(2.0, 3.0, (8, 32)), jnp.float32)
    q, s, z = quantize(x, 8, 8, symmetric=False)
    back = dequantize(q, s, z, 8, symmetric=False, shape=x.shape)
    assert float(jnp.max(jnp.abs(back - x))) < float(jnp.max(s)) * 0.51 + 1e-6


def test_int4_pack_unpack_roundtrip():
    x = jnp.asarray(np.random.default_rng(2).normal(size=(4, 32)), jnp.float32)
    q, s, z = quantize(x, 4, num_bits=4)
    packed = pack_int4(q)
    assert packed.shape == (4, 16) and packed.dtype == jnp.uint8
    unpacked = unpack_int4(packed)
    np.testing.assert_array_equal(np.asarray(unpacked), np.asarray(q))


def test_fake_quantize_straight_through_grad():
    x = jnp.asarray(np.random.default_rng(3).normal(size=(64,)), jnp.float32)
    g = jax.grad(lambda t: jnp.sum(fake_quantize(t, 4, 8) * 2.0))(x)
    np.testing.assert_allclose(np.asarray(g), 2.0 * np.ones(64), rtol=1e-6)


def test_ternary_binary_shapes():
    x = jnp.asarray(np.random.default_rng(4).normal(size=(8, 16)), jnp.float32)
    t = quantize_ternary(x, 8)
    b = quantize_binary(x, 8)
    assert t.shape == (8, 16) and b.shape == (8, 16)
    # binary has exactly one magnitude per group
    mags = np.unique(np.round(np.abs(np.asarray(b[0])), 5))
    assert len(mags) == 1


def test_moq_quantizer_bit_schedule():
    qz = Quantizer(q_groups=4, q_start_bits=10, q_target_bits=8, q_period=2)
    params = {"w": jnp.ones((8, 8), jnp.float32),
              "b": jnp.ones((8,), jnp.float32)}
    assert qz.any_precision_switch()
    for _ in range(30):
        params = qz.quantize(params)
    assert qz.current_bits[0] == 8
    assert not qz.any_precision_switch()
    # bias untouched by quantization
    np.testing.assert_array_equal(np.asarray(params["b"]), np.ones(8))


def test_moq_skips_on_overflow():
    qz = Quantizer(q_start_bits=8, q_target_bits=8)
    params = {"w": jnp.ones((4, 4))}
    out = qz.quantize(params, overflow=True)
    assert out is params


def test_eigenvalue_power_iteration_quadratic():
    # loss = 0.5 x^T A x with known dominant eigenvalue
    A = jnp.diag(jnp.asarray([5.0, 2.0, 1.0]))
    loss = lambda p: 0.5 * p["x"] @ A @ p["x"]
    ev = Eigenvalue(max_iter=200, tol=1e-4)
    val = ev.compute_eigenvalue(loss, {"x": jnp.ones(3)})
    assert abs(val - 5.0) < 0.1


def test_eigenvalue_post_process():
    ev = Eigenvalue()
    out = ev.post_process([2.0, 0.0, float("nan"), 4.0])
    assert out == [0.5, 1.0, 1.0, 1.0]


def test_pld_theta_anneals():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta() == 1.0
    pld.update_state(0)
    assert abs(pld.get_theta() - 1.0) < 1e-6
    pld.update_state(10_000)
    assert abs(pld.get_theta() - 0.5) < 1e-2
    assert pld.get_state()["progressive_layer_drop"]


def test_layer_keep_prob_monotone_in_depth():
    ps = [layer_keep_prob(0.6, i, 12) for i in range(12)]
    assert ps[0] == 1.0 and all(a >= b for a, b in zip(ps, ps[1:]))


def test_maybe_drop_layer_expectation():
    x = jnp.ones((4,), jnp.float32)
    layer = lambda t: t * 3.0
    outs = []
    for i in range(200):
        outs.append(maybe_drop_layer(layer, x, jax.random.key(i), 0.5))
    mean = float(jnp.mean(jnp.stack(outs)))
    # E[out] = x + E[keep/p](out-x) = 3.0
    assert abs(mean - 3.0) < 0.45


def test_weight_quantizer_awkward_shapes_and_asymmetric():
    """WeightQuantization edge cases: prime-sized tensors keep the
    configured group granularity (padding, no whole-tensor collapse);
    asymmetric int4 round-trips via the tensor's OWN metadata."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.runtime.weight_quantizer import (QuantizedWeight,
                                                        WeightQuantization)
    rng = np.random.default_rng(0)

    # awkward numel (89*89, coprime with 64) with an outlier: per-group
    # scales must localize it
    x = rng.standard_normal(89 * 89).astype(np.float32)
    x[13] = 100.0
    wq = WeightQuantization(bits=8, group_size=64, min_ndim=1)
    qw = wq.quantize_leaf(jnp.asarray(x).reshape(89, 89))
    assert qw.scale.shape[0] > 100          # real groups, not 1
    back = np.asarray(wq.dequantize_leaf(qw, jnp.float32)).reshape(-1)
    # the outlier coarsens ONLY its own group (~group_size elems); all
    # other groups keep fine scales
    err = np.abs(back - x)
    assert (err > 2e-2).sum() <= 70, (err > 2e-2).sum()
    assert err[200:].max() < 2e-2    # far from the outlier: tight

    # asymmetric int4: dequant reads qw.symmetric/bits, not the decoder's
    y = jnp.asarray(rng.standard_normal((9, 9)), jnp.float32)  # odd dims
    wq4 = WeightQuantization(bits=4, group_size=32, symmetric=False)
    qw4 = wq4.quantize_leaf(y)
    assert qw4.bits == 4 and not qw4.symmetric
    decoder = WeightQuantization()           # default symmetric int8
    back4 = np.asarray(decoder.dequantize_leaf(qw4, jnp.float32))
    err = np.abs(back4 - np.asarray(y)).max()
    assert err < 0.3, err                    # int4 coarse but sane
