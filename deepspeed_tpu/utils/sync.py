"""Device-sync helpers.

Through the axon tunnel ``jax.block_until_ready`` can return before the
device work is actually done; the reliable fence is a DEPENDENT transfer —
fetching a scalar derived from the output forces completion.  Every timing
path (bench.py, op_bench, flops profiler) must use this one helper.
"""

import numpy as np

import jax


def dependent_sync_scalar(x):
    """Block until ``x`` (array or pytree) is computed by fetching one
    scalar derived from it; returns that scalar as a float."""
    leaf = jax.tree.leaves(x)[0]
    return float(np.asarray(jax.device_get(leaf)).reshape(-1)[0])
