"""TL011 positive fixture — implicit resharding seams.

Mid-step placement changes inside hot paths (direct, via helper, and the
constraint form) and literal mesh-axis names the canonical topology does
not define (shard_map specs and traced collectives)."""
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from deepspeed_tpu.tools.lint.hotpath import hot_path

mesh = Mesh(jax.devices(), ("tp",))


@hot_path("fixture.decode_step")
def decode_step(params, cache, token):
    # a mid-step reshard: host-synchronized, in no locked comm budget
    cache = jax.device_put(cache, NamedSharding(mesh, P("tp")))
    logits = apply(params, cache, token)
    out = jax.lax.with_sharding_constraint(logits, P("tp"))
    return out


def _respill(grads):
    # flagged through hot reachability: called from the hot train step
    return jax.device_put(grads, NamedSharding(mesh, P("tp")))


@hot_path("fixture.train_step")
def train_step(params, grads):
    return _respill(grads)


def body(x, w):
    return x @ w


# axis names the canonical topology (pp/mdp/edp/ep/sp/tp) does not define
smap_bad_axis = shard_map(body, mesh=mesh,
                          in_specs=(P("dp"), P(None, "model")),
                          out_specs=P(("data", "model")))


def reduce_over(x):
    y = jax.lax.psum(x, "model")
    z = jax.lax.all_gather(x, axis_name="shard")
    return y + z
