"""SPMD pipeline parallelism over the ``pp`` mesh axis.

TPU-native re-design of the reference pipeline engine
(``runtime/pipe/engine.py:42``, ``schedule.py:189`` 1F1B, ``p2p.py:50,71``).
The reference interprets an instruction schedule per-rank and exchanges
activations with NCCL point-to-point sends.  Under single-controller SPMD the
whole schedule becomes ONE differentiable program:

* stages are shards of the ``pp`` axis inside ``shard_map`` (manual over
  ``pp`` only — dp/tp/sp stay GSPMD-automatic);
* the schedule is a ``lax.scan`` over ticks; stage *s* works on microbatch
  ``m = t - s`` (the classic pipeline wavefront);
* activation transfer is one ``lax.ppermute`` per tick riding ICI neighbors
  (both halves of the reference's send/recv pair);
* the backward pipeline is **not hand-written**: differentiating the scan
  yields the reverse wavefront with reversed ppermutes automatically, with
  the per-tick stage inputs as residuals (= the reference's activation
  stash).  ``jax.checkpoint`` on the stage body gives the same memory
  behavior as its activation-checkpointed stages.

The dead-time fraction is the standard bubble ``(P-1)/(M+P-1)`` — identical
to GPipe/1F1B fill-drain; XLA overlaps the ppermute with compute.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import PP_AXIS


def spmd_pipeline(stage_fn, stacked_params, x0, num_micro, mesh,
                  pp_axis=PP_AXIS, remat_stage=True):
    """Run the pipelined forward: returns last-stage outputs ``[M, ...]``.

    ``stage_fn(stage_params, x) -> y`` maps one stage over one microbatch
    activation (same shape in/out).  ``stacked_params`` leaves have leading
    dim P (one slice per stage).  ``x0``: ``[M, ...]`` microbatch activations
    entering stage 0.  Fully differentiable.
    """
    n_stages = mesh.shape[pp_axis]
    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn)

    # XLA's CPU backend (the simulated test mesh) crashes promoting bf16
    # all-reduces, which the region's backward emits for the replicated x0
    # cotangent.  Run the region in f32 on CPU; TPU stays bf16.
    cast_back = None
    if jax.default_backend() == "cpu" and x0.dtype == jnp.bfloat16:
        cast_back = x0.dtype
        x0 = x0.astype(jnp.float32)
        inner_stage_fn = stage_fn
        stage_fn = lambda p, x: inner_stage_fn(p, x.astype(jnp.bfloat16)).astype(jnp.float32)

    def region(params, x0):
        sid = lax.axis_index(pp_axis)
        M = num_micro
        T = M + n_stages - 1
        params_local = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        state0 = jnp.zeros_like(x0[0])

        fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(state, t):
            # receive previous stage's activation (stage 0 receives zeros)
            recv = lax.ppermute(state, pp_axis, fwd_perm) if n_stages > 1 else state
            x_t = lax.dynamic_index_in_dim(x0, jnp.minimum(t, M - 1), 0,
                                           keepdims=False)
            inp = jnp.where(sid == 0, x_t, recv)
            m = t - sid
            active = jnp.logical_and(m >= 0, m < M)
            y = stage_fn(params_local, inp)
            y = jnp.where(active, y, jnp.zeros_like(y))
            # emit only the last stage's finished microbatches
            out = jnp.where(jnp.logical_and(active, sid == n_stages - 1), y,
                            jnp.zeros_like(y))
            return y, out

        _, outs = lax.scan(tick, state0, jnp.arange(T))
        # outs[t] holds microbatch m = t-(P-1) on the last stage, zeros
        # elsewhere; psum over pp broadcasts last-stage values to all shards.
        outs = outs[n_stages - 1:]
        if n_stages > 1:
            outs = lax.psum(outs, pp_axis)
        return outs

    in_specs = (jax.tree.map(lambda _: P(pp_axis), stacked_params), P())
    out = jax.shard_map(
        region, mesh=mesh, in_specs=in_specs, out_specs=P(),
        axis_names=frozenset({pp_axis}), check_vma=False,
    )(stacked_params, x0)
    return out.astype(cast_back) if cast_back is not None else out


def pipeline_bubble_fraction(num_micro, num_stages):
    return (num_stages - 1) / (num_micro + num_stages - 1)


def stack_stage_params(per_layer_params, num_stages):
    """Group L per-layer param trees (identical structure) into
    ``[P, L/P, ...]`` stacked pytrees for the SPMD pipeline."""
    L = len(per_layer_params)
    if L % num_stages != 0:
        raise ValueError(f"{L} body layers not divisible by {num_stages} stages")
    per_stage = L // num_stages
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *per_layer_params)
    return jax.tree.map(
        lambda a: a.reshape(num_stages, per_stage, *a.shape[1:]), stacked)
