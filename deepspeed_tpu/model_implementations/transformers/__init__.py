from deepspeed_tpu.model_implementations.transformers.ds_transformer import (  # noqa: F401
    DeepSpeedTransformerInference)
