"""TL007 — variable read after being passed in a donated position.

``donate_argnums`` hands the input buffer to XLA: after the call the
Python name still points at a *dead* array whose storage the program
reused for its outputs.  Reading it afterwards is exactly the bug class
behind the PR 5 serving-cache corruption — wrong values, cross-lane
clobbers, or a crash, all nondeterministic because liveness depends on
scheduling.  The rule runs an intraprocedural dataflow over each
function:

* a **name** passed in a donated position of a module-locally resolvable
  donating callable (``x = jax.jit(f, donate_argnums=...)`` bindings,
  ``@partial(jax.jit, donate_argnums=...)`` defs, inline
  ``jax.jit(f, ...)(args)``) is CONSUMED at that statement;
* any later read of that name is a finding, unless a rebind (assignment,
  loop target, ``with ... as``) intervenes — ``cache = f(params, cache)``
  rebinds at the consuming statement and is clean;
* a donation inside a loop whose body never rebinds the name is flagged
  at the call: the next iteration dispatches a dead buffer (the
  ``KVCacheWorkspace.take()/give_back()`` protocol exists to make this
  rebind explicit).

Attribute state (``self._cache``) is out of scope — the serving engine
re-binds those from program outputs by contract; the jaxpr harness and
the contract lockfile guard that path at the compiler level instead.
"""

import ast

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule
from deepspeed_tpu.tools.lint.rules.tl002_missing_donation import (
    JIT_NAMES, jit_decorator_kwargs)
from deepspeed_tpu.tools.lint.rules.tl004_bad_static_args import (
    _int_tuple, _str_tuple)


def _donate_spec(keywords):
    """(argnums, argnames) of a jit application's donation kwargs."""
    nums, names = (), ()
    for kw in keywords or []:
        if kw.arg == "donate_argnums":
            nums = _int_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            names = _str_tuple(kw.value)
    return nums, names


def _donating_callables(module):
    """Bare name -> (donated_argnums, donated_argnames)."""
    out = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and dotted_name(node.value.func) in JIT_NAMES:
            nums, names = _donate_spec(node.value.keywords)
            if nums or names:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = (nums, names)
    for fn in module.functions:
        kws = jit_decorator_kwargs(fn.node)
        if kws:
            nums, names = _donate_spec(kws)
            if nums or names:
                out[fn.name] = (nums, names)
    return out


def _own_nodes(fn_node):
    """Nodes of ``fn_node`` excluding nested function bodies (each nested
    def is analyzed as its own function)."""
    nested = set()
    for child in ast.walk(fn_node):
        if child is not fn_node and isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            nested.update(n for n in ast.walk(child) if n is not child)
    return [n for n in ast.walk(fn_node) if n not in nested]


def _parents(fn_node):
    out = {}
    for parent in ast.walk(fn_node):
        for child in ast.iter_child_nodes(parent):
            out[child] = parent
    return out


def _stmt_of(node, parents, fn_node):
    while node in parents and not isinstance(node, ast.stmt):
        node = parents[node]
    return node if isinstance(node, ast.stmt) else fn_node


def _enclosing_loops(node, parents, fn_node):
    loops = []
    while node in parents and node is not fn_node:
        node = parents[node]
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            loops.append(node)
    return loops


@rule("TL007", "variable read after donation")
def check(module):
    donating = _donating_callables(module)
    if not donating:
        return
    for fi in module.functions:
        own = _own_nodes(fi.node)
        own_set = set(own)
        parents = _parents(fi.node)
        stores = [n for n in own if isinstance(n, ast.Name)
                  and isinstance(n.ctx, ast.Store)]
        loads = [n for n in own if isinstance(n, ast.Name)
                 and isinstance(n.ctx, ast.Load)]

        for call in own:
            if not isinstance(call, ast.Call):
                continue
            callee = call.func
            spec = None
            cname = None
            if isinstance(callee, ast.Name) and callee.id in donating:
                spec, cname = donating[callee.id], callee.id
            elif isinstance(callee, ast.Call) and \
                    dotted_name(callee.func) in JIT_NAMES:
                nums, names = _donate_spec(callee.keywords)
                if nums or names:
                    spec = (nums, names)
                    cname = dotted_name(callee.args[0]) \
                        if callee.args else "jit"
            if spec is None:
                continue
            nums, names = spec
            donated = [(a.id, a) for i, a in enumerate(call.args)
                       if i in nums and isinstance(a, ast.Name)]
            donated += [(kw.value.id, kw.value) for kw in call.keywords
                        if kw.arg in names and isinstance(kw.value, ast.Name)]
            if not donated:
                continue
            stmt = _stmt_of(call, parents, fi.node)
            stmt_end = getattr(stmt, "end_lineno", stmt.lineno)
            loops = _enclosing_loops(call, parents, fi.node)
            for name, arg_node in donated:
                # the consuming statement rebinding the name from the
                # result (`cache = f(params, cache)`) clears the taint
                rebound_here = any(
                    s.id == name and
                    _stmt_of(s, parents, fi.node) is stmt for s in stores)
                if not rebound_here:
                    for read in loads:
                        if read.id != name or read.lineno <= stmt_end:
                            continue
                        cleared = any(
                            s.id == name and
                            stmt_end <= s.lineno < read.lineno and
                            _stmt_of(s, parents, fi.node) is not
                            _stmt_of(read, parents, fi.node)
                            for s in stores)
                        if not cleared:
                            yield Finding(
                                "TL007", module.path, read.lineno,
                                read.col_offset,
                                f"'{name}' read after being donated to "
                                f"'{cname}' (line {call.lineno}) — the "
                                f"buffer is dead; use the returned value "
                                f"or re-materialize it")
                            break       # one finding per donated name
                # donation in a loop: the call itself re-reads the name
                # next iteration unless the loop body rebinds it
                for loop in loops:
                    loop_stores = any(
                        s.id == name and s in own_set and
                        loop.lineno <= s.lineno <=
                        (loop.end_lineno or loop.lineno) for s in stores)
                    if not loop_stores:
                        yield Finding(
                            "TL007", module.path, call.lineno,
                            call.col_offset,
                            f"'{name}' donated to '{cname}' inside a loop "
                            f"that never rebinds it — the next iteration "
                            f"dispatches a dead buffer; rebind from the "
                            f"call's result (or take() a fresh one) each "
                            f"iteration")
                        break
