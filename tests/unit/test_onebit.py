"""1-bit optimizer + compressed-collective tests (analog of reference
``tests/unit/runtime/half_precision/onebit/test_onebit.py``)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.comm.compressed import (CompressedBackend,
                                                   compressed_allreduce,
                                                   pack_signs, unpack_signs)

from simple_model import SimpleModel, random_batch


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(100), jnp.float32)
    signs = unpack_signs(pack_signs(x), 100)
    np.testing.assert_array_equal(np.asarray(signs),
                                  np.where(np.asarray(x) >= 0, 1.0, -1.0))
    # wire volume really is 1 bit/elem (+padding to bytes)
    assert pack_signs(x).nbytes == 13


@pytest.mark.parametrize("opt_type", ["OneBitAdam", "ZeroOneAdam", "OneBitLamb"])
def test_onebit_optimizers_train(opt_type):
    """Every 1-bit family member must train SimpleModel to a lower loss,
    both in warmup and in the compressed regime (freeze_step=3)."""
    params = {"lr": 1e-2}
    if opt_type in ("OneBitAdam", "OneBitLamb"):
        params["freeze_step"] = 3
    else:
        params["var_freeze_step"] = 3
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": opt_type, "params": params}})
    losses = []
    for i in range(12):
        # fixed batch: the compressed-regime assertion needs a deterministic
        # decreasing trajectory, not fresh noise per step
        loss = engine(random_batch(batch_size=16, seed=0))
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], (opt_type, losses)


def test_zero_one_adam_refresh_schedule():
    """Variance refreshes must be geometrically spaced and keep firing
    forever (a naive per-step interval formula stops refreshing after a few
    multiples of var_update_scaler)."""
    from deepspeed_tpu.ops.adam.onebit_adam import ZeroOneAdam
    opt = ZeroOneAdam(var_update_scaler=4, var_freeze_step=10**6)
    steps = np.arange(1, 2000)
    hits = [int(s) for s in steps if bool(opt._is_refresh_step(jnp.float32(s)))]
    # fires in every segment: intervals 1,2,4,8,... with 4 refreshes each
    assert hits[:8] == [1, 2, 3, 4, 6, 8, 10, 12], hits[:10]
    # still refreshing late (the buggy formula goes silent after step ~64)
    assert any(h > 1000 for h in hits), hits[-5:]
    # spacing grows geometrically
    gaps = np.diff(hits)
    assert gaps[-1] > gaps[0]
    assert all(g in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024) for g in gaps)


def test_onebit_lamb_freezes_trust_ratio():
    from deepspeed_tpu.ops.lamb.onebit_lamb import OnebitLamb
    opt = OnebitLamb(lr=1e-2, freeze_step=2)
    params = {"w": jnp.ones((8, 8))}
    state = opt.init(params)
    # non-uniform gradient: a constant tensor compresses losslessly (sign ×
    # mean|.| is exact), which would leave no error feedback to observe
    g = {"w": jnp.asarray(np.random.default_rng(0).standard_normal((8, 8)),
                          jnp.float32) * 0.1}
    for step in range(1, 6):
        params, state = opt.update(g, state, params, step=step)
        if step == 2:
            frozen = float(state.frozen_lamb_coeff["w"])
    # post-freeze the cached coefficient must not change
    assert float(state.frozen_lamb_coeff["w"]) == frozen
    # error feedback active post-freeze
    assert float(jnp.abs(state.error_feedback["w"]).sum()) > 0


def test_compressed_allreduce_approximates_mean(eight_devices):
    """Compressed allreduce must approximate the exact mean and the error
    feedback must tighten it over repeated rounds of the same signal."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(eight_devices), ("dp",))
    be = CompressedBackend(mesh, "dp")
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(1000), jnp.float32)
    exact = np.asarray(x)  # every worker holds the same tensor → mean == x
    # error feedback guarantees the CUMULATIVE transmitted signal telescopes
    # to the cumulative true signal (Σ out = Σ x + e_0 − e_T): per-round
    # outputs may wobble, the running sum must track
    cum = np.zeros_like(exact)
    cum_errs = []
    for i in range(1, 7):
        out = be.allreduce("g", x)
        cum += np.asarray(out)
        cum_errs.append(float(np.linalg.norm(cum - i * exact)
                              / np.linalg.norm(i * exact)))
    assert cum_errs[-1] < cum_errs[0], cum_errs
    assert cum_errs[-1] < 0.5, cum_errs
    # buffers persist + update
    assert float(jnp.abs(be.worker_errors["g"]).sum()) > 0


def test_compressed_allreduce_padded_tail(eight_devices):
    """n not divisible by world×8: pad bits must not bias the last chunk
    (pads decode as +1 sign with no error feedback unless masked)."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(eight_devices), ("dp",))
    be = CompressedBackend(mesh, "dp")
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal(1001), jnp.float32)
    exact = np.asarray(x)
    cum = np.zeros_like(exact)
    for i in range(1, 7):
        cum += np.asarray(be.allreduce("t", x))
    np.testing.assert_array_less(
        np.linalg.norm(cum - 6 * exact) / np.linalg.norm(6 * exact), 0.5)
    # tail elements specifically must track (they share the padded chunk)
    tail_err = np.abs(cum[-60:] / 6 - exact[-60:]).mean()
    head_err = np.abs(cum[:60] / 6 - exact[:60]).mean()
    assert tail_err < 3 * head_err + 0.2, (tail_err, head_err)
    # name reuse at a different size resets feedback instead of crashing
    out = be.allreduce("t", jnp.asarray(rng.standard_normal(257), jnp.float32))
    assert out.shape == (257,)


def test_compressed_allreduce_unbiased_over_workers(eight_devices):
    """With different per-worker tensors (sharded batch axis), the decoded
    mean must correlate strongly with the true mean."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from deepspeed_tpu.utils.jax_compat import shard_map
    import functools
    mesh = Mesh(np.array(eight_devices), ("dp",))
    rng = np.random.default_rng(1)
    per_worker = rng.standard_normal((8, 512)).astype(np.float32)
    true_mean = per_worker.mean(0)

    @functools.partial(shard_map, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P(), check_vma=False)
    def run(xs):
        x = xs[0]
        out, _, _ = compressed_allreduce(
            x, jnp.zeros_like(x), jnp.zeros((512 // 8,), jnp.float32), "dp")
        return out

    out = np.asarray(run(jnp.asarray(per_worker)))
    corr = np.corrcoef(out, true_mean)[0, 1]
    assert corr > 0.5, corr
