"""TPU accelerator implementation.

The TPU analog of the reference's ``accelerator/cuda_accelerator.py`` —
every ABC method mapped onto JAX device APIs instead of torch.cuda.
"""

import os

import jax
import jax.numpy as jnp

from .abstract_accelerator import Accelerator

# Datasheet HBM capacity per chip in bytes (public figures) — the
# fallback ``bytes_limit`` when the backend reports no
# ``memory_stats()['bytes_limit']`` (tunneled/relay PJRT platforms and
# the CPU test backend return empty/None stats).  0 = unknown/unbounded
# (host RAM is not a fixed budget); callers should skip budget checks.
DATASHEET_HBM_BYTES = {
    "tpu v4": int(32.0e9),
    "tpu v5 lite": int(16.0e9),     # v5e
    "tpu v5e": int(16.0e9),
    "tpu v5": int(96.0e9),          # v5p
    "tpu v6 lite": int(32.0e9),     # trillium
    "cpu": 0,
}


def datasheet_hbm_bytes(device=None):
    """Datasheet HBM capacity for ``device`` (default: device 0), keyed
    by its ``device_kind`` prefix; 0 when unknown."""
    d = device if device is not None else jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for key, val in DATASHEET_HBM_BYTES.items():
        if kind.startswith(key):
            return val
    return DATASHEET_HBM_BYTES.get(d.platform, 0)


class TPU_Accelerator(Accelerator):

    def __init__(self, platform="tpu"):
        super().__init__()
        self._name = platform
        self._communication_backend_name = "xla"
        self._seed = 42
        self._key = None
        self._peak_bytes = {}

    # ----------------------------------------------------------------- #
    def device_name(self, device_index=None):
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def is_available(self):
        try:
            return len(self.devices()) > 0
        except RuntimeError:
            return False

    def devices(self):
        try:
            return jax.local_devices()
        except RuntimeError:
            return []

    def device_count(self):
        return jax.local_device_count()

    def global_device_count(self):
        return jax.device_count()

    def current_device(self):
        return self.devices()[0]

    def current_device_name(self):
        return self.device_name(0)

    # ----------------------------------------------------------------- #
    def synchronize(self, device_index=None):
        # XLA dispatch is async; a tiny reduction forced to completion acts
        # as a full device barrier for profiling/timers.
        jnp.zeros(()).block_until_ready()

    # ----------------------------------------------------------------- #
    def manual_seed(self, seed):
        self._seed = int(seed)
        self._key = jax.random.key(self._seed)

    def initial_seed(self):
        return self._seed

    def rng_key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    # ----------------------------------------------------------------- #
    def memory_stats(self, device_index=None):
        dev = self.devices()[device_index or 0]
        try:
            stats = dev.memory_stats() or {}
        except Exception:
            stats = {}
        in_use = stats.get("bytes_in_use", 0)
        peak = max(self._peak_bytes.get(dev.id, 0),
                   stats.get("peak_bytes_in_use", 0))
        if in_use > peak:
            peak = in_use
        self._peak_bytes[dev.id] = peak
        stats.setdefault("peak_bytes_in_use", peak)
        return stats

    def memory_snapshot(self, device_index=None):
        """The base normalization (one canonical reader — see the ABC
        docstring) refined with the datasheet fallback: when the
        backend reports no live ``bytes_limit`` (the CPU test backend,
        tunneled PJRT), the budget falls back to the datasheet
        capacity for the device kind, ``limit_source`` labeled
        ``"datasheet"`` (``"unknown"`` when the kind isn't tabled)."""
        snap = super().memory_snapshot(device_index)
        if not snap["bytes_limit"]:
            dev = self.devices()[device_index or 0]
            limit = datasheet_hbm_bytes(dev)
            snap["bytes_limit"] = limit
            snap["limit_source"] = "datasheet" if limit else "unknown"
        return snap

    def memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None):
        s = self.memory_stats(device_index)
        return max(s.get("peak_bytes_in_use", 0), s.get("bytes_in_use", 0))

    def reset_peak_memory_stats(self, device_index=None):
        dev = self.devices()[device_index or 0]
        self._peak_bytes[dev.id] = 0

    def total_memory(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        s = self.memory_stats(device_index)
        return s.get("bytes_limit", 0) - s.get("bytes_in_use", 0)

    # ----------------------------------------------------------------- #
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True

    def supported_dtypes(self):
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    # ----------------------------------------------------------------- #
    def communication_backend_name(self):
        return self._communication_backend_name

    def get_op_builder(self, class_name):
        from deepspeed_tpu.ops.op_builder import get_builder
        return get_builder(class_name)

    def on_accelerator(self, array):
        try:
            shards = getattr(array, "sharding", None)
            if shards is None:
                return False
            platforms = {d.platform for d in shards.device_set}
            return platforms <= {self._name, "axon"}
        except Exception:
            return False


class CPU_Accelerator(TPU_Accelerator):
    """CPU-simulated accelerator for hostless CI (the analog of the
    reference's fake-backend test path, ``tests/unit/common.py:92``) —
    identical surface, ``platform == "cpu"``."""

    def __init__(self):
        super().__init__(platform="cpu")

    def is_bf16_supported(self):
        return True

    def total_memory(self, device_index=None):
        try:
            import psutil
            return psutil.virtual_memory().total
        except Exception:
            return int(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES"))

    def available_memory(self, device_index=None):
        try:
            return int(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_AVPHYS_PAGES"))
        except Exception:
            return 0
