"""TL007 negative fixture: donation handled correctly."""
import jax
import jax.numpy as jnp


def _step(params, cache, tok):
    return tok, cache


step = jax.jit(_step, donate_argnums=(1,))


def rebind_from_result(params, cache, tok):
    out, cache = step(params, cache, tok)    # rebinds at the consuming stmt
    return out, cache.shape                  # reads the NEW buffer


def read_before_donation(params, cache, tok):
    shape = cache.shape                      # read BEFORE the donation
    out, _ = step(params, cache, tok)
    return out, shape


def loop_rebinds(params, cache, toks):
    outs = []
    for tok in toks:
        out, cache = step(params, cache, tok)   # fresh buffer each iter
        outs.append(out)
    return outs


def loop_takes_fresh(params, workspace, toks):
    outs = []
    for tok in toks:
        cache = workspace.pop()
        out, _ = step(params, cache, tok)
        outs.append(out)
    return outs


def different_name(params, cache, other, tok):
    out, _ = step(params, cache, tok)
    return out, other.shape                  # `other` was never donated


def undonated_callee(params, cache, tok):
    plain = jax.jit(_step)
    out, _ = plain(params, cache, tok)       # no donation declared
    return out, cache.shape
