// Async tensor I/O host library: the NVMe tier under optimizer/param offload.
//
// TPU-native equivalent of reference csrc/aio/ (libaio O_DIRECT async
// read/write with worker threads + bounce buffers, py_lib/deepspeed_aio_thread
// .cpp / deepspeed_py_aio_handle.cpp). Same architecture — a handle owns a
// pool of I/O threads; submissions are split into block_size chunks fanned
// across the pool; wait() drains completions — but implemented with portable
// POSIX pread/pwrite on a std::thread pool (io_uring/libaio headers are not
// guaranteed in this image), exposed through a C ABI for ctypes binding
// (reference binds via pybind11, csrc/aio/py_lib/py_ds_aio.cpp).
//
// O_DIRECT is honored when requested and the buffer/offset alignment allows,
// falling back to buffered I/O otherwise (reference fallback behaviour in
// deepspeed_aio_common.cpp).

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

namespace {

struct IoTask {
    std::function<int64_t()> fn;
};

class AioHandle {
  public:
    AioHandle(int num_threads, int64_t block_size, bool o_direct)
        : block_size_(block_size > 0 ? block_size : (1 << 20)),
          o_direct_(o_direct),
          pending_(0),
          errors_(0),
          stop_(false) {
        if (num_threads <= 0) num_threads = 1;
        for (int i = 0; i < num_threads; ++i)
            workers_.emplace_back([this] { worker_loop(); });
    }

    ~AioHandle() {
        {
            std::lock_guard<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto& t : workers_) t.join();
    }

    int num_threads() const { return (int)workers_.size(); }
    int64_t block_size() const { return block_size_; }

    // Split [0, nbytes) into block_size chunks and enqueue one task each.
    // write=true: buf -> file; write=false: file -> buf.
    int submit(const std::string& path, char* buf, int64_t nbytes, bool write,
               bool validate) {
        if (write) {
            // Create/truncate up-front so chunk writers can pwrite anywhere.
            int flags = O_WRONLY | O_CREAT | O_TRUNC;
            int fd = ::open(path.c_str(), flags, 0644);
            if (fd < 0) return -1;
            ::close(fd);
        } else if (validate) {
            struct stat st;
            if (::stat(path.c_str(), &st) != 0) return -1;
            if (st.st_size < nbytes) return -2;
        }
        int64_t n_chunks = (nbytes + block_size_ - 1) / block_size_;
        if (n_chunks == 0) n_chunks = 1;
        {
            std::lock_guard<std::mutex> lk(mu_);
            for (int64_t c = 0; c < n_chunks; ++c) {
                int64_t off = c * block_size_;
                int64_t len = std::min(block_size_, nbytes - off);
                if (len < 0) len = 0;
                pending_++;
                tasks_.push(IoTask{[this, path, buf, off, len, write]() {
                    return do_chunk(path, buf + off, off, len, write);
                }});
            }
        }
        cv_.notify_all();
        return 0;
    }

    // Block until all submitted work is done; returns -(#errors) or 0.
    int wait() {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [this] { return pending_ == 0; });
        int e = errors_.exchange(0);
        return e > 0 ? -e : 0;
    }

  private:
    int64_t do_chunk(const std::string& path, char* buf, int64_t off,
                     int64_t len, bool write) {
        int flags = write ? O_WRONLY : O_RDONLY;
#ifdef O_DIRECT
        bool direct = o_direct_ && (reinterpret_cast<uintptr_t>(buf) % 4096 == 0) &&
                      (off % 4096 == 0) && (len % 4096 == 0);
        if (direct) flags |= O_DIRECT;
#endif
        int fd = ::open(path.c_str(), flags);
#ifdef O_DIRECT
        if (fd < 0 && (flags & O_DIRECT)) {
            flags &= ~O_DIRECT;  // filesystem may refuse O_DIRECT
            fd = ::open(path.c_str(), flags);
        }
#endif
        if (fd < 0) return -1;
        int64_t total = 0;
        while (total < len) {
            ssize_t r = write ? ::pwrite(fd, buf + total, len - total, off + total)
                              : ::pread(fd, buf + total, len - total, off + total);
            if (r <= 0) {
                ::close(fd);
                return -1;
            }
            total += r;
        }
        ::close(fd);
        return total;
    }

    void worker_loop() {
        for (;;) {
            IoTask task;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
                if (stop_ && tasks_.empty()) return;
                task = std::move(tasks_.front());
                tasks_.pop();
            }
            int64_t rc = task.fn();
            if (rc < 0) errors_++;
            {
                std::lock_guard<std::mutex> lk(mu_);
                if (--pending_ == 0) done_cv_.notify_all();
            }
        }
    }

    int64_t block_size_;
    bool o_direct_;
    std::vector<std::thread> workers_;
    std::queue<IoTask> tasks_;
    std::mutex mu_;
    std::condition_variable cv_;
    std::condition_variable done_cv_;
    int64_t pending_;
    std::atomic<int> errors_;
    bool stop_;
};

}  // namespace

extern "C" {

void* aio_handle_create(int num_threads, int64_t block_size, int o_direct) {
    return new AioHandle(num_threads, block_size, o_direct != 0);
}

void aio_handle_destroy(void* h) { delete static_cast<AioHandle*>(h); }

int aio_handle_num_threads(void* h) {
    return static_cast<AioHandle*>(h)->num_threads();
}

int64_t aio_handle_block_size(void* h) {
    return static_cast<AioHandle*>(h)->block_size();
}

// Async submissions (reference async_pwrite/async_pread,
// deepspeed_py_aio_handle.cpp). Pair with aio_wait.
int aio_async_pwrite(void* h, const char* path, const void* buf, int64_t n) {
    return static_cast<AioHandle*>(h)->submit(
        path, const_cast<char*>(static_cast<const char*>(buf)), n, true, false);
}

int aio_async_pread(void* h, const char* path, void* buf, int64_t n) {
    return static_cast<AioHandle*>(h)->submit(path, static_cast<char*>(buf), n,
                                              false, true);
}

int aio_wait(void* h) { return static_cast<AioHandle*>(h)->wait(); }

// Synchronous convenience wrappers (reference sync_pwrite/sync_pread).
int aio_sync_pwrite(void* h, const char* path, const void* buf, int64_t n) {
    AioHandle* handle = static_cast<AioHandle*>(h);
    int rc = handle->submit(
        path, const_cast<char*>(static_cast<const char*>(buf)), n, true, false);
    if (rc != 0) return rc;
    return handle->wait();
}

int aio_sync_pread(void* h, const char* path, void* buf, int64_t n) {
    AioHandle* handle = static_cast<AioHandle*>(h);
    int rc = handle->submit(path, static_cast<char*>(buf), n, false, true);
    if (rc != 0) return rc;
    return handle->wait();
}

}  // extern "C"
