"""Fused LAMB (layerwise adaptive moments) — TPU-native equivalent of
reference ``csrc/lamb/fused_lamb_cuda_kernel.cu`` behind
``deepspeed/ops/lamb/fused_lamb.py:14``.  Per-leaf trust-ratio scaling with
the norm reductions fused into the jitted update."""

from typing import NamedTuple, Any

import jax
import jax.numpy as jnp


class LambState(NamedTuple):
    exp_avg: Any
    exp_avg_sq: Any


class FusedLamb:

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 max_coeff=10.0, min_coeff=0.01, bias_correction=True,
                 master_dtype=jnp.float32):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.bias_correction = bias_correction
        self.master_dtype = master_dtype

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, dtype=self.master_dtype)
        return LambState(exp_avg=jax.tree.map(zeros, params),
                         exp_avg_sq=jax.tree.map(zeros, params))

    def update(self, grads, state, params, lr=None, step=1):
        lr = self.lr if lr is None else lr
        b1, b2 = self.beta1, self.beta2
        step = jnp.asarray(step, dtype=jnp.float32)
        bc1 = 1.0 - b1 ** step if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** step if self.bias_correction else 1.0

        def leaf(p, g, m, v):
            g32 = g.astype(self.master_dtype)
            p32 = p.astype(self.master_dtype)
            m = b1 * m + (1.0 - b1) * g32
            v = b2 * v + (1.0 - b2) * (g32 * g32)
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if self.weight_decay != 0.0:
                upd = upd + self.weight_decay * p32
            w_norm = jnp.linalg.norm(p32.reshape(-1))
            u_norm = jnp.linalg.norm(upd.reshape(-1))
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                1.0)
            return (p32 - lr * trust * upd).astype(p.dtype), m, v

        out = jax.tree.map(leaf, params, grads, state.exp_avg, state.exp_avg_sq)
        is_t = lambda t: isinstance(t, tuple)
        return (jax.tree.map(lambda t: t[0], out, is_leaf=is_t),
                LambState(jax.tree.map(lambda t: t[1], out, is_leaf=is_t),
                          jax.tree.map(lambda t: t[2], out, is_leaf=is_t)))
